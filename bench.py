"""Headline benchmark: simulated gossip rounds/sec on one TPU chip.

Baseline: the reference advances its whole 10-VM cluster exactly ONE gossip
round per wall-clock second (the hardcoded 1 s heartbeat driver, reference:
main.go:27-33) — 1 round/s regardless of hardware.  ``vs_baseline`` is
therefore the sim's rounds/sec directly: how many times faster than real time
the TPU advances the *entire cluster's* protocol state — at N far beyond the
reference's 10-node / ~25-member ceiling (slave/slave.go:210).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The chip is reached through the axon tunnel, which can be held by another
session; the TPU probe runs in a subprocess with a timeout and the bench
falls back to CPU (honestly labelled) rather than hanging the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_TPU = 16_384
N_CPU = 2_048
ROUNDS = 100
CRASH_RATE = 0.01


def probe_tpu(timeout_s: float = 120.0) -> bool:
    """Check the axon TPU is claimable without risking a driver hang."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()[0]"],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


_SWAR_PROBE = """
import dataclasses, jax, jax.numpy as jnp
from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
outs = {}
for ew in ("lanes", "swar"):
    cfg = SimConfig(n=4096, topology="random_arc", fanout=16, arc_align=8,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_cooldown=12, merge_kernel="pallas_rr",
                    merge_block_c=2048, view_dtype="int8", hb_dtype="int8",
                    rr_resident="on", merge_block_r=512, elementwise=ew)
    out = run_rounds(init_state(cfg), cfg, 4, jax.random.PRNGKey(0),
                     crash_rate=0.01)
    outs[ew] = jax.tree.leaves(out)
assert all(bool(jnp.array_equal(a, b))
           for a, b in zip(outs["lanes"], outs["swar"]))
"""


_RR_ROTATE_PROBE = """
import jax, jax.numpy as jnp
from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
outs = {}
for kern in ("xla", "pallas_rr"):
    cfg = SimConfig(n=4096, topology="random_arc", fanout=16, arc_align=8,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_cooldown=12, merge_kernel=kern,
                    merge_block_c=2048, view_dtype="int8", hb_dtype="int8",
                    rr_resident="auto", merge_block_r=512,
                    rr_rotate="auto")
    out = run_rounds(init_state(cfg), cfg, 4, jax.random.PRNGKey(0),
                     crash_rate=0.01)
    outs[kern] = jax.tree.leaves(out)
assert all(bool(jnp.array_equal(a, b))
           for a, b in zip(outs["xla"], outs["pallas_rr"]))
"""


def probe_rr_rotate(timeout_s: float = 600.0) -> bool:
    """Compiled-Mosaic validation of the round-9 row-budget layouts (the
    ring-rotated aligned-arc view build + LANE-compacted flags) before
    the headline uses them: 4 aligned-arc rr rounds at N=4,096, compiled
    rr vs the XLA scan bit-equal ON THE CHIP.  The interpret-mode parity
    suite pins the semantics on CPU; this probe gates the COMPILED form
    (Mosaic lowering of the ring's dynamic W flush and the compact
    flags' lane->sublane reshape) into the headline config, in a
    subprocess so a lowering failure costs the rr_rotate="off" fallback
    (the round-5 full-T/replicated layouts), not the bench run."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _RR_ROTATE_PROBE],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


_RR_SUSPICION_PROBE = """
import jax, jax.numpy as jnp
from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.scenarios import split_halves
from gossipfs_tpu.scenarios.tensor import compile_tensor
from gossipfs_tpu.suspicion.params import SuspicionParams
tsc = compile_tensor(split_halves(4096, start=2, end=8))
outs = {}
for kern in ("xla", "pallas_rr"):
    cfg = SimConfig(n=4096, topology="random_arc", fanout=16, arc_align=8,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_cooldown=12, merge_kernel=kern, t_fail=3,
                    merge_block_c=2048, view_dtype="int8", hb_dtype="int8",
                    rr_resident="auto", merge_block_r=512,
                    elementwise="swar" if kern != "xla" else "lanes",
                    suspicion=SuspicionParams(t_suspect=2))
    out = run_rounds(init_state(cfg), cfg, 10, jax.random.PRNGKey(0),
                     crash_rate=0.01, scenario=tsc, crash_only_events=True)
    outs[kern] = jax.tree.leaves(out)
assert all(bool(jnp.array_equal(a, b))
           for a, b in zip(outs["xla"], outs["pallas_rr"]))
"""


def probe_rr_suspicion(timeout_s: float = 600.0) -> bool:
    """Compiled-Mosaic validation of the round-11 fused fast path before
    an on-chip suspicion anchor trusts it: 10 aligned-arc rr/SWAR rounds
    at N=4,096 with the SWIM lifecycle armed AND a timed partition
    scenario loaded, compiled rr vs the XLA-lanes oracle bit-equal ON
    THE CHIP — every lane, the first_suspect carry and the suspicion
    counters.  The interpret-mode suite (oracle grid + golden fuzz +
    verify_claims fastpath_parity) pins the semantics on CPU; this probe
    gates the COMPILED form (Mosaic lowering of the fused suspect/
    confirm selects, the refute mask, the packed suspicion-count
    reduction and the edge_filter masked gather), in a subprocess so a
    lowering failure costs the staged fallback (--suspicion runs drop to
    elementwise="lanes", then to the XLA oracle config), not the bench
    run."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _RR_SUSPICION_PROBE],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def probe_swar(timeout_s: float = 600.0) -> bool:
    """Compiled-Mosaic validation of the SWAR elementwise path before the
    headline uses it: 4 aligned-arc rr rounds at N=4,096, swar vs lanes
    bit-equal ON THE CHIP.  The interpret-mode parity suite pins the
    semantics on CPU; this probe is what gates the COMPILED form (Mosaic
    lowering of the packed-word ops) into the headline config, in a
    subprocess so a lowering failure costs the lanes fallback, not the
    bench run."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SWAR_PROBE],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", type=str, default=None, metavar="PATH",
                    help="write the measured run's flight-recorder event "
                         "stream (obs/schema.py JSONL) to PATH — decoded "
                         "post-scan from outputs the bench reads anyway, "
                         "so the timed device program is untouched")
    ap.add_argument("--xprof", type=str, default=None, metavar="DIR",
                    help="capture a jax.profiler (xprof) trace of ONE "
                         "extra run after sampling (obs/profile.py); "
                         "open DIR in Perfetto/TensorBoard or reduce "
                         "with utils/profiling.op_breakdown")
    ap.add_argument("--monitor", action="store_true",
                    help="stream the measured run's decoded events "
                         "through the online invariant monitor "
                         "(obs/monitor.py) and stamp its verdict into "
                         "the bench JSON (self-describing, like "
                         "rr_rotate); exits nonzero on any violation — "
                         "the headline number never ships over a run "
                         "that broke a protocol invariant")
    ap.add_argument("--suspicion", action="store_true",
                    help="arm the SWIM lifecycle (t_fail=3, t_suspect=2 "
                         "— the SUSPECT_r08 fast knob) on the headline "
                         "config: the round-11 fused-fast-path anchor.  "
                         "On TPU the fused rr/SWAR form is gated on "
                         "probe_rr_suspicion() (on-chip parity "
                         "subprocess) with staged lanes/XLA fallbacks, "
                         "mirroring the swar and rr_rotate probes")
    args = ap.parse_args(argv)
    use_tpu = os.environ.get("JAX_PLATFORMS", "") == "axon" and probe_tpu()
    if not use_tpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if not use_tpu:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state

    n = N_TPU if use_tpu else N_CPU
    cfg = SimConfig(
        n=n,
        # windowed-arc gossip: each receiver hears from fanout CONSECUTIVE
        # senders at a random base — the same shape as the reference's
        # consecutive ring neighbors (slave/slave.go:517-519), at
        # fanout=16 (= log2(N) + 2) instead of 3.  Protocol-equivalent
        # detection quality vs iid-random edges (bench/curves.py measures
        # both); on-device it turns the F-way row gather into one windowed
        # row-max + a single load.  TILE-ALIGNED arcs (arc_align=8: bases
        # are multiples of 8, fanout two 8-groups) collapse that row-max
        # to a group reduction riding the view build plus one pair-max —
        # the shift-doubling passes disappear (~2 ms/round at N=16k).
        # BASELINE.md keeps the iid-random number alongside for
        # continuity with rounds 1-4.
        topology="random_arc" if use_tpu else "random",
        fanout=16 if use_tpu else SimConfig.log_fanout(n),
        arc_align=8 if use_tpu else 1,
        remove_broadcast=False,
        fresh_cooldown=True,
        t_cooldown=12,
        # the resident-round kernel (ops/merge_pallas.py) runs the whole
        # round — tick, in-kernel gossip-view build, merge, reductions —
        # in ONE pallas call with in-place lane update; CPU keeps the XLA
        # path
        merge_kernel="pallas_rr" if use_tpu else "xla",
        merge_block_r=512 if use_tpu else 128,
        # int8 rebased view (required by the stripe kernel's VMEM budget)
        view_dtype="int8",
        merge_block_c=2_048 if use_tpu else 16_384,
        # resident lanes: the ticked lanes park in VMEM during the view
        # build, so the receiver sweep reads no HBM — the round moves the
        # 4 N^2-byte packed-wire floor (round 5; the round-4 attempt lost
        # to an exposed DMA-latency chain at narrow stripes, fixed by the
        # VSLOTS-deep view-build pipeline)
        rr_resident="on" if use_tpu else "auto",
        # all-int8 state: every matrix lane is 1 B, the ALU-bound round
        # packs 4x denser and the kernel's lane DMAs shrink accordingly.
        # The 126-round int8 rebase window is certified by the 50k-round
        # churn soak (bench/soak_hb16.py, int8 lane)
        hb_dtype="int8",
        # SWAR packed-word elementwise (ops/swar.py): 4 subjects per i32
        # VPU op for the tick/view/merge compare-select chains — the
        # round-6 attack on the ~7 ms/round VPU compute wall the round-5
        # stub bisection quantified.  Gated on probe_swar(): the compiled
        # Mosaic form must prove bit-equality on-chip before the headline
        # trusts it (CPU interpret parity is pinned by the test suite,
        # but this session had no TPU to validate the compiled lowering)
        elementwise="swar" if use_tpu and probe_swar() else "lanes",
        # round-9 row-budget layouts (ring-rotated view build + compacted
        # flags), same probe/fallback pattern: the compiled Mosaic form
        # must prove on-chip bit-equality before the headline trusts it;
        # "off" restores the round-5 layouts (identical bits, more VMEM)
        rr_rotate=("auto" if not use_tpu or probe_rr_rotate() else "off"),
    )
    import dataclasses

    if args.suspicion:
        # round-11 fused fast path: suspicion rides the CONFIGURED
        # kernel (no substitution).  On TPU the compiled fused form must
        # first prove bit-equality on-chip (probe_rr_suspicion); a probe
        # failure drops the anchor to the XLA oracle config — still a
        # valid suspicion-on number, honestly labeled by the emitted
        # merge_kernel field — rather than silently benching an
        # unvalidated lowering
        from gossipfs_tpu.config import fallback_config
        from gossipfs_tpu.suspicion.params import SuspicionParams

        cfg = dataclasses.replace(
            cfg, t_fail=3, suspicion=SuspicionParams(t_suspect=2))
        if use_tpu and not probe_rr_suspicion():
            cfg = fallback_config(cfg)
    key = jax.random.PRNGKey(0)
    state = init_state(cfg)

    # warmup: compile + one short run, with staged fallbacks if the
    # headline-shape compile fails where the small-shape probes passed:
    # first the widened lanes path, then the pre-rotation rr layouts
    # (suspicion runs append the XLA-oracle config as the last resort)
    fallbacks = []
    if cfg.elementwise == "swar":
        fallbacks.append(dict(elementwise="lanes"))
    if cfg.rr_rotate != "off":
        fallbacks.append(dict(elementwise="lanes", rr_rotate="off"))
    if args.suspicion and cfg.merge_kernel != "xla":
        fallbacks.append(dict(elementwise="lanes", merge_kernel="xla"))
    while True:
        try:
            st, mc, pr = run_rounds(state, cfg, ROUNDS, key,
                                    crash_rate=CRASH_RATE)
            jax.block_until_ready(st)
            break
        except Exception:
            if not fallbacks:
                raise
            cfg = dataclasses.replace(cfg, **fallbacks.pop(0))

    # best over a sampling window: the axon chip is pooled and can be
    # time-/bandwidth-shared with other tenants for minutes at a stretch
    # (individual runs measured bimodal ~2x apart with identical programs;
    # one observed contention episode suppressed EVERY attempt of a full
    # 90 s window ~25x).  The minimum over spread-out attempts measures
    # the framework's rate on the chip, not the neighbor's workload;
    # per-call tunnel latency is likewise excluded by taking the best
    # attempt.  The base window is 90 s; if the best attempt still looks
    # contention-suppressed (> 3x the quiet-window rate this build
    # measures, documented in BASELINE.md), sampling extends up to 300 s
    # total to find an uncontended slot.
    samples: list[float] = []  # per-attempt elapsed seconds
    start = time.monotonic()
    deadline = start + 90.0
    hard_deadline = start + 300.0
    while len(samples) < 3 or (time.monotonic() < deadline
                               and len(samples) < 60):
        t0 = time.perf_counter()
        st, mc, pr = run_rounds(state, cfg, ROUNDS, key, crash_rate=CRASH_RATE)
        jax.block_until_ready(st)
        samples.append(time.perf_counter() - t0)
        if (use_tpu and time.monotonic() >= deadline
                and ROUNDS / min(samples) < 30.0 and deadline < hard_deadline):
            deadline = min(deadline + 60.0, hard_deadline)
        if len(samples) < 60 and time.monotonic() < deadline - 3.0:
            time.sleep(3.0)

    # honest headline: the MEDIAN attempt is the canonical value (what a
    # typical window delivers); the best attempt is reported alongside —
    # it remains the right lens for "the framework's rate on the chip"
    # under neighbor contention, but it no longer IS the headline
    # (VERDICT r5 "what's weak" #1)
    rates = sorted(ROUNDS / s for s in samples)
    median = rates[len(rates) // 2] if len(rates) % 2 else (
        (rates[len(rates) // 2 - 1] + rates[len(rates) // 2]) / 2.0
    )
    best = rates[-1]
    platform = jax.devices()[0].platform

    monitor_doc = None
    if args.monitor:
        # decode the LAST sample's outputs (arrays a summarize-style
        # reader transfers anyway — the timed program never saw the
        # flag) and stream them through the invariant monitor
        from gossipfs_tpu.obs.monitor import monitor_verdict
        from gossipfs_tpu.obs.recorder import decode_scan

        evs = decode_scan(pr, mc, n=n, alive=st.alive,
                          suspicion=cfg.suspicion is not None)
        monitor_doc = monitor_verdict(evs, n=n)
        del monitor_doc["violations"]  # verdict + counts stay; evidence
        # rides --trace artifacts, not the one-line headline doc

    trace_events = None
    if args.trace:
        # post-scan decode of the LAST sample's outputs — the recorder
        # consumes arrays summarize-style reads already make; the timed
        # program above never saw the flag
        from gossipfs_tpu.obs.recorder import write_trace

        trace_events = write_trace(
            args.trace, pr, mc, n=n, source="bench", alive=st.alive,
            suspicion=cfg.suspicion is not None,
            elementwise=cfg.elementwise, rr_rotate=cfg.rr_rotate,
            merge_kernel=cfg.merge_kernel, crash_rate=CRASH_RATE,
        )
    if args.xprof:
        # one EXTRA run under the profiler (obs/profile.py) so the trace
        # never contaminates the sampled rates
        from gossipfs_tpu.obs.profile import maybe_xprof

        with maybe_xprof(args.xprof):
            st2, _, _ = run_rounds(state, cfg, ROUNDS, key,
                                   crash_rate=CRASH_RATE)
            jax.block_until_ready(st2)

    print(
        json.dumps(
            {
                "metric": (
                    f"simulated gossip rounds/sec, N={n}, "
                    f"{'fanout=16 tile-aligned arc' if use_tpu else 'fanout=log2(N)'}, "
                    f"1% crash churn ({platform})"
                ),
                "value": round(median, 2),
                "median": round(median, 2),
                "best": round(best, 2),
                "attempts": len(samples),
                "window_s": round(time.monotonic() - start, 1),
                # self-describing artifact: which elementwise path and
                # which rr layouts ACTUALLY ran (post-probe, post-fallback)
                # — a BENCH_r*.json reader no longer has to guess which
                # formulation produced the number
                "elementwise": cfg.elementwise,
                "rr_rotate": cfg.rr_rotate,
                "merge_kernel": cfg.merge_kernel,
                "suspicion": cfg.suspicion is not None,
                "unit": "rounds/s",
                # reference heartbeat loop = 1 round/s of wall clock
                "vs_baseline": round(median, 2),
                **({"monitor": monitor_doc} if monitor_doc else {}),
                **({"trace": args.trace, "trace_events": trace_events}
                   if args.trace else {}),
                **({"xprof": args.xprof} if args.xprof else {}),
            }
        )
    )
    if monitor_doc is not None and not monitor_doc["ok"]:
        # --monitor asserts: a headline over a run that broke a protocol
        # invariant is not a headline (verdict already stamped above)
        sys.exit(1)


if __name__ == "__main__":
    main()
