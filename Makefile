# Repo-level verbs (the native build keeps its own Makefile in native/).
#
#   make lint           gossipfs-lint (tools/lint.py, protocol-spec rules
#                       included) + clang Thread Safety Analysis (make -C
#                       native tsa) + clang-tidy (make -C native
#                       lint-native) as ONE verb; the clang-based legs
#                       skip gracefully where the toolchain is absent
#   make test           tier-1 suite (the ROADMAP verify command's core)
#   make verify-claims  every headline claim end-to-end (accelerator
#                       lanes included — see tools/verify_claims.py)
#   make conformance    adversarial-schedule conformance matrix, every
#                       engine (tools/conformance.py --matrix; exits
#                       nonzero on any verdict flip)

PY ?= python

lint:
	$(PY) tools/lint.py
	$(MAKE) -C native tsa
	$(MAKE) -C native lint-native

test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider

verify-claims:
	$(PY) tools/verify_claims.py

conformance:
	env JAX_PLATFORMS=cpu $(PY) tools/conformance.py --matrix

.PHONY: lint test verify-claims conformance
