"""Co-simulation: the SDFS control plane driven by the simulated detector.

This is the TPU build's equivalent of the reference's whole-node runtime
(main.go:14-35): the failure detector produces membership views, the SDFS
master consumes them through the Update_member seam (reference:
slave/slave.go:478, master/master.go:46-48), detections trigger delayed
re-replication (slave.go:1122-1133), and a vanished master triggers election
(slave.go:452-457).  BASELINE config 5 = this class at N=100k.

Fidelity note: the metadata authority consumes the *master node's own
membership view* (its row of the sim tensor), not ground truth — exactly like
the reference, where placement decisions follow the master's possibly-stale
or false-positive-ridden MemberList.
"""

from __future__ import annotations

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.detector.api import DetectionEvent
from gossipfs_tpu.detector.sim import SimDetector
from gossipfs_tpu.sdfs.cluster import SDFSCluster
from gossipfs_tpu.sdfs.types import RECOVERY_DELAY, STRIPE_K, STRIPE_M
from gossipfs_tpu.utils.eventlog import EventLog


def select_observer(
    view_live: list[int], reachable: set[int], master: int
) -> int | None:
    """Whose membership view the metadata authority consumes.

    Normally the master's own row (slave.go:478).  If the master process is
    down (its RPC port refuses — observable immediately, unlike gossip
    detection), consumers fall through to the election candidate: the lowest
    node of the previous view that answers RPC; failing that, any reachable
    node.  Shared by the interactive CoSim and the chunked bench co-sim so
    config-5 observer semantics can't drift between them.
    """
    if master in reachable:
        return master
    candidates = [x for x in view_live if x in reachable]
    if candidates:
        return min(candidates)
    return min(reachable) if reachable else None


class CoSim:
    """Gossip detector + SDFS cluster advancing in lockstep rounds."""

    def __init__(
        self,
        config: SimConfig,
        seed: int = 0,
        log: EventLog | None = None,
        election: str = "local",
        detector=None,
        repair_budget: int | None = None,
        redundancy: str = "replica",
        stripe_k: int = STRIPE_K,
        stripe_m: int = STRIPE_M,
        rack_size: int | None = None,
    ):
        """``election``: "local" computes election outcomes centrally inside
        ``update_membership`` (the in-process fast path); "rpc" defers them —
        the cluster only flags ``election_pending`` and the gRPC shim drives
        the real per-node Vote / AssignNewMaster protocol
        (``ShimServicer.run_pending_election``), matching the reference's
        distributed revote (slave.go:930-1051).

        ``detector``: any FailureDetector (default: a fresh SimDetector).
        The capacity-frontier interactive CLI passes a
        ``detector.sim.PackedDetector`` — same seam, rr-kernel state.

        ``repair_budget``: per-pass cap on executed re-replications (the
        traffic plane's repair-storm scheduler — ``SDFSCluster.
        fail_recover(budget=...)``); a pass that defers work schedules
        another pass NEXT round, so a mass failure drains at budget/round
        instead of serializing one giant pass.  None = unbounded (the
        reference's behavior).

        ``redundancy``: "replica" (4 full copies, the reference) or
        "stripe" — the erasure plane (``gossipfs_tpu/erasure/``): puts
        land k+m rack-balanced Reed-Solomon fragments, repairs re-encode
        at ~1/k the bytes.  ``rack_size`` groups nodes into contiguous
        racks, the stripe placement's correlated-failure domain."""
        if election not in ("local", "rpc"):
            raise ValueError(f"unknown election mode: {election!r}")
        self.config = config
        self.election = election
        self.detector = detector or SimDetector(config, seed=seed)
        self.cluster = SDFSCluster(config.n, seed=seed,
                                   introducer=config.introducer,
                                   redundancy=redundancy, stripe_k=stripe_k,
                                   stripe_m=stripe_m, rack_size=rack_size)
        self.log = log or EventLog()
        self._recover_at: list[int] = []  # rounds at which to run fail_recover
        self.events: list[DetectionEvent] = []
        if repair_budget is not None and repair_budget <= 0:
            raise ValueError(
                "repair_budget must be positive (None = unbounded)")
        self.repair_budget = repair_budget
        # traffic-plane vitals (obs.schema.VITALS_FIELDS tail): client ops
        # issued/acked through this co-sim plus the repair scheduler's
        # cumulative/backlog counters — the CLI `traffic status` verb and
        # the shim Vitals RPC render these
        self.ops_issued = 0
        self.ops_acked = 0
        self.repairs_done = 0
        # files currently reported lost (no replica in the view) — a heal
        # that brings replicas back clears the entry so a re-loss re-emits
        self._lost_reported: set[str] = set()
        # armed fault scenario (scenarios/): the detector gets the gossip
        # transport rules; the control plane additionally confines
        # RPC/scp-level reachability to the master's side of any active
        # partition (see _reachable)
        self.scenario = None
        self._scn_round0 = 0
        # flight recorder (obs/): forwarded to the detector's protocol
        # seams; the control plane adds its own events (election,
        # replica_put/repair) so one stream carries the WHOLE
        # crash -> ... -> repair timeline
        self._recorder = None

    # -- observability (obs/) ----------------------------------------------
    def attach_recorder(self, recorder) -> None:
        """Arm an obs.FlightRecorder on both planes: the detector's
        lifecycle events plus the SDFS control plane's."""
        det = self.detector
        if hasattr(det, "attach_recorder"):
            det.attach_recorder(recorder)
        self._recorder = recorder

    def _rec(self, kind: str, subject: int = -1, observer: int = -1,
             **detail) -> None:
        if self._recorder is None:
            return
        from gossipfs_tpu.obs.schema import Event

        self._recorder.emit(Event(round=self.round, observer=observer,
                                  subject=subject, kind=kind,
                                  detail=detail))

    def vitals(self) -> dict:
        """The uniform counter set (obs.schema.VITALS_FIELDS) for the
        CLI ``metrics`` verb and the shim's ``Vitals`` RPC.  The sim
        knows ground truth, so every field is live; suspicion counters
        appear only when the lifecycle is armed (consumers render the
        absence as n/a)."""
        doc = {
            "engine": "sim",
            "round": self.round,
            "n_alive": len(self.detector.alive_nodes()),
            "detections": len(self.events),
            "false_positives": sum(
                1 for e in self.events if e.false_positive),
        }
        sus = self.suspicion_status()
        if sus is not None:
            doc.update({k: sus[k] for k in (
                "suspects_now", "suspects_entered", "refutations",
                "confirms", "fp_suppressed") if k in sus})
        doc.update(self.traffic_status())
        return doc

    def load_scenario(self, scenario) -> None:
        """Arm a scenarios.FaultScenario on BOTH planes: gossip transport
        (detector.load_scenario — every engine behind the FailureDetector
        seam supports it) and the SDFS control plane's reachability.
        Rule windows count from the current round."""
        det = self.detector
        if not hasattr(det, "load_scenario"):
            raise NotImplementedError(
                f"{type(det).__name__} has no scenario support"
            )
        det.load_scenario(scenario)
        self.scenario = scenario
        self._scn_round0 = self.round

    def clear_scenario(self) -> None:
        det = self.detector
        if hasattr(det, "clear_scenario"):
            det.clear_scenario()
        self.scenario = None

    def scenario_status(self) -> dict | None:
        if self.scenario is None:
            return None
        # one status-document producer: the detector's (load_scenario
        # guarantees it exists — a second hand-built copy here would
        # drift from the engine surfaces)
        return self.detector.scenario_status()

    def suspicion_status(self) -> dict | None:
        """Suspicion vitals (suspicion/) — the detector's document, same
        one-producer rule as scenario_status; None when the detector has
        no suspicion support or none is armed."""
        det = self.detector
        if hasattr(det, "suspicion_status"):
            return det.suspicion_status()
        return None

    def _reachable(self) -> set[int]:
        """Transport-level reachability from the control plane's seat.

        The metadata authority lives with the master, so under an active
        partition only the master's side answers its RPC/scp — replica
        pushes to the far side fail, which is exactly what starves a
        minority-side write of its quorum (reference: an scp to an
        unreachable VM fails immediately).  Without a scenario this is
        ground-truth liveness, as before.
        """
        alive = set(self.detector.alive_nodes())
        if self.scenario is None:
            return alive
        rel = self.round - self._scn_round0
        # outage-group members and dark-phase flappers answer no RPC/scp
        # at all (round-13 gray-failure rules)
        alive -= self.scenario.unreachable_at(rel)
        pid = self.scenario.pid_at(rel)
        if pid is None:
            return alive
        side = pid[self.cluster.master_node]
        return {x for x in alive if pid[x] == side}

    @property
    def round(self) -> int:
        det = self.detector
        return det.round if hasattr(det, "round") else int(det.state.round)

    def _observer(self) -> int | None:
        """See ``select_observer`` — the *view itself* stays pure gossip data:
        dead-but-undetected members remain in it, so placement/election react
        at detection time, not at crash time."""
        # "answers RPC" — partition-confined under an armed scenario
        return select_observer(
            self.cluster.live, self._reachable(), self.cluster.master_node
        )

    def tick(self, rounds: int = 1) -> None:
        """Advance the detector and let the control plane react per round."""
        for _ in range(rounds):
            self.detector.advance(1)
            now = self.round
            new_events = self.detector.drain_events()
            self.events.extend(new_events)
            for ev in new_events:
                # logged by the DETECTING machine (slave.go:474): the entry
                # lands in the observer's own Machine.log view
                self.log.write(
                    f"Failure Detected of node {ev.subject} by {ev.observer}",
                    round=now,
                    kind="failure_detected",
                    false_positive=ev.false_positive,
                    node=ev.observer,
                )
                # detection schedules recovery 8 heartbeats out (slave.go:1123)
                self._recover_at.append(now + RECOVERY_DELAY)
            observer = self._observer()
            if observer is not None:
                old_master = self.cluster.master_node
                self.cluster.update_membership(
                    self.detector.membership(observer),
                    reachable=sorted(self._reachable()),
                    now=now,
                    elect=self.election == "local",
                )
                if self.cluster.master_node != old_master:
                    # the reference logs the vote outcome (revote_master /
                    # Receive_vote, slave.go:930-984)
                    self.log.write(
                        f"Elected new master {self.cluster.master_node} "
                        f"(was {old_master})",
                        round=now,
                        kind="election",
                        node=self.cluster.master_node,  # the winner announces
                    )
                    self._rec("election", subject=self.cluster.master_node,
                              was=old_master)
            due = [r for r in self._recover_at if r <= now]
            if due:
                self._recover_at = [r for r in self._recover_at if r > now]
                plans = self.cluster.fail_recover(budget=self.repair_budget)
                self.repairs_done += len(plans)
                for plan in plans:
                    if self.cluster.redundancy == "stripe":
                        # a stripe repair has k sources, not one: the
                        # master coordinates, so it owns the log line
                        self.log.write(
                            f"Re-encoded {plan.file} v{plan.version} "
                            f"slots {list(plan.slots)} to "
                            f"{list(plan.new_nodes)}",
                            round=now,
                            kind="re_replicate",
                            node=self.cluster.master_node,
                        )
                        self._rec("stripe_repair",
                                  observer=self.cluster.master_node,
                                  file=plan.file, version=plan.version,
                                  slots=list(plan.slots),
                                  targets=list(plan.new_nodes))
                        continue
                    # logged by the SOURCE machine doing the Re_put
                    # (slave.go:1174)
                    self.log.write(
                        f"Re-replicated {plan.file} v{plan.version} "
                        f"from {plan.source} to {list(plan.new_nodes)}",
                        round=now,
                        kind="re_replicate",
                        node=plan.source,
                    )
                    self._rec("replica_repair", observer=plan.source,
                              file=plan.file, version=plan.version,
                              targets=list(plan.new_nodes))
                if self.cluster.last_repair_pending:
                    # budget deferred planned repairs: drain next round
                    # (the repair-storm scheduler's retry cadence)
                    self._recover_at.append(now + 1)
                # files with no replica left in the view: observable loss
                # evidence (recovers — and re-arms — across heals)
                lost_now = set(self.cluster.lost_files())
                lost_kind = ("stripe_lost"
                             if self.cluster.redundancy == "stripe"
                             else "replica_lost")
                for name in sorted(lost_now - self._lost_reported):
                    self.log.write(
                        f"All replicas of {name} lost from the view"
                        if lost_kind == "replica_lost" else
                        f"Stripe {name} below k live fragments in the view",
                        round=now, kind="lost",
                        node=self.cluster.master_node,
                    )
                    self._rec(lost_kind,
                              observer=self.cluster.master_node, file=name)
                self._lost_reported = lost_now

    # -- client verbs delegated with sim time ------------------------------
    def _put_event(self, name: str) -> None:
        """One acked put's schema event: the committed version plus the
        replica nodes that actually acked (reachable at commit time) —
        what the durability audit (traffic/audit.py) replays.  Stripe
        mode reports the slot-aligned fragment holders instead (-1 where
        the fragment did not land), plus the (k, m) shape the replay
        needs for its k-of-(k+m) loss line."""
        if self.cluster.redundancy == "stripe":
            sinfo = self.cluster.master.stripes.get(name)
            if sinfo is None:
                return
            fragments = [
                nd if nd >= 0 and nd in self.cluster.reachable else -1
                for nd in sinfo.fragment_nodes
            ]
            self._rec("stripe_put", observer=self.cluster.master_node,
                      file=name, version=sinfo.version, fragments=fragments,
                      k=self.cluster.stripe_k, m=self.cluster.stripe_m)
            return
        info = self.cluster.master.files.get(name)
        if info is None:
            return
        acked = [nd for nd in info.node_list if nd in self.cluster.reachable]
        self._rec("replica_put", observer=self.cluster.master_node,
                  file=name, version=info.version, replicas=acked)

    def put(self, name: str, data: bytes, confirm=None) -> bool:
        self.ops_issued += 1
        ok = self.cluster.put(name, data, now=self.round, confirm=confirm)
        # logged at the master handling Get_put_info (server.go:74-121)
        self.log.write(
            f"put {name} -> {'ok' if ok else 'rejected'}",
            round=self.round,
            kind="put",
            node=self.cluster.master_node,
        )
        if ok:
            self.ops_acked += 1
            self._put_event(name)
        return ok

    def put_batch(self, items, confirm=None) -> dict[str, bool]:
        """Batched write verb for the open-loop traffic plane: one
        vectorized placement draw for the round's new files
        (``SDFSCluster.put_batch``), per-file acks/events as usual."""
        self.ops_issued += len(items)
        results = self.cluster.put_batch(items, now=self.round,
                                         confirm=confirm)
        for name, ok in results.items():
            self.log.write(
                f"put {name} -> {'ok' if ok else 'rejected'}",
                round=self.round,
                kind="put",
                node=self.cluster.master_node,
            )
            if ok:
                self.ops_acked += 1
                self._put_event(name)
        return results

    def get(self, name: str) -> bytes | None:
        self.ops_issued += 1
        blob = self.cluster.get(name)
        if blob is not None:
            self.ops_acked += 1
        return blob

    def delete(self, name: str) -> bool:
        self.ops_issued += 1
        ok = self.cluster.delete(name)
        if ok:
            self.ops_acked += 1
            self.log.write(
                f"delete {name}", round=self.round, kind="delete",
                node=self.cluster.master_node,
            )
            self._rec("replica_delete", observer=self.cluster.master_node,
                      file=name)
            self._lost_reported.discard(name)
        return ok

    # -- traffic vitals (obs/schema.py VITALS_FIELDS tail) ------------------
    def traffic_status(self) -> dict:
        """The traffic-plane counter document: ops issued/acked through
        this co-sim, repairs executed, and the CURRENT repair backlog
        (budget-deferred plans from the last recovery pass plus files
        still under-replicated right now — computed on demand; cheap at
        interactive scale).  Stripe mode adds the erasure vitals
        (``stripes_degraded`` / ``fragments_lost``); replica mode leaves
        them ABSENT so consumers render n/a, never a fabricated 0."""
        cl = self.cluster
        if cl.redundancy == "stripe":
            pending = len(cl.master.plan_stripe_repairs(
                cl.live, reachable=cl.reachable
            ))
        else:
            pending = len(cl.master.plan_repairs(
                cl.live, reachable=cl.reachable
            ))
        doc = {
            "ops_issued": self.ops_issued,
            "ops_acked": self.ops_acked,
            "repairs_pending": pending,
            "repairs_done": self.repairs_done,
        }
        if cl.redundancy == "stripe":
            from gossipfs_tpu.sdfs.quorum import stripe_read_quorum

            live_set = set(cl.live)
            width = cl.stripe_k + cl.stripe_m
            rq = stripe_read_quorum(cl.stripe_k, cl.stripe_m)
            degraded = 0
            frag_lost = 0
            for info in cl.master.stripes.values():
                w = sum(1 for nd in info.fragment_nodes if nd in live_set)
                if w < width:
                    frag_lost += width - w
                    if w >= rq:
                        degraded += 1
            doc["stripes_degraded"] = degraded
            doc["fragments_lost"] = frag_lost
        mon = getattr(self._recorder, "monitor", None)
        if mon is not None:
            # online health plane (obs/monitor.py): the live invariant
            # verdict rides the traffic/metrics surfaces.  Without an
            # attached monitor the field is ABSENT — consumers render
            # n/a, never a fabricated clean 0 (the round-8 rule)
            doc["invariant_violations"] = len(mon.violations)
        return doc
