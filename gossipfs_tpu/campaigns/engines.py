"""Socket-engine campaign runners: the SAME committed case files, real
transports.

Round 13's campaigns ran only the tensor engine; the UDP and deploy
lanes had never been driven through a fault family at all, even though
``ScenarioRuntime`` implements every primitive per message.  This module
closes that gap: :func:`run_case_engine` takes the SAME
``gossipfs-campaign-case/v1`` files tier-1 replays on the tensor engine
and drives them through

  * the asyncio UDP cluster (``detector/udp.py`` — real datagrams on
    localhost, the scenario armed at the ``UdpNode._send`` hook, crashes
    as socket teardown), recording a ``gossipfs-obs/v1`` stream whose
    ``round_tick`` rows carry the in-process ground truth
    (``UdpCluster.run(emit_round_ticks=True)``), or
  * the per-process deployment (``deploy/launcher.py`` — one OS process
    per node, the rule table pushed over the control plane with the
    round-14 bounded-backoff RPC discipline, crashes as ``kill -9``,
    events tailed from the per-node ``node<i>.log`` schema streams), or
  * the native C++ epoll engine (``native/engine.cc`` via
    ``gossipfs_tpu/native.py`` — the sanitizer-certified runtime; the
    scenario compiled to the in-engine send-gate table, suspicion + the
    Lifeguard stretch running inside the engine, events drained over
    ``gfs_obs_drain`` and rendered through the same ``FlightRecorder``
    — the COHORT-EXACT lane: committed n=256+ cases run at their
    committed n, which the asyncio loop cannot sustain),

then feeds the recorded stream through ``StreamMonitor.feed_jsonl`` —
the SAME file-attachment seam, the SAME invariant table, the SAME
``MonitorParams`` the case file carries — and requires the verdict to
AGREE with the tensor replay's on every invariant both engines can
check.  A campaign case that reproduces its storm (or its absorption)
over real sockets is the strongest evidence the finding is protocol
physics, not a tensor-engine artifact — and a deploy campaign that
finishes at all under a correlated outage is itself evidence the
control plane degrades gracefully (the round-14 backoff hardening).

Real-socket runs are wall-clock and scheduling-jittered, so they are
NOT bit-reproducible like tensor replays; what must reproduce is the
VERDICT.  Committed cases are campaign-generated (family metadata in
the case doc), so :func:`scale_case` can regenerate the same family
point at a smaller n — the deploy lane's process budget is ~8 nodes,
not 256 — with the severity knobs (duty cycles, loss rates, outage
size) preserved and the fault cohorts re-picked around the scaled
tracked victims.
"""

from __future__ import annotations

import asyncio
import copy
import json
import pathlib
import tempfile
import time

from gossipfs_tpu.campaigns.driver import (
    campaign_rounds,
    case_verdict_ok,
    load_case,
    make_scenario,
    run_case_doc,
)
from gossipfs_tpu.obs.monitor import MonitorParams, StreamMonitor
from gossipfs_tpu.scenarios.schedule import FaultScenario

ENGINES = ("tensor", "udp", "deploy", "native")


def scale_case(doc: dict, n: int) -> dict:
    """Regenerate a campaign case at a different cohort size.

    Only campaign-GENERATED cases scale (they carry ``family`` /
    ``axis`` / ``axis_value`` metadata plus the fixed knobs in the
    scenario name): the scenario is re-made by the same
    ``make_scenario`` rules at the new n — fractional cohorts (1/frac)
    scale naturally, absolute knobs (outage size, flap duty cycle) are
    preserved, and the fault nodes re-avoid the scaled tracked victims.
    Hand-written cases without the metadata are rejected rather than
    guessed at.
    """
    from gossipfs_tpu.bench.run import tracked_victims

    if "family" not in doc or "axis" not in doc:
        raise ValueError(
            "case carries no campaign family metadata — only "
            "campaign-generated cases can be scaled; run it at its "
            "committed n instead")
    out = copy.deepcopy(doc)
    c = out["config"]
    old_sc = FaultScenario.from_json(json.dumps(doc["scenario"]))
    # fault_rounds: reconstruct the window length from the committed
    # scenario (make_scenario's windows are [start, start + rounds))
    rules = (*old_sc.flapping, *old_sc.link_faults, *old_sc.slow_nodes,
             *old_sc.partitions, *old_sc.outages)
    fault_rounds = max(r.end - r.start for r in rules)
    knobs = {}
    for kv in old_sc.name.split("-", 1)[1].split(","):
        k, _, v = kv.partition("=")
        knobs[k] = int(v)
    # make_scenario excludes `start` from the name; recover it from the
    # committed windows so a non-default start survives the rescale (the
    # probe/heal phase alignment the surface shows is crash_at-coupled)
    knobs["start"] = min(r.start for r in rules)
    avoid = set(tracked_victims(n, int(c["track"]))) | {0}
    sc = make_scenario(doc["family"], n, fault_rounds, avoid=avoid,
                       **knobs)
    out["scenario"] = json.loads(sc.to_json())
    n_old = int(c["n"])
    c["n"] = n
    if float(c.get("lh_frac", 0.0)) > 0 and int(c.get("lh_multiplier", 0)):
        # the Lifeguard degradation threshold is an ABSOLUTE suspect
        # count in disguise (frac x listed ~ frac x n): a case tuned to
        # sit between "4 simultaneous tracked probes" and "an 8-node
        # rack" must keep those COUNTS when the cohort shrinks, so the
        # fraction scales by n_old/n — 1/64 at n=256 (threshold ~4)
        # becomes 1/16 at n=64 (threshold ~4), not 1/64 (threshold ~1,
        # which would stretch on every routine probe)
        c["lh_frac"] = min(float(c["lh_frac"]) * n_old / n, 0.5)
    out["scaled_from"] = n_old
    return out


def _case_plan(doc: dict):
    """The run plan every socket engine derives from a case doc —
    ``(n, scenario, crash_at, rounds, victims)`` with ONE owner, so a
    change to the bound/rounds derivation cannot silently
    desynchronize the engines' run lengths (``campaign_rounds``'
    single-owner rationale, extended to the whole scaffold)."""
    from gossipfs_tpu.bench.run import tracked_victims

    c = doc["config"]
    n = int(c["n"])
    sc = FaultScenario.from_json(json.dumps(doc["scenario"]))
    crash_at = int(c.get("crash_at", 10))
    bound = doc["monitor"].get("reconverge_bound") or (int(c["t_fail"]) + 6)
    rounds = campaign_rounds(sc.horizon, crash_at, bound)
    victims = tracked_victims(n, int(c["track"]))
    return n, sc, crash_at, rounds, victims


def _wire_knobs(c: dict) -> dict:
    """Optional dissemination knobs a case config may carry (round 20).

    ``delta=1`` switches the engine's membership refresh to the
    delta-piggyback profile (``protocol_spec.DELTA_GOSSIP``) with the
    case's ``delta_entries`` / ``anti_entropy_every``; absent, the
    engines keep the committed full-list wire format, so every existing
    case file runs bit-identically.  Both socket engines accept the same
    keys — one derivation, like ``_case_plan``.
    """
    if not int(c.get("delta", 0)):
        return {}
    return {
        "delta": True,
        "delta_entries": int(c.get("delta_entries", 16)),
        "anti_entropy_every": int(c.get("anti_entropy_every", 4)),
    }


def _suspicion_params(c: dict):
    if int(c.get("t_suspect", 0)) <= 0:
        return None
    from gossipfs_tpu.suspicion import SuspicionParams

    return SuspicionParams(
        t_suspect=int(c["t_suspect"]),
        lh_multiplier=int(c.get("lh_multiplier", 0)),
        lh_frac=float(c.get("lh_frac", 0.25)),
    )


def _monitor_row(trace_path, params: MonitorParams, n: int,
                 crash_rounds: dict[int, int] | None = None) -> dict:
    """Feed one written stream through a fresh monitor (the
    ``feed_jsonl`` file-attachment seam — deliberately NOT the inline
    recorder: the committed artifact is re-derivable from the file
    alone) and shape the verdict like a campaign ledger row."""
    mon = StreamMonitor(params=params, n=n)
    if crash_rounds:
        mon.observe_header({"n": n, "crash_rounds": {
            str(k): v for k, v in crash_rounds.items()}})
    mon.feed_jsonl(trace_path)
    mon.finish()
    s = mon.summary()
    return {
        "verdict": "violated" if mon.violations else "pass",
        "monitor": mon.verdict(),
        # round_tick rows seen: zero means the stream cannot evaluate
        # the rolling-FPR invariant at all (deploy node logs carry no
        # ground-truth ticks) — verdict_agreement drops fpr_storm then
        "observed_round_ticks": s["rounds"],
        "estimators": {
            "false_positives": s["false_positives"],
            "false_positive_rate": s["false_positive_rate"],
            "worst_window_fpr": s["worst_window_fpr"],
            "ttd_first_median": s["ttd_first_median"],
            "detected": s["detected"],
            "tracked_crashes": s["tracked_crashes"],
        },
        "violations": s["violations"],
    }


def verdict_agreement(tensor_row: dict, engine_row: dict) -> dict:
    """Per-invariant agreement over the invariants BOTH engines checked.

    The UDP lane checks the full table (its ``round_tick`` rows carry
    ground truth); the deploy lane has no ground-truth FPR, so its
    stream never grows ``fpr_storm`` windows — comparing only the
    intersection keeps the agreement requirement honest instead of
    vacuously failing on unknowables.
    """
    a = tensor_row["monitor"]
    b = engine_row["monitor"]
    compared = sorted(set(a["invariants_checked"])
                      & set(b["invariants_checked"]))
    if engine_row.get("observed_round_ticks") == 0:
        # the invariant table lists fpr_storm whenever a threshold is
        # set, but a stream with no round_tick rows never evaluated it
        compared = [inv for inv in compared if inv != "fpr_storm"]
    mismatch = [
        inv for inv in compared
        if bool(a["by_invariant"].get(inv)) != bool(
            b["by_invariant"].get(inv))
    ]
    return {"match": not mismatch, "compared": compared,
            "mismatched": mismatch}


# ---------------------------------------------------------------------------
# UDP engine
# ---------------------------------------------------------------------------


def _free_udp_base(n: int) -> int:
    """A UDP port window with room for ``n`` sockets — the launcher's
    bind-and-hold probe (ONE owner), UDP-only: two concurrent campaign
    runners (a tier-1 smoke racing a committed-artifact run) must not
    land on the same window and cross-talk their clusters (observed: a
    fixed base_port made two overlapping runs merge memberships)."""
    from gossipfs_tpu.deploy.launcher import _free_port_base

    return _free_port_base(n, tcp=False)


def _wire_delta(v0: dict, v1: dict, rounds: int) -> dict:
    """Measured-window wire accounting (the delta-gossip A/B surface):
    payload bytes and frame counts actually handed to the transport
    between two vitals snapshots, normalized per round."""
    bytes_sent = v1["bytes_sent"] - v0["bytes_sent"]
    return {
        "rounds": rounds,
        "bytes_sent": bytes_sent,
        "bytes_per_round": bytes_sent / max(rounds, 1),
        "frames_full": v1["frames_full"] - v0["frames_full"],
        "frames_delta": v1["frames_delta"] - v0["frames_delta"],
    }


async def _udp_case(doc: dict, trace: str, period: float,
                    warmup_timeout: float):
    """Drive one case on an in-process UdpCluster; returns the crash
    schedule ({victim: round}) for the monitor's TTD accounting plus
    the measured window's wire accounting."""
    from gossipfs_tpu.detector.udp import UdpCluster
    from gossipfs_tpu.obs.recorder import FlightRecorder

    c = doc["config"]
    n, sc, crash_at, rounds, victims = _case_plan(doc)

    from gossipfs_tpu.config import SimConfig

    cluster = UdpCluster(
        n, base_port=_free_udp_base(n), period=period,
        t_fail=int(c["t_fail"]),
        t_cooldown=max(12, int(c["t_fail"]) + 4), fresh_cooldown=True,
        suspicion=_suspicion_params(c),
        # the campaign protocol profile — the same knobs
        # campaigns.campaign_config sets on the tensor engine (random
        # log-fanout push, gossip-only removal): verdict agreement must
        # compare PROTOCOLS, not the reference ring's O(N)-tick event
        # propagation (see UdpCluster's push notes)
        push="random", fanout=int(c.get("fanout", SimConfig.log_fanout(n))),
        remove_broadcast=False,
        **_wire_knobs(c),
    )
    await cluster.start_all()
    try:
        # fully-joined steady-state start, like the tensor campaign's
        # init_state (the O(N^2) protocol boot takes minutes at
        # campaign cohort sizes), then a short warmup OFF the
        # observational round clock (nodes tick on their own heartbeat
        # tasks; cluster._round stays 0, so the recorded stream's
        # rounds are scenario-relative like the tensor trace's) until
        # every counter is past the hb<=1 detection grace
        cluster.seed_full_membership()
        deadline = time.monotonic() + warmup_timeout
        while time.monotonic() < deadline:
            full = all(
                len(node.members) == n
                and min(m.hb for m in node.members.values()) > 1
                for node in cluster.nodes
            )
            if full:
                break
            await asyncio.sleep(period)
        else:
            raise TimeoutError(
                f"udp cluster (n={n}) did not converge within "
                f"{warmup_timeout}s of warmup")

        rec = FlightRecorder(trace, source="udp-campaign", n=n,
                             case=doc.get("family", "case"),
                             crash_rounds={str(v): crash_at
                                           for v in victims})
        cluster.attach_recorder(rec)
        cluster.load_scenario(sc)
        v0 = cluster.vitals()
        for r in range(rounds):
            if r == crash_at:
                for v in victims:
                    cluster.crash(v)
            await cluster.run(1, emit_round_ticks=True)
        wire = _wire_delta(v0, cluster.vitals(), rounds)
        rec.close()
        return {v: crash_at for v in victims}, wire
    finally:
        cluster.stop_all()


def udp_period(n: int) -> float:
    """The asyncio lane's default heartbeat period: one python event
    loop parses n full-list datagram fan-outs per period, and the
    engine is documented load-sensitive (UDPCAMPAIGN_r14) — n=64 runs
    all ride 0.1 s in the committed evidence while the n=24 tier-1
    smoke keeps 0.05 s.  ~1.5 ms of loop budget per node, floored at
    the small-lane 0.05 s."""
    return max(0.05, n / 640.0)


def run_case_udp(doc: dict, *, period: float | None = None,
                 trace: str | None = None,
                 warmup_timeout: float = 60.0) -> dict:
    """One case on the asyncio UDP engine; returns the ledger-row shape
    plus the written trace path (re-feed it through
    ``StreamMonitor.feed_jsonl`` to re-derive the verdict)."""
    if period is None:
        period = udp_period(int(doc["config"]["n"]))
    if trace is None:
        trace = tempfile.mktemp(prefix="udp_case_", suffix=".jsonl")
    crash_rounds, wire = asyncio.run(
        _udp_case(doc, trace, period, warmup_timeout))
    row = _monitor_row(trace, MonitorParams.from_dict(doc["monitor"]),
                       int(doc["config"]["n"]),
                       crash_rounds=crash_rounds)
    row.update(engine="udp", trace=str(trace), period=period, wire=wire)
    return row


# ---------------------------------------------------------------------------
# native engine (C++ epoll — campaigns/engines' third real transport)
# ---------------------------------------------------------------------------


def native_period(n: int) -> float:
    """The native lane's default heartbeat period: the engine is one
    epoll thread doing all N nodes' protocol work, and detection clocks
    are WALL time — a period the tick+merge pass can't keep costs
    false positives by PHYSICS (rounds lag, entries look stale), not
    protocol.  ~2 ms of budget per node: at n=256 the full-list merge
    pass costs ~60-100 ms/round on the 1-core box (measured via the
    round_tick ``tick_ms`` samples — n/1024 s was observably too tight:
    warmup churned with view-shrink storms), so n/512 leaves the round
    ~5x of headroom.  The floor is 0.1 s — 2x the asyncio lane's small-n
    floor on purpose: the native engine ticks EVERY node at the same
    instant (one loop), so entry ages are quantized to whole periods
    and the t_fail staleness edge is one scheduling hiccup wide, where
    the asyncio engine's per-node tasks stagger their phases across
    the period."""
    return max(0.1, n / 512.0)


def run_case_native(doc: dict, *, period: float | None = None,
                    trace: str | None = None,
                    warmup_timeout: float | None = None) -> dict:
    """One case on the native C++ epoll engine (real localhost
    datagrams, one OS thread) — the cohort-exact lane: the asyncio
    engine honestly melts past n~64 (UDPCAMPAIGN_r14), so committed
    n=256+ cases run here at their COMMITTED n instead of rescaled.

    Same contract as :func:`run_case_udp`: campaign protocol profile
    (random log-fanout push, gossip-only removal), seeded steady-state
    start, the scenario armed as the engine's send-gate table, the
    recorded ``gossipfs-obs/v1`` stream fed back through
    ``StreamMonitor.feed_jsonl``.  The native round_ticks carry
    in-process ground truth, so the full invariant table (fpr_storm
    included) evaluates; ``tick_ms`` rides every round_tick and the
    returned row carries the per-round latency histogram (the 'did the
    engine keep its period' evidence a real-time verdict rests on).
    """
    import time as _time

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.native import NativeUdpDetector, latency_histogram
    from gossipfs_tpu.obs.recorder import FlightRecorder, load_stream

    c = doc["config"]
    n, sc, crash_at, rounds, victims = _case_plan(doc)
    if period is None:
        period = native_period(n)
    if warmup_timeout is None:
        # scales with n for the same reason as run_ab_cell: the seeded
        # cold start's one-time staleness churn grows with cohort size
        warmup_timeout = max(120.0, 0.75 * n)
    if trace is None:
        trace = tempfile.mktemp(prefix="native_case_", suffix=".jsonl")

    det = NativeUdpDetector(
        n, base_port=_free_udp_base(n), period=period,
        t_fail=int(c["t_fail"]),
        t_cooldown=max(12, int(c["t_fail"]) + 4), fresh_cooldown=True,
        push="random", fanout=int(c.get("fanout", SimConfig.log_fanout(n))),
        remove_broadcast=False, suspicion=_suspicion_params(c),
        loops=int(c.get("loops", 1)),
        **_wire_knobs(c),
    )
    try:
        det.seed_full_membership()
        deadline = _time.monotonic() + warmup_timeout
        while not det.warm():
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"native cluster (n={n}) did not converge within "
                    f"{warmup_timeout}s of warmup")
            _time.sleep(period)
        rec = FlightRecorder(trace, source="native-campaign", n=n,
                             case=doc.get("family", "case"),
                             crash_rounds={str(v): crash_at
                                           for v in victims})
        # one relative clock for the stream AND the gate windows: the
        # absolute round attach_recorder rebased to anchors both
        r0 = det.attach_recorder(rec)
        det.load_scenario(sc, round0=r0)
        v0 = det.vitals()
        det.advance((r0 + crash_at) - det.round)
        for v in victims:
            det.crash(v)
        remaining = (r0 + rounds) - det.round
        if remaining > 0:
            det.advance(remaining)
        wire = _wire_delta(v0, det.vitals(), rounds)
        # stop the loop BEFORE draining: the drain's host-side parse is
        # seconds of CPU the 1-core epoll thread would otherwise lose —
        # enough wall time to stale entries and cascade manufactured
        # FPs into the recorded tail (gfs_stop's raison d'etre)
        det.stop()
        det.pump_obs()
        rec.close()
    finally:
        det.close()

    row = _monitor_row(trace, MonitorParams.from_dict(doc["monitor"]), n,
                       crash_rounds={v: crash_at for v in victims})
    _, events = load_stream(trace)
    row.update(engine="native", trace=str(trace), period=period,
               tick_ms=latency_histogram(events), wire=wire)
    return row


def run_ab_cell(n: int, *, delta: bool, loops: int = 1,
                rounds: int = 24, period: float | None = None,
                fanout: int | None = None, t_fail: int = 12,
                delta_entries: int = 16, anti_entropy_every: int = 6,
                settle: int | None = None,
                warmup_timeout: float | None = None) -> dict:
    """One quiet-cluster perf cell on the native engine — the delta
    A/B's measurement unit (``tools/campaign.py --ab``): warm a fresh
    n-node cluster in (delta, loops) mode, run ``rounds`` steady-state
    rounds, and report the wire accounting (bytes/round, full vs delta
    frame split) plus the per-round ``tick_ms`` histogram.  No faults:
    the verdict plane is the matrix's job; this cell isolates the two
    payoff observables — payload bytes and merge-pass latency.

    ``fanout`` defaults to max(16, log-fanout): delta mode concentrates
    a stable entry's refresh opportunities on anti-entropy rounds, so
    the per-node miss floor is ~e^-fanout per AE round — 16 keeps the
    expected misses over the run well under one node even at n=1024,
    and BOTH arms run the same fanout so the A/B isolates the wire
    format, not the push width.

    ``settle`` rounds run between warmup and the measurement window so
    per-peer delta cursors populate first — a cursor-less peer gets a
    full list, and with random push each peer pair first meets after
    ~n/fanout rounds in expectation, so an unsettled window measures
    mostly first-contact fulls instead of the steady-state delta mix.
    Defaults to 2*ceil(n/fanout) (residual cursor-less fraction ~e^-2);
    both arms settle identically so the A/B stays symmetric.

    ``t_fail`` defaults to 2x ``anti_entropy_every``: delta mode only
    GUARANTEES an entry refresh on anti-entropy rounds (the changed-
    first slots are recency-biased and the rr tail gets leftover
    capacity only), so the staleness budget must clear the AE cadence
    with margin — t_fail >= 2*anti_entropy_every keeps a single lost
    AE push from crossing the suspicion threshold on a quiet cluster."""
    import time as _time

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.native import NativeUdpDetector, latency_histogram
    from gossipfs_tpu.obs.recorder import FlightRecorder, load_stream

    if period is None:
        period = native_period(n)
    if fanout is None:
        fanout = max(16, SimConfig.log_fanout(n))
    if settle is None:
        settle = 2 * -(-n // fanout)
    if warmup_timeout is None:
        # the seeded cold start pays a one-time staleness cascade in
        # delta mode (every entry starts equally stale and the bounded
        # frames throttle first refreshes): n=1024 warms in ~120s of
        # churn that then fully quenches, so the gate scales with n
        warmup_timeout = max(300.0, 0.75 * n)
    knobs = {}
    if delta:
        knobs = dict(delta=True, delta_entries=delta_entries,
                     anti_entropy_every=anti_entropy_every)
    trace = tempfile.mktemp(prefix="ab_cell_", suffix=".jsonl")
    det = NativeUdpDetector(
        n, base_port=_free_udp_base(n), period=period, t_fail=t_fail,
        t_cooldown=t_fail + 4, fresh_cooldown=True, push="random",
        fanout=fanout, remove_broadcast=False, loops=loops, **knobs)
    try:
        det.seed_full_membership()
        deadline = _time.monotonic() + warmup_timeout
        while not det.warm():
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"ab cell (n={n}, delta={delta}, loops={loops}) "
                    f"did not converge within {warmup_timeout}s")
            _time.sleep(period)
        if settle > 0:
            det.advance(settle)
        rec = FlightRecorder(trace, source="native-ab", n=n, case="ab")
        det.attach_recorder(rec)
        v0 = det.vitals()
        det.advance(rounds)
        v1 = det.vitals()
        wire = _wire_delta(v0, v1, rounds)
        det.stop()
        det.pump_obs()
        rec.close()
    finally:
        det.close()
    _, events = load_stream(trace)
    cell = {
        "n": n, "delta": bool(delta), "loops": loops, "period": period,
        "fanout": fanout, "rounds": rounds, "settle": settle,
        "t_fail": t_fail,
        "false_positives": (v1["false_positives"]
                            - v0["false_positives"]),
        "n_alive": v1["n_alive"],
        "wire": wire, "tick_ms": latency_histogram(events),
    }
    if delta:
        cell["delta_entries"] = delta_entries
        cell["anti_entropy_every"] = anti_entropy_every
    return cell


# ---------------------------------------------------------------------------
# deploy engine
# ---------------------------------------------------------------------------


def _merge_streams(paths) -> str:
    """Stable round-order merge of several node logs into one stream
    file feed_jsonl can tail (tools/timeline.py's merge semantics:
    concatenate, stable-sort by round — per-node logs are already
    round-ordered)."""
    from gossipfs_tpu.obs import schema
    from gossipfs_tpu.obs.recorder import load_stream

    events = []
    for p in paths:
        _, evs = load_stream(p)
        events.extend(evs)
    events.sort(key=lambda e: e.round)
    out = tempfile.mktemp(prefix="deploy_case_", suffix=".jsonl")
    with open(out, "w", encoding="utf-8") as f:
        f.write(schema.dumps(schema.header("deploy-campaign")) + "\n")
        for e in events:
            f.write(schema.dumps(e.to_record()) + "\n")
    return out


def run_case_deploy(doc: dict, *, period: float = 0.1,
                    trace: str | None = None) -> dict:
    """One case on the per-process deployment.

    Spawns the launcher cluster, pushes the scenario + suspicion params
    over the (backoff-hardened) control plane, ``kill -9``s the tracked
    victims at the case's crash round, and tails the per-node
    ``node<i>.log`` schema streams through the monitor.  The deploy
    daemons have no ground-truth aliveness, so the verdict covers the
    invariants their streams can carry (``verdict_agreement`` compares
    only those against the tensor run).
    """
    from gossipfs_tpu.deploy.launcher import Cluster

    c = doc["config"]
    n, sc, crash_at, rounds, victims = _case_plan(doc)

    cluster = Cluster(n, period=period, t_fail=int(c["t_fail"]))
    try:
        cluster.start()
        sus = _suspicion_params(c)
        if sus is not None:
            acked = cluster.load_suspicion(sus)
            if len(acked) != n:
                raise RuntimeError(f"suspicion push acked by {acked}")
        acked = cluster.load_scenario(sc)
        if len(acked) != n:
            raise RuntimeError(f"scenario push acked by {acked}")
        # scenario-relative clock: each node anchored its windows at the
        # push; read the survivors' round counters to place the crashes
        r0 = max((line.get("round", 0)
                  for line in cluster.vitals()), default=0)
        time.sleep(crash_at * period)
        for v in victims:
            cluster.kill9(v)
        time.sleep(max(rounds - crash_at, 0) * period)
        logs = [str(pathlib.Path(cluster.root) / f"node{i}.log")
                for i in range(n)]
    finally:
        cluster.stop()

    merged = _merge_streams(logs)
    # shift the monitor clocks to the arming-relative frame: the crash
    # landed ~crash_at rounds after the push-time round r0
    row = _monitor_row(
        merged, MonitorParams.from_dict(doc["monitor"]), n,
        crash_rounds={v: r0 + crash_at for v in victims})
    if trace is not None:
        pathlib.Path(merged).rename(trace)
        merged = trace
    row.update(engine="deploy", trace=str(merged), period=period,
               arming_round=r0)
    return row


# ---------------------------------------------------------------------------
# the one entry tools/campaign.py --engine calls
# ---------------------------------------------------------------------------


def run_case_engine(path, engine: str = "udp", *, scale_n: int | None = None,
                    period: float | None = None,
                    trace: str | None = None) -> dict:
    """Drive a committed case file through a socket engine and require
    its monitor verdict to agree with the tensor replay's.

    Returns ``{"reproduced": ..., "agreement": {...}, "tensor": ...,
    "engine_row": ...}`` — ``reproduced`` is True iff the socket
    verdict reproduces the case's expectation AND agrees with the
    tensor run on every invariant both checked.
    """
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; pick from {ENGINES}")
    doc = load_case(path)
    if scale_n is not None:
        doc = scale_case(doc, scale_n)
    tensor = run_case_doc(doc)
    if engine == "tensor":
        return {**tensor, "engine": "tensor", "n": doc["config"]["n"]}
    if engine == "udp":
        row = run_case_udp(doc, **({"period": period} if period else {}),
                           trace=trace)
    elif engine == "native":
        row = run_case_native(doc, **({"period": period} if period else {}),
                              trace=trace)
    else:
        row = run_case_deploy(doc, **({"period": period} if period else {}),
                              trace=trace)
    agreement = verdict_agreement(tensor["row"], row)
    # the cross-engine contract is AGREEMENT with the tensor replay on
    # every invariant both checked.  The case's own expectation applies
    # on top only at the COMMITTED cohort size: a rescaled run's
    # breaking point legitimately moves (the absorption knife-edge is
    # cohort-sized — see scale_case / the n=64 twin's finding), so there
    # the tensor replay of the SAME scaled doc is the reference.
    reproduced = agreement["match"]
    expect_ok = None
    if doc.get("scaled_from") is None and (
        set(doc["expect"].get("invariants", []))
        <= set(row["monitor"]["invariants_checked"])
    ):
        expect_ok = case_verdict_ok(row, doc["expect"])
        reproduced = reproduced and expect_ok
    return {
        "engine": engine,
        "n": doc["config"]["n"],
        "scaled_from": doc.get("scaled_from"),
        "reproduced": bool(reproduced),
        "expect_reproduced": expect_ok,
        "agreement": agreement,
        "expect": doc["expect"],
        "tensor_verdict": tensor["row"]["verdict"],
        "engine_verdict": row["verdict"],
        "engine_row": row,
    }
