"""Campaign driver: scenario families x the streaming monitor oracle.

One campaign RUN (:func:`run_scenario`) is: build the north-star
gossip-only config at the requested (t_fail, t_suspect) knob, schedule
``track`` deterministic crashes (the TTD/reconvergence probes), run the
bulk tensor engine with the family's compiled fault scenario armed,
decode the scan into ``gossipfs-obs/v1`` events (the PR-5 flight
recorder — zero extra device work), and stream them through a
:class:`~gossipfs_tpu.obs.monitor.StreamMonitor`.  The monitor's
verdict IS the run's verdict: estimators + the invariant table, no
hand-read artifacts.

Determinism: runs take no random churn (``crash_rate=0``) — the only
randomness is the per-round topology sampling and any Bernoulli loss
rules, both keyed from the run seed — so a committed regression case
replays bit-identically (the tier-1 smoke's contract).

Severity axes are searched two ways: :func:`sweep_axis` (grid) and
:func:`bisect_axis` (smallest violating value of a monotone axis — the
breaking point).  Confirmed breaking points are committed as CASE files
(:func:`write_case` / :func:`run_case`): scenario + config knobs +
monitor params + the expected verdict, self-contained.
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib

from gossipfs_tpu.obs import schema
from gossipfs_tpu.obs.monitor import MonitorParams, StreamMonitor
from gossipfs_tpu.scenarios.schedule import (
    CorrelatedOutage,
    FaultScenario,
    Flapping,
    LinkFault,
    Partition,
)

CASE_SCHEMA = "gossipfs-campaign-case/v1"

# severity axis per family (the knob sweep/bisect walks), with the
# family's fixed knob defaults.  ``frac`` knobs count as "1/frac of the
# cohort"; node sets are drawn deterministically, skipping the tracked
# crash victims and the introducer so the fault rules never overlap the
# TTD probes.
FAMILIES: dict[str, dict] = {
    "flap": {
        "axis": "down",
        "knobs": {"down": 4, "up": 2, "frac": 16, "start": 2},
        "doc": "flapping senders: `down` dark rounds per `up`+`down` "
               "cycle on 1/frac of the cohort — the Lifeguard gray "
               "failure; the breaking point is the dark span that "
               "outlives the (t_fail [+ t_suspect]) window",
    },
    "loss": {
        "axis": "rate_pct",
        "knobs": {"rate_pct": 50, "frac": 16, "start": 2},
        "doc": "Bernoulli loss on 1/frac of senders' outgoing links at "
               "rate_pct/100 — asymmetric lossy NICs",
    },
    "partition": {
        "axis": "split_len",
        "knobs": {"split_len": 12, "start": 5},
        "doc": "half/half netsplit held for split_len rounds, then "
               "healed — the split-brain / reconvergence probe",
    },
    "outage": {
        "axis": "size",
        "knobs": {"size": 8, "length": 10, "start": 5},
        "doc": "correlated rack blackout: `size` nodes lose ALL "
               "transport for `length` rounds, then resurface with "
               "frozen views",
    },
}


def campaign_config(n: int, t_fail: int = 5, t_suspect: int = 0):
    """The campaign protocol profile: gossip-only random log-fanout on
    the XLA merge (the CPU-feasible oracle form — an on-TPU campaign
    passes its own kernel knobs through ``run_scenario(config=...)``)."""
    from gossipfs_tpu.config import SimConfig

    cfg = SimConfig(
        n=n, topology="random", fanout=SimConfig.log_fanout(n),
        remove_broadcast=False, fresh_cooldown=True, t_fail=t_fail,
        t_cooldown=max(12, t_fail + 4), merge_kernel="xla",
    )
    if t_suspect > 0:
        from gossipfs_tpu.suspicion import SuspicionParams

        cfg = dataclasses.replace(
            cfg, suspicion=SuspicionParams(t_suspect=t_suspect))
    return cfg


def _pick_nodes(n: int, count: int, avoid: set[int]) -> tuple[int, ...]:
    """First ``count`` ids outside ``avoid`` — deterministic, disjoint
    from the tracked crash victims."""
    out = []
    for x in range(n):
        if x not in avoid:
            out.append(x)
            if len(out) == count:
                break
    return tuple(out)


def make_scenario(family: str, n: int, fault_rounds: int,
                  avoid: set[int] | None = None, **knobs) -> FaultScenario:
    """Build one family scenario at a severity point (see FAMILIES)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; pick from "
                         f"{sorted(FAMILIES)}")
    kn = dict(FAMILIES[family]["knobs"])
    unknown = set(knobs) - set(kn)
    if unknown:
        raise ValueError(f"unknown {family} knobs {sorted(unknown)}; "
                         f"family takes {sorted(kn)}")
    kn.update(knobs)
    avoid = set(avoid or ())
    start = int(kn["start"])
    name = f"{family}-" + ",".join(
        f"{k}={kn[k]}" for k in sorted(kn) if k != "start")
    if family == "flap":
        nodes = _pick_nodes(n, max(n // int(kn["frac"]), 1), avoid)
        return FaultScenario(
            name=name, n=n,
            flapping=(Flapping(start=start, end=start + fault_rounds,
                               up=int(kn["up"]), down=int(kn["down"]),
                               nodes=nodes),))
    if family == "loss":
        nodes = _pick_nodes(n, max(n // int(kn["frac"]), 1), avoid)
        return FaultScenario(
            name=name, n=n,
            link_faults=(LinkFault(start=start, end=start + fault_rounds,
                                   rate=int(kn["rate_pct"]) / 100.0,
                                   src=nodes, dst=tuple(range(n))),))
    if family == "partition":
        return FaultScenario(
            name=name, n=n,
            partitions=(Partition(start=start,
                                  end=start + int(kn["split_len"]),
                                  groups=(tuple(range(n // 2)),)),))
    # outage
    nodes = _pick_nodes(n, int(kn["size"]), avoid)
    return FaultScenario(
        name=name, n=n,
        outages=(CorrelatedOutage(start=start,
                                  end=start + int(kn["length"]),
                                  nodes=nodes),))


def default_monitor_params(cfg, horizon: int) -> MonitorParams:
    """The campaign invariant knobs: FPR-storm threshold 1e-4 (healthy
    regimes measure ~4e-7, raw-t3 storms ~4e-3 — SUSPECT_r08), and the
    reconvergence bound t_fail + gossip diameter + slack clocked from
    the scenario horizon (faults legitimately delay convergence while
    armed)."""
    diameter = math.ceil(math.log(max(cfg.n, 2))
                         / math.log(cfg.fanout + 1))
    return MonitorParams(
        fpr_threshold=1e-4,
        fpr_window=10,
        reconverge_bound=cfg.t_fail + diameter + 4,
        clock_floor=horizon,
        expect_suspicion=cfg.suspicion is not None,
    )


def run_scenario(n: int, scenario: FaultScenario, *, t_fail: int = 5,
                 t_suspect: int = 0, rounds: int | None = None,
                 seed: int = 0, track: int = 4, crash_at: int = 10,
                 params: MonitorParams | None = None,
                 config=None) -> dict:
    """One campaign run: bulk engine + decode + streaming monitor.

    Returns the ledger row: verdict, monitor estimators, the violation
    list, and the violating event window (all decoded events within 2
    rounds of the first violation — the evidence a human reads)."""
    import jax

    from gossipfs_tpu.bench.run import tracked_crash_events
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state
    from gossipfs_tpu.obs.recorder import decode_scan
    from gossipfs_tpu.scenarios.tensor import compile_tensor

    cfg = config if config is not None else campaign_config(
        n, t_fail=t_fail, t_suspect=t_suspect)
    if params is None:
        params = default_monitor_params(cfg, scenario.horizon)
    if rounds is None:
        # past the last fault window + the reconvergence deadline
        bound = params.reconverge_bound or (cfg.t_fail + 6)
        rounds = scenario.horizon + bound + 8
    events, crash_rounds, churn_ok = tracked_crash_events(
        cfg, rounds, track, crash_at)
    final, carry, per_round = run_rounds(
        init_state(cfg), cfg, rounds, jax.random.PRNGKey(seed),
        events=events, crash_only_events=True,
        scenario=compile_tensor(scenario),
    )
    jax.block_until_ready(carry)
    evs = decode_scan(
        per_round, carry, n=cfg.n, crash_rounds=crash_rounds,
        alive=final.alive, suspicion=cfg.suspicion is not None,
    )
    mon = StreamMonitor(params=params, n=cfg.n)
    mon.observe_header(schema.header(
        "campaign", n=cfg.n,
        crash_rounds={str(k): v for k, v in crash_rounds.items()}))
    mon.feed(evs)
    mon.finish()
    s = mon.summary()
    window: list[dict] = []
    if mon.violations:
        w = mon.violations[0].round
        window = [e.to_record() for e in evs
                  if abs(e.round - w) <= 2][:48]
    return {
        "n": cfg.n,
        "t_fail": cfg.t_fail,
        "t_suspect": (cfg.suspicion.t_suspect if cfg.suspicion else 0),
        "rounds": rounds,
        "seed": seed,
        "scenario": scenario.name,
        "horizon": scenario.horizon,
        "monitor_params": dataclasses.asdict(params),
        "verdict": "violated" if mon.violations else "pass",
        "monitor": mon.verdict(),
        "estimators": {
            "false_positive_rate": s["false_positive_rate"],
            "worst_window_fpr": s["worst_window_fpr"],
            "ttd_first_median": s["ttd_first_median"],
            "detected": s["detected"],
            "tracked_crashes": s["tracked_crashes"],
            "storm_rounds": s["storm_rounds"],
            "split_brain_rounds": s["split_brain_rounds"],
            **({"fp_suppressed": s["fp_suppressed"],
                "refutations": s["refutations"]} if s["suspicion"] else {}),
        },
        "violations": s["violations"],
        "violation_window": window,
    }


# ---------------------------------------------------------------------------
# the campaign ledger — a gossipfs-obs/v1 stream timeline.py ingests
# ---------------------------------------------------------------------------


class CampaignLedger:
    """JSONL ledger: the obs header row, then one ``campaign_verdict``
    event per run (detail = the ledger row).  ``tools/timeline.py``
    loads it like any other stream; the verdict rows ride ``detail``."""

    def __init__(self, path, family: str, n: int, axis: str, **meta):
        self.path = pathlib.Path(path)
        self.rows: list[dict] = []
        self._fh = open(self.path, "w", encoding="utf-8")
        self._fh.write(schema.dumps(schema.header(
            "campaign", n=n, family=family, axis=axis, **meta)) + "\n")

    def add(self, axis_value, row: dict) -> None:
        self.rows.append(row)
        ev = schema.Event(
            round=len(self.rows) - 1, observer=-1, subject=-1,
            kind="campaign_verdict",
            detail={"axis_value": axis_value, **row})
        self._fh.write(schema.dumps(ev.to_record()) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def sweep_axis(family: str, n: int, values, *, fault_rounds: int = 24,
               t_fail: int = 5, t_suspect: int = 0, seed: int = 0,
               track: int = 4, ledger: CampaignLedger | None = None,
               **fixed_knobs) -> dict:
    """Grid-sweep the family's severity axis; returns rows + the
    breaking points (axis values whose run violated an invariant)."""
    axis = _axis_checked(family, fixed_knobs)
    rows = []
    for v in values:
        sc, row = _run_point(family, n, axis, v, fault_rounds, t_fail,
                             t_suspect, seed, track, fixed_knobs)
        rows.append(row)
        if ledger is not None:
            ledger.add(v, row)
    return {
        "family": family, "axis": axis, "n": n,
        "t_fail": t_fail, "t_suspect": t_suspect,
        "rows": rows,
        "breaking": [r["axis_value"] for r in rows
                     if r["verdict"] == "violated"],
    }


def bisect_axis(family: str, n: int, lo: int, hi: int, *,
                fault_rounds: int = 24, t_fail: int = 5,
                t_suspect: int = 0, seed: int = 0, track: int = 4,
                ledger: CampaignLedger | None = None,
                **fixed_knobs) -> dict:
    """Smallest axis value in [lo, hi] whose run violates an invariant
    (the axis must be severity-monotone — every family's is).  Probes
    the endpoints first: if ``lo`` already violates the breaking point
    is <= lo; if ``hi`` passes there is none in range."""
    axis = _axis_checked(family, fixed_knobs)
    evals: dict[int, dict] = {}

    def probe(v: int) -> dict:
        if v not in evals:
            _, row = _run_point(family, n, axis, v, fault_rounds, t_fail,
                                t_suspect, seed, track, fixed_knobs)
            evals[v] = row
            if ledger is not None:
                ledger.add(v, row)
        return evals[v]

    out = {"family": family, "axis": axis, "n": n, "lo": lo, "hi": hi,
           "t_fail": t_fail, "t_suspect": t_suspect}
    if probe(hi)["verdict"] != "violated":
        return {**out, "breaking_point": None, "evals": len(evals),
                "rows": [evals[v] for v in sorted(evals)]}
    if probe(lo)["verdict"] == "violated":
        return {**out, "breaking_point": lo, "evals": len(evals),
                "rows": [evals[v] for v in sorted(evals)]}
    # invariant: lo passes, hi violates
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if probe(mid)["verdict"] == "violated":
            hi = mid
        else:
            lo = mid
    return {**out, "breaking_point": hi, "evals": len(evals),
            "rows": [evals[v] for v in sorted(evals)]}


def _axis_checked(family: str, fixed_knobs: dict) -> str:
    """The family's severity axis, rejecting a fixed-knob collision
    up front (before any run or ledger row) instead of letting the
    duplicate-kwarg TypeError surface mid-campaign."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; pick from "
                         f"{sorted(FAMILIES)}")
    axis = FAMILIES[family]["axis"]
    if axis in fixed_knobs:
        raise ValueError(
            f"{axis!r} is the {family} family's swept severity axis — "
            "give it via the sweep values / bisect range, not as a "
            "fixed knob")
    return axis


def _run_point(family, n, axis, value, fault_rounds, t_fail, t_suspect,
               seed, track, fixed_knobs):
    from gossipfs_tpu.bench.run import tracked_crash_events

    cfg = campaign_config(n, t_fail=t_fail, t_suspect=t_suspect)
    # victims are a pure function of (cfg, track) — compute them first so
    # the family's fault nodes can avoid the TTD probes
    _, crash_rounds, _ = tracked_crash_events(cfg, fault_rounds + 1,
                                              track, 10)
    sc = make_scenario(family, n, fault_rounds,
                       avoid=set(crash_rounds) | {cfg.introducer},
                       **{axis: value}, **fixed_knobs)
    row = run_scenario(n, sc, t_fail=t_fail, t_suspect=t_suspect,
                       seed=seed, track=track)
    return sc, {"axis_value": value, **row}


# ---------------------------------------------------------------------------
# regression case files — committed breaking points, replayed by tier-1
# ---------------------------------------------------------------------------


def write_case(path, scenario: FaultScenario, *, t_fail: int,
               t_suspect: int, seed: int, track: int,
               params: MonitorParams, expect: dict, **meta) -> None:
    """Commit one confirmed breaking point as a self-contained case:
    the scenario, the exact run knobs, the monitor params, and the
    verdict a replay must reproduce."""
    doc = {
        "schema": CASE_SCHEMA,
        "scenario": json.loads(scenario.to_json()),
        "config": {"n": scenario.n, "t_fail": t_fail,
                   "t_suspect": t_suspect, "seed": seed, "track": track},
        "monitor": dataclasses.asdict(params),
        "expect": expect,
        **meta,
    }
    pathlib.Path(path).write_text(json.dumps(doc, indent=2) + "\n")


def run_case(path) -> dict:
    """Replay a committed regression case; ``reproduced`` is the tier-1
    assertion: the verdict matches and (for violations) every expected
    invariant fired."""
    doc = json.loads(pathlib.Path(path).read_text())
    if doc.get("schema") != CASE_SCHEMA:
        raise ValueError(f"{path}: not a {CASE_SCHEMA} case file")
    sc = FaultScenario.from_json(json.dumps(doc["scenario"]))
    c = doc["config"]
    row = run_scenario(
        c["n"], sc, t_fail=c["t_fail"], t_suspect=c["t_suspect"],
        seed=c["seed"], track=c["track"],
        params=MonitorParams.from_dict(doc["monitor"]),
    )
    expect = doc["expect"]
    ok = row["verdict"] == expect["verdict"]
    for inv in expect.get("invariants", []):
        ok = ok and inv in row["monitor"]["by_invariant"]
    return {"reproduced": bool(ok), "expect": expect, "row": row}
