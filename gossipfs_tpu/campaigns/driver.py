"""Campaign driver: scenario families x the streaming monitor oracle.

One campaign RUN (:func:`run_scenario`) is: build the north-star
gossip-only config at the requested (t_fail, t_suspect) knob, schedule
``track`` deterministic crashes (the TTD/reconvergence probes), run the
bulk tensor engine with the family's compiled fault scenario armed,
decode the scan into ``gossipfs-obs/v1`` events (the PR-5 flight
recorder — zero extra device work), and stream them through a
:class:`~gossipfs_tpu.obs.monitor.StreamMonitor`.  The monitor's
verdict IS the run's verdict: estimators + the invariant table, no
hand-read artifacts.

Determinism: runs take no random churn (``crash_rate=0``) — the only
randomness is the per-round topology sampling and any Bernoulli loss
rules, both keyed from the run seed — so a committed regression case
replays bit-identically (the tier-1 smoke's contract).

Severity axes are searched two ways: :func:`sweep_axis` (grid) and
:func:`bisect_axis` (smallest violating value of a monotone axis — the
breaking point).  Confirmed breaking points are committed as CASE files
(:func:`write_case` / :func:`run_case`): scenario + config knobs +
monitor params + the expected verdict, self-contained.
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib

from gossipfs_tpu.obs import schema
from gossipfs_tpu.obs.monitor import MonitorParams, StreamMonitor
from gossipfs_tpu.scenarios.schedule import (
    CorrelatedOutage,
    FaultScenario,
    Flapping,
    LinkFault,
    Partition,
)

CASE_SCHEMA = "gossipfs-campaign-case/v1"

# severity axis per family (the knob sweep/bisect walks), with the
# family's fixed knob defaults.  ``frac`` knobs count as "1/frac of the
# cohort"; node sets are drawn deterministically, skipping the tracked
# crash victims and the introducer so the fault rules never overlap the
# TTD probes.
FAMILIES: dict[str, dict] = {
    "flap": {
        "axis": "down",
        "knobs": {"down": 4, "up": 2, "frac": 16, "start": 2},
        "doc": "flapping senders: `down` dark rounds per `up`+`down` "
               "cycle on 1/frac of the cohort — the Lifeguard gray "
               "failure; the breaking point is the dark span that "
               "outlives the (t_fail [+ t_suspect]) window",
    },
    "loss": {
        "axis": "rate_pct",
        "knobs": {"rate_pct": 50, "frac": 16, "start": 2},
        "doc": "Bernoulli loss on 1/frac of senders' outgoing links at "
               "rate_pct/100 — asymmetric lossy NICs",
    },
    "partition": {
        "axis": "split_len",
        "knobs": {"split_len": 12, "start": 5},
        "doc": "half/half netsplit held for split_len rounds, then "
               "healed — the split-brain / reconvergence probe",
    },
    "outage": {
        "axis": "size",
        "knobs": {"size": 8, "length": 10, "start": 5},
        "doc": "correlated rack blackout: `size` nodes lose ALL "
               "transport for `length` rounds, then resurface with "
               "frozen views",
    },
}


def campaign_config(n: int, t_fail: int = 5, t_suspect: int = 0,
                    lh_multiplier: int = 0, lh_frac: float = 0.25):
    """The campaign protocol profile: gossip-only random log-fanout on
    the XLA merge (the CPU-feasible oracle form — an on-TPU campaign
    passes its own kernel knobs through ``run_scenario(config=...)``).

    ``lh_multiplier``/``lh_frac`` (round 14): the Lifeguard local-health
    knobs, now first-class campaign axes — an observer whose own view
    holds more than ``lh_frac`` of its peers simultaneously SUSPECT
    stretches its confirmation window by ``1 + lh_multiplier``.  Use
    exact binary fractions (1/32, 1/64...) per suspicion/params.py.
    """
    from gossipfs_tpu.config import SimConfig

    cfg = SimConfig(
        n=n, topology="random", fanout=SimConfig.log_fanout(n),
        remove_broadcast=False, fresh_cooldown=True, t_fail=t_fail,
        t_cooldown=max(12, t_fail + 4), merge_kernel="xla",
    )
    if t_suspect > 0:
        from gossipfs_tpu.suspicion import SuspicionParams

        cfg = dataclasses.replace(
            cfg, suspicion=SuspicionParams(
                t_suspect=t_suspect, lh_multiplier=lh_multiplier,
                lh_frac=lh_frac))
    elif lh_multiplier > 0:
        raise ValueError(
            "lh_multiplier > 0 (Lifeguard local health) requires the "
            "SWIM lifecycle: pass t_suspect >= 1")
    return cfg


def _pick_nodes(n: int, count: int, avoid: set[int]) -> tuple[int, ...]:
    """First ``count`` ids outside ``avoid`` — deterministic, disjoint
    from the tracked crash victims."""
    out = []
    for x in range(n):
        if x not in avoid:
            out.append(x)
            if len(out) == count:
                break
    return tuple(out)


def make_scenario(family: str, n: int, fault_rounds: int,
                  avoid: set[int] | None = None, **knobs) -> FaultScenario:
    """Build one family scenario at a severity point (see FAMILIES)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; pick from "
                         f"{sorted(FAMILIES)}")
    kn = dict(FAMILIES[family]["knobs"])
    unknown = set(knobs) - set(kn)
    if unknown:
        raise ValueError(f"unknown {family} knobs {sorted(unknown)}; "
                         f"family takes {sorted(kn)}")
    kn.update(knobs)
    avoid = set(avoid or ())
    start = int(kn["start"])
    name = f"{family}-" + ",".join(
        f"{k}={kn[k]}" for k in sorted(kn) if k != "start")
    if family == "flap":
        nodes = _pick_nodes(n, max(n // int(kn["frac"]), 1), avoid)
        return FaultScenario(
            name=name, n=n,
            flapping=(Flapping(start=start, end=start + fault_rounds,
                               up=int(kn["up"]), down=int(kn["down"]),
                               nodes=nodes),))
    if family == "loss":
        nodes = _pick_nodes(n, max(n // int(kn["frac"]), 1), avoid)
        return FaultScenario(
            name=name, n=n,
            link_faults=(LinkFault(start=start, end=start + fault_rounds,
                                   rate=int(kn["rate_pct"]) / 100.0,
                                   src=nodes, dst=tuple(range(n))),))
    if family == "partition":
        return FaultScenario(
            name=name, n=n,
            partitions=(Partition(start=start,
                                  end=start + int(kn["split_len"]),
                                  groups=(tuple(range(n // 2)),)),))
    # outage
    nodes = _pick_nodes(n, int(kn["size"]), avoid)
    return FaultScenario(
        name=name, n=n,
        outages=(CorrelatedOutage(start=start,
                                  end=start + int(kn["length"]),
                                  nodes=nodes),))


def default_monitor_params(cfg, horizon: int) -> MonitorParams:
    """The campaign invariant knobs: FPR-storm threshold 1e-4 (healthy
    regimes measure ~4e-7, raw-t3 storms ~4e-3 — SUSPECT_r08), and the
    reconvergence bound: the armed detector's WORST-CASE confirmation
    window + gossip diameter + slack, clocked from the scenario horizon
    (faults legitimately delay convergence while armed).  The worst-case
    window is ``t_fail + t_suspect * (1 + lh_multiplier)`` under the
    SWIM lifecycle (``SuspicionParams.max_confirm_after`` — a
    local-health-stretched observer legitimately confirms, and
    stops gossiping, that much later) and plain ``t_fail`` without it;
    round 13's ``t_fail``-only bound under-counted armed suspicion by
    ``t_suspect`` and flagged correctly-converging lh runs."""
    diameter = math.ceil(math.log(max(cfg.n, 2))
                         / math.log(cfg.fanout + 1))
    worst = (cfg.suspicion.max_confirm_after(cfg.t_fail)
             if cfg.suspicion is not None else cfg.t_fail)
    return MonitorParams(
        fpr_threshold=1e-4,
        fpr_window=10,
        reconverge_bound=worst + diameter + 4,
        clock_floor=horizon,
        expect_suspicion=cfg.suspicion is not None,
    )


def campaign_rounds(horizon: int, crash_at: int, bound: int) -> int:
    """THE run-length derivation every engine shares: past the last
    fault window AND the tracked crashes' own detection horizon, plus
    the reconvergence deadline and slack.  One owner — the socket
    runners (campaigns/engines.py) are verdict-compared against the
    tensor replay round for round, so a drifted copy would silently
    compare different experiments."""
    return max(horizon, crash_at) + bound + 8


def run_scenario(n: int, scenario: FaultScenario | None, *,
                 t_fail: int = 5,
                 t_suspect: int = 0, lh_multiplier: int = 0,
                 lh_frac: float = 0.25, rounds: int | None = None,
                 seed: int = 0, track: int = 4, crash_at: int = 10,
                 params: MonitorParams | None = None,
                 config=None) -> dict:
    """One campaign run: bulk engine + decode + streaming monitor.

    ``scenario=None`` runs the QUIET baseline — no fault rules, same
    tracked crashes — which is what the local-health knob surface
    compares outage rows against (the deterministic t_fail=5 quiet run
    has ZERO false positives, so "FPR at the t_fail=5 baseline" is an
    exact-count comparison, not a tolerance).

    Returns the ledger row: verdict, monitor estimators, the violation
    list, and the violating event window (all decoded events within 2
    rounds of the first violation — the evidence a human reads)."""
    import jax

    from gossipfs_tpu.bench.run import tracked_crash_events
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state
    from gossipfs_tpu.obs.recorder import decode_scan
    from gossipfs_tpu.scenarios.tensor import compile_tensor

    if scenario is None:
        scenario = FaultScenario(name="quiet", n=n)
    cfg = config if config is not None else campaign_config(
        n, t_fail=t_fail, t_suspect=t_suspect,
        lh_multiplier=lh_multiplier, lh_frac=lh_frac)
    if params is None:
        params = default_monitor_params(cfg, scenario.horizon)
    if rounds is None:
        bound = params.reconverge_bound or (cfg.t_fail + 6)
        rounds = campaign_rounds(scenario.horizon, crash_at, bound)
    events, crash_rounds, churn_ok = tracked_crash_events(
        cfg, rounds, track, crash_at)
    final, carry, per_round = run_rounds(
        init_state(cfg), cfg, rounds, jax.random.PRNGKey(seed),
        events=events, crash_only_events=True,
        scenario=compile_tensor(scenario),
    )
    jax.block_until_ready(carry)
    evs = decode_scan(
        per_round, carry, n=cfg.n, crash_rounds=crash_rounds,
        alive=final.alive, suspicion=cfg.suspicion is not None,
    )
    mon = StreamMonitor(params=params, n=cfg.n)
    mon.observe_header(schema.header(
        "campaign", n=cfg.n,
        crash_rounds={str(k): v for k, v in crash_rounds.items()}))
    mon.feed(evs)
    mon.finish()
    s = mon.summary()
    window: list[dict] = []
    if mon.violations:
        w = mon.violations[0].round
        window = [e.to_record() for e in evs
                  if abs(e.round - w) <= 2][:48]
    return {
        "n": cfg.n,
        "t_fail": cfg.t_fail,
        "t_suspect": (cfg.suspicion.t_suspect if cfg.suspicion else 0),
        "lh_multiplier": (cfg.suspicion.lh_multiplier
                          if cfg.suspicion else 0),
        "lh_frac": (cfg.suspicion.lh_frac if cfg.suspicion else 0.0),
        "rounds": rounds,
        "seed": seed,
        "scenario": scenario.name,
        "horizon": scenario.horizon,
        "monitor_params": dataclasses.asdict(params),
        "verdict": "violated" if mon.violations else "pass",
        "monitor": mon.verdict(),
        "estimators": {
            "false_positives": s["false_positives"],
            "false_positive_rate": s["false_positive_rate"],
            "worst_window_fpr": s["worst_window_fpr"],
            "ttd_first_median": s["ttd_first_median"],
            "detected": s["detected"],
            "tracked_crashes": s["tracked_crashes"],
            "storm_rounds": s["storm_rounds"],
            "split_brain_rounds": s["split_brain_rounds"],
            **({"fp_suppressed": s["fp_suppressed"],
                "refutations": s["refutations"]} if s["suspicion"] else {}),
        },
        "violations": s["violations"],
        "violation_window": window,
    }


# ---------------------------------------------------------------------------
# the campaign ledger — a gossipfs-obs/v1 stream timeline.py ingests
# ---------------------------------------------------------------------------


class CampaignLedger:
    """JSONL ledger: the obs header row, then one ``campaign_verdict``
    event per run (detail = the ledger row).  ``tools/timeline.py``
    loads it like any other stream; the verdict rows ride ``detail``."""

    def __init__(self, path, family: str, n: int, axis: str, **meta):
        self.path = pathlib.Path(path)
        self.rows: list[dict] = []
        self._fh = open(self.path, "w", encoding="utf-8")
        self._fh.write(schema.dumps(schema.header(
            "campaign", n=n, family=family, axis=axis, **meta)) + "\n")

    def add(self, axis_value, row: dict) -> None:
        self.rows.append(row)
        ev = schema.Event(
            round=len(self.rows) - 1, observer=-1, subject=-1,
            kind="campaign_verdict",
            detail={"axis_value": axis_value, **row})
        self._fh.write(schema.dumps(ev.to_record()) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def sweep_axis(family: str, n: int, values, *, fault_rounds: int = 24,
               t_fail: int = 5, t_suspect: int = 0,
               lh_multiplier: int = 0, lh_frac: float = 0.25,
               seed: int = 0,
               track: int = 4, ledger: CampaignLedger | None = None,
               **fixed_knobs) -> dict:
    """Grid-sweep the family's severity axis; returns rows + the
    breaking points (axis values whose run violated an invariant)."""
    axis = _axis_checked(family, fixed_knobs)
    rows = []
    for v in values:
        sc, row = _run_point(family, n, axis, v, fault_rounds, t_fail,
                             t_suspect, lh_multiplier, lh_frac, seed,
                             track, fixed_knobs)
        rows.append(row)
        if ledger is not None:
            ledger.add(v, row)
    return {
        "family": family, "axis": axis, "n": n,
        "t_fail": t_fail, "t_suspect": t_suspect,
        "lh_multiplier": lh_multiplier, "lh_frac": lh_frac,
        "rows": rows,
        "breaking": [r["axis_value"] for r in rows
                     if r["verdict"] == "violated"],
    }


def bisect_axis(family: str, n: int, lo: int, hi: int, *,
                fault_rounds: int = 24, t_fail: int = 5,
                t_suspect: int = 0, lh_multiplier: int = 0,
                lh_frac: float = 0.25, seed: int = 0, track: int = 4,
                ledger: CampaignLedger | None = None,
                **fixed_knobs) -> dict:
    """Smallest axis value in [lo, hi] whose run violates an invariant
    (the axis must be severity-monotone — every family's is).  Probes
    the endpoints first: if ``lo`` already violates the breaking point
    is <= lo; if ``hi`` passes there is none in range."""
    axis = _axis_checked(family, fixed_knobs)
    evals: dict[int, dict] = {}

    def probe(v: int) -> dict:
        if v not in evals:
            _, row = _run_point(family, n, axis, v, fault_rounds, t_fail,
                                t_suspect, lh_multiplier, lh_frac, seed,
                                track, fixed_knobs)
            evals[v] = row
            if ledger is not None:
                ledger.add(v, row)
        return evals[v]

    out = {"family": family, "axis": axis, "n": n, "lo": lo, "hi": hi,
           "t_fail": t_fail, "t_suspect": t_suspect,
           "lh_multiplier": lh_multiplier, "lh_frac": lh_frac}
    if probe(hi)["verdict"] != "violated":
        return {**out, "breaking_point": None, "evals": len(evals),
                "rows": [evals[v] for v in sorted(evals)]}
    if probe(lo)["verdict"] == "violated":
        return {**out, "breaking_point": lo, "evals": len(evals),
                "rows": [evals[v] for v in sorted(evals)]}
    # invariant: lo passes, hi violates
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if probe(mid)["verdict"] == "violated":
            hi = mid
        else:
            lo = mid
    return {**out, "breaking_point": hi, "evals": len(evals),
            "rows": [evals[v] for v in sorted(evals)]}


def _axis_checked(family: str, fixed_knobs: dict) -> str:
    """The family's severity axis, rejecting a fixed-knob collision
    up front (before any run or ledger row) instead of letting the
    duplicate-kwarg TypeError surface mid-campaign."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; pick from "
                         f"{sorted(FAMILIES)}")
    axis = FAMILIES[family]["axis"]
    if axis in fixed_knobs:
        raise ValueError(
            f"{axis!r} is the {family} family's swept severity axis — "
            "give it via the sweep values / bisect range, not as a "
            "fixed knob")
    return axis


def _run_point(family, n, axis, value, fault_rounds, t_fail, t_suspect,
               lh_multiplier, lh_frac, seed, track, fixed_knobs):
    from gossipfs_tpu.bench.run import tracked_crash_events

    cfg = campaign_config(n, t_fail=t_fail, t_suspect=t_suspect,
                          lh_multiplier=lh_multiplier, lh_frac=lh_frac)
    # victims are a pure function of (cfg, track) — compute them first so
    # the family's fault nodes can avoid the TTD probes
    _, crash_rounds, _ = tracked_crash_events(cfg, fault_rounds + 1,
                                              track, 10)
    sc = make_scenario(family, n, fault_rounds,
                       avoid=set(crash_rounds) | {cfg.introducer},
                       **{axis: value}, **fixed_knobs)
    row = run_scenario(n, sc, t_fail=t_fail, t_suspect=t_suspect,
                       lh_multiplier=lh_multiplier, lh_frac=lh_frac,
                       seed=seed, track=track)
    return sc, {"axis_value": value, **row}


# ---------------------------------------------------------------------------
# regression case files — committed breaking points, replayed by tier-1
# ---------------------------------------------------------------------------


def write_case(path, scenario: FaultScenario, *, t_fail: int,
               t_suspect: int, seed: int, track: int,
               params: MonitorParams, expect: dict,
               lh_multiplier: int = 0, lh_frac: float = 0.25,
               crash_at: int = 10, **meta) -> None:
    """Commit one confirmed breaking point as a self-contained case:
    the scenario, the exact run knobs (local health included), the
    monitor params, and the verdict a replay must reproduce."""
    doc = {
        "schema": CASE_SCHEMA,
        "scenario": json.loads(scenario.to_json()),
        "config": {"n": scenario.n, "t_fail": t_fail,
                   "t_suspect": t_suspect,
                   "lh_multiplier": lh_multiplier, "lh_frac": lh_frac,
                   "crash_at": crash_at,
                   "seed": seed, "track": track},
        "monitor": dataclasses.asdict(params),
        "expect": expect,
        **meta,
    }
    pathlib.Path(path).write_text(json.dumps(doc, indent=2) + "\n")


def load_case(path) -> dict:
    """Parse + schema-check one committed case file (shared by the
    tensor replay below and the socket-engine runners in engines.py)."""
    doc = json.loads(pathlib.Path(path).read_text())
    if doc.get("schema") != CASE_SCHEMA:
        raise ValueError(f"{path}: not a {CASE_SCHEMA} case file")
    return doc


def case_verdict_ok(row: dict, expect: dict) -> bool:
    """Whether a replay row reproduces the case's expectation — the one
    predicate every engine's replay shares."""
    ok = row["verdict"] == expect["verdict"]
    for inv in expect.get("invariants", []):
        ok = ok and inv in row["monitor"]["by_invariant"]
    return bool(ok)


def run_traffic_case_doc(doc: dict) -> dict:
    """Replay a TRAFFIC-plane case: the durability harness's rack-kill
    storm (``traffic/harness.repair_storm``) in the case's redundancy
    mode — the campaign matrix's byte-plane leg, same self-contained
    knob contract as the gossip cases.  The verdict is the storm's
    durability triple: ``pass`` iff zero acked writes are lost under
    the cluster-state ledger AND the event replay AND the streaming
    monitor, with all three accountings in exact agreement (the
    ``no_acked_write_lost`` invariant holding verbatim in stripe mode)."""
    from gossipfs_tpu.traffic.harness import repair_storm
    from gossipfs_tpu.traffic.workload import WorkloadSpec

    t = doc["traffic"]
    spec = WorkloadSpec(
        rate=float(t.get("rate", 4.0)),
        n_keys=int(t.get("n_keys", 32)),
        payload_cap=int(t.get("payload_cap", 4096)),
        seed=int(t.get("seed", 0)),
        redundancy=t.get("redundancy", "replica"),
        **({"stripe_k": int(t["stripe_k"])} if "stripe_k" in t else {}),
        **({"stripe_m": int(t["stripe_m"])} if "stripe_m" in t else {}),
    )
    out = repair_storm(
        int(t["n"]), spec, files=int(t.get("files", 32)),
        rack=tuple(t.get("rack", (8, 8))),
        repair_budget=int(t.get("repair_budget", 8)),
        seed=int(t.get("seed", 0)),
    )
    d = out["durability"]
    ok = (d["harness"]["lost"] == 0 and d["events"]["lost"] == 0
          and d["match"] and d["monitor"]["ok"]
          and d["monitor"]["match_events"])
    row = {
        "verdict": "pass" if ok else "violated",
        "lost": d["harness"]["lost"],
        "files_acked": d["harness"]["files_acked"],
        "rack_killed": out["rack_killed"],
        "repairs_total": out["repairs_total"],
        "repair_bytes_written": out["repair_bytes_written"],
        "repair_copies": out["repair_copies"],
        "durability": d,
        "traffic_vitals": out["traffic_vitals"],
    }
    expect = doc["expect"]
    reproduced = (row["verdict"] == expect["verdict"]
                  and row["lost"] == int(expect.get("lost", row["lost"])))
    return {"reproduced": bool(reproduced), "expect": expect, "row": row}


def run_case_doc(doc: dict) -> dict:
    """Replay one parsed case document — gossip cases on the tensor
    engine, ``"traffic"`` cases on the durability harness."""
    if "traffic" in doc:
        return run_traffic_case_doc(doc)
    sc = FaultScenario.from_json(json.dumps(doc["scenario"]))
    c = doc["config"]
    row = run_scenario(
        c["n"], sc, t_fail=c["t_fail"], t_suspect=c["t_suspect"],
        lh_multiplier=int(c.get("lh_multiplier", 0)),
        lh_frac=float(c.get("lh_frac", 0.25)),
        seed=c["seed"], track=c["track"],
        crash_at=int(c.get("crash_at", 10)),
        params=MonitorParams.from_dict(doc["monitor"]),
    )
    expect = doc["expect"]
    return {"reproduced": case_verdict_ok(row, expect), "expect": expect,
            "row": row}


def run_case(path) -> dict:
    """Replay a committed regression case; ``reproduced`` is the tier-1
    assertion: the verdict matches and (for violations) every expected
    invariant fired."""
    return run_case_doc(load_case(path))


# ---------------------------------------------------------------------------
# the local-health knob surface — (outage size x lh knobs) absorption map
# ---------------------------------------------------------------------------


def _slim(row: dict) -> dict:
    """One surface row, estimators only (the full violation windows make
    a sizes x knobs artifact unreadable)."""
    return {
        "verdict": row["verdict"],
        "by_invariant": row["monitor"]["by_invariant"],
        "false_positives": row["estimators"].get("false_positives"),
        "false_positive_rate": row["estimators"]["false_positive_rate"],
        "worst_window_fpr": row["estimators"]["worst_window_fpr"],
        "ttd_first_median": row["estimators"]["ttd_first_median"],
        "detected": row["estimators"]["detected"],
        "tracked_crashes": row["estimators"]["tracked_crashes"],
    }


def knob_surface(n: int, sizes, lh_points, *, t_fail: int = 3,
                 t_suspect: int = 2, baseline_t_fail: int = 5,
                 length: int = 10, start: int = 5, rounds: int = 35,
                 seed: int = 0, track: int = 4, crash_at: int = 10,
                 ledger: CampaignLedger | None = None) -> dict:
    """Map the Lifeguard knob surface against correlated outages.

    For every outage ``size`` x ``(lh_multiplier, lh_frac)`` point, runs
    the outage scenario AND the quiet baseline at the SWIM knob
    (t_fail=3 + t_suspect=2 — the SUSPECT_r08 production profile, total
    window == the t_fail=5 reference), next to three reference rows: the
    raw t_fail=5 detector on the same outage (the designed-in storm),
    the lh-off SWIM knob on the same outage, and the lh-off quiet run.

    A point ABSORBS a size when (a) its outage run's FPR sits in the
    t_fail=5-class band — ``max(10x the t5 quiet baseline, 1e-6)``, the
    exact floor ``verify_claims.suspicion_fpr`` already uses (the quiet
    baseline is deterministic and measures 0.0; the floor admits the
    1-2 FP events from entries already past the detection window when
    the outage lands, ~7e-7, while rejecting the heal-race leak at
    ~7e-5 and the full storm at ~4e-4 by two orders each), (b) its
    outage run passes every monitor invariant, and (c) the
    tracked-crash median TTD grew at most one round over the lh-off
    QUIET baseline — on the outage run AND on the point's own quiet run
    (the stretch must not tax detection; the lh-off OUTAGE row is not a
    usable TTD reference, its storm confirms the probes before they
    crash).

    ``crash_at`` is a load-bearing axis, not a nuisance parameter: the
    probes' suspect windows overlap the outage's heal, and the surface
    at several crash_at values is what exposed the HEAL RACE — an
    observer whose rack refutations arrive staggered un-degrades while
    its remaining rack entries are still stale and confirms them (fp
    ~200 at crash_at >= 14, where the probe suspicions no longer cover
    the gap).  See BASELINE.md's knob-surface summary.

    Returns the surface document (LOCALHEALTH_r14.json's per-probe
    shape): baselines, one row per (size, point), and the absorption
    frontier.
    """
    from gossipfs_tpu.bench.run import tracked_crash_events

    cfg0 = campaign_config(n, t_fail=t_fail, t_suspect=t_suspect)
    _, crash_rounds, _ = tracked_crash_events(cfg0, rounds, track,
                                              crash_at)
    avoid = set(crash_rounds) | {cfg0.introducer}

    def outage(size):
        return make_scenario("outage", n, length, avoid=avoid,
                             size=size, length=length, start=start)

    def point_row(sc, tf, ts, m, f):
        row = run_scenario(n, sc, t_fail=tf, t_suspect=ts,
                           lh_multiplier=m, lh_frac=f, rounds=rounds,
                           seed=seed, track=track, crash_at=crash_at)
        if ledger is not None:
            ledger.add(sc.name if sc is not None else "quiet", row)
        return row

    base = {
        "t5_quiet": _slim(point_row(None, baseline_t_fail, 0, 0, 0.25)),
        "lh_off_quiet": _slim(point_row(None, t_fail, t_suspect, 0, 0.25)),
    }
    base["t5_outage"] = {
        str(s): _slim(point_row(outage(s), baseline_t_fail, 0, 0, 0.25))
        for s in sizes
    }
    base["lh_off_outage"] = {
        str(s): _slim(point_row(outage(s), t_fail, t_suspect, 0, 0.25))
        for s in sizes
    }

    def growth(a, b):
        if a is None or b is None:
            return None
        return a - b

    fpr_floor = max(10 * base["t5_quiet"]["false_positive_rate"], 1e-6)
    rows = []
    for (m, f) in lh_points:
        quiet = _slim(point_row(None, t_fail, t_suspect, m, f))
        qg = growth(quiet["ttd_first_median"],
                    base["lh_off_quiet"]["ttd_first_median"])
        for s in sizes:
            r = _slim(point_row(outage(s), t_fail, t_suspect, m, f))
            og = growth(r["ttd_first_median"],
                        base["lh_off_quiet"]["ttd_first_median"])
            absorbed = (
                r["false_positive_rate"] <= fpr_floor
                and r["verdict"] == "pass"
                and og is not None and og <= 1
                and qg is not None and qg <= 1
            )
            rows.append({
                "size": s, "lh_multiplier": m, "lh_frac": f,
                "outage": r, "quiet": quiet,
                "ttd_growth_outage": og, "ttd_growth_quiet": qg,
                "absorbed": absorbed,
            })
    return {
        "metric": "Lifeguard local-health knob surface vs correlated "
                  "outages (tensor engine, deterministic campaign runs)",
        "n": n, "t_fail": t_fail, "t_suspect": t_suspect,
        "baseline_t_fail": baseline_t_fail,
        "outage": {"length": length, "start": start},
        "rounds": rounds, "seed": seed, "track": track,
        "crash_at": crash_at,
        "fpr_floor": fpr_floor,
        "baselines": base,
        "rows": rows,
        "frontier": {
            str(s): [
                {"lh_multiplier": r["lh_multiplier"],
                 "lh_frac": r["lh_frac"]}
                for r in rows if r["size"] == s and r["absorbed"]
            ]
            for s in sizes
        },
    }
