"""Adversarial scenario campaigns: find the protocol's breaking points
automatically.

The ROADMAP's standing falsification item, industrialized: a campaign
drives the tensor-sim engine through a FAMILY of fault scenarios
(flapping duty cycles, loss rates, partition lengths, correlated-outage
sizes — ``driver.FAMILIES``), uses the streaming invariant monitor
(``obs/monitor.py``) as the per-run machine-checkable oracle, sweeps or
BISECTS the severity axis to the exact knee where an invariant breaks,
and commits each confirmed breaking point as a regression CASE file a
tier-1 test replays deterministically (``driver.run_case``).

``tools/campaign.py`` is the CLI; the ledger it writes is a
``gossipfs-obs/v1`` stream (header + ``campaign_verdict`` rows) so
``tools/timeline.py`` ingests it unchanged.
"""

from gossipfs_tpu.campaigns.engines import (
    run_case_engine,
    scale_case,
    verdict_agreement,
)
from gossipfs_tpu.campaigns.driver import (
    FAMILIES,
    CampaignLedger,
    bisect_axis,
    campaign_config,
    case_verdict_ok,
    knob_surface,
    load_case,
    make_scenario,
    run_case,
    run_scenario,
    run_traffic_case_doc,
    sweep_axis,
    write_case,
)

__all__ = [
    "FAMILIES",
    "CampaignLedger",
    "bisect_axis",
    "campaign_config",
    "case_verdict_ok",
    "knob_surface",
    "load_case",
    "make_scenario",
    "run_case",
    "run_case_engine",
    "run_scenario",
    "run_traffic_case_doc",
    "scale_case",
    "sweep_axis",
    "verdict_agreement",
    "write_case",
]
