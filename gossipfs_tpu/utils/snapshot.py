"""Async membership snapshots: read the sim while the scan keeps running.

SURVEY.md §7.4 ("async boundary"): the gRPC shim must be able to serve the
membership view without stalling a long device-resident scan.  Mechanism:
``SimDetector.advance_bulk(rounds, snapshot_every=k)`` splits the horizon
into k-round compiled scans and pipelines them from a background thread,
publishing a :class:`Snapshot` as each chunk completes.  The snapshot holds
the chunk-boundary device state (a *completed* array — never an in-flight
future) plus an eagerly-fetched ``alive`` vector; membership rows are read
lazily one observer at a time, so serving ``lsm`` costs one [N]-row
transfer, not an [N, N] pull.

Earlier rounds used an in-scan ``io_callback`` instead; host callbacks
cannot cross a remote-PJRT TPU tunnel (the callable lives on the wrong
side), so the chunked design replaces them with plain device reads —
tunnel-safe by construction.

The reference has no analog (every read walks the live Go structures, racy
by design — SURVEY §2.4); this is the simulator's equivalent of reading
`slave.MemberList` mid-run, made race-free by construction.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import cached_property

import numpy as np


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """One consistent point-in-time view of the whole cluster.

    ``state`` is the completed chunk-boundary ``SimState`` (device-resident;
    row reads transfer one [N] slice).  At most one snapshot's state is kept
    alive by a latest-wins buffer, so holding it does not accumulate HBM.
    """

    round: int
    alive: np.ndarray  # bool [N], fetched eagerly (small)
    state: "object"    # the completed chunk-boundary SimState

    def membership(self, node: int) -> list[int]:
        from gossipfs_tpu.core.state import MEMBER

        row = np.asarray(self.state.status[node])
        return np.nonzero(row == int(MEMBER))[0].tolist()

    @cached_property
    def status(self) -> np.ndarray:
        """Full [N, N] status matrix (one bulk transfer; prefer
        :meth:`membership` for single-observer reads)."""
        n = self.alive.shape[0]
        return np.asarray(self.state.status).reshape(n, n)


class SnapshotBuffer:
    """Latest-wins buffer written by the chunk pipeline, read by any thread."""

    def __init__(self, keep_history: bool = False):
        self._lock = threading.Lock()
        self._latest: Snapshot | None = None
        self._history: list[Snapshot] | None = [] if keep_history else None

    def push(self, snap: Snapshot) -> None:
        with self._lock:
            self._latest = snap
            if self._history is not None:
                self._history.append(snap)

    def clear(self) -> None:
        """Drop the latest view (and history) — called when a new bulk scan
        starts so stale rounds can't serve reads, and so the previous run's
        chunk states get released."""
        with self._lock:
            self._latest = None
            if self._history is not None:
                self._history = []

    def latest(self) -> Snapshot | None:
        with self._lock:
            return self._latest

    @property
    def history(self) -> list[Snapshot]:
        with self._lock:
            return list(self._history or [])
