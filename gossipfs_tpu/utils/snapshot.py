"""Async membership snapshots: read the sim while the scan keeps running.

SURVEY.md §7.4 ("async boundary"): the gRPC shim must be able to serve the
membership view without stalling a long device-resident scan.  Mechanism:
``run_rounds(..., snapshot=(buffer, every))`` plants a ``jax.experimental.
io_callback`` inside the scan that pushes (round, alive, status) to this
host-side buffer every ``every`` rounds.  Because jax dispatch is
asynchronous, the Python caller gets control back while the device scans;
any thread (e.g. the gRPC server) reads ``buffer.latest()`` for the
freshest view — no blocking ``device_get`` against in-flight futures.

The reference has no analog (every read walks the live Go structures, racy
by design — SURVEY §2.4); this is the simulator's equivalent of reading
`slave.MemberList` mid-run, made race-free by construction.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """One consistent point-in-time view of the whole cluster."""

    round: int
    alive: np.ndarray    # bool [N]
    status: np.ndarray   # int8 [N, N] — row i is node i's membership table

    def membership(self, node: int) -> list[int]:
        from gossipfs_tpu.core.state import MEMBER

        return np.nonzero(self.status[node] == int(MEMBER))[0].tolist()


class SnapshotBuffer:
    """Latest-wins buffer written by the in-scan callback, read by any thread."""

    def __init__(self, keep_history: bool = False):
        self._lock = threading.Lock()
        self._latest: Snapshot | None = None
        self._history: list[Snapshot] | None = [] if keep_history else None

    def push(self, round_, alive, status) -> None:
        """io_callback target — converts device payloads to host arrays.

        ``status`` may arrive in the scan's blocked 4-D layout; on the host
        it is plain C-order, so the [N, N] reshape is free.
        """
        alive = np.asarray(alive)
        n = alive.shape[0]
        snap = Snapshot(
            round=int(np.asarray(round_)),
            alive=alive,
            status=np.asarray(status).reshape(n, n),
        )
        with self._lock:
            self._latest = snap
            if self._history is not None:
                self._history.append(snap)

    def latest(self) -> Snapshot | None:
        with self._lock:
            return self._latest

    @property
    def history(self) -> list[Snapshot]:
        with self._lock:
            return list(self._history or [])
