"""Profiling — the observability the reference never had.

The reference's entire performance tooling is one wall-clock print in ``Get``
(reference: slave/slave.go:888-890).  Here: JAX profiler traces of the
compiled round program (open in Perfetto / TensorBoard) and a slope-based
round timer that is robust to fixed per-program dispatch overhead — on this
image the TPU is reached through a network tunnel whose per-call latency
dwarfs small kernels, so naive "time one call" numbers are garbage; timing
two scan lengths and fitting the slope isolates true per-round device time
(this is how the BASELINE kernel numbers were measured).
"""

from __future__ import annotations

import contextlib
import pathlib
import time
from typing import Iterator

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import SimState


@contextlib.contextmanager
def trace(log_dir: str | pathlib.Path) -> Iterator[None]:
    """``with trace("/tmp/trace"):`` — wraps jax.profiler.trace."""
    with jax.profiler.trace(str(log_dir)):
        yield


def op_breakdown(log_dir: str | pathlib.Path, top: int = 20) -> list[dict]:
    """Device-op time breakdown from a :func:`trace` capture.

    Parses the perfetto JSON the profiler writes, keeps the TPU process's
    complete events, and sums durations by op name.  This is the ground
    truth that guided every optimization round — it is how the per-round
    blocked-layout relayout cost (~35% of round time, invisible to
    wall-clock timing) was found.  Works through the axon tunnel, where
    naive timings do not (module docstring).

    Returns [{"name", "total_ms", "count"}] sorted by total, and prints a
    table when run as a script:

        python -m gossipfs_tpu.utils.profiling /tmp/trace
    """
    import collections
    import glob
    import gzip
    import json

    paths = sorted(
        glob.glob(str(pathlib.Path(log_dir) / "plugins/profile/*/*.trace.json.gz"))
    )
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {log_dir}")
    with gzip.open(paths[-1]) as f:
        tr = json.load(f)
    events = tr["traceEvents"]
    pids = {
        e["pid"]: e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    dev = {p for p, name in pids.items() if "TPU" in name or "GPU" in name}
    durs: dict[str, float] = collections.defaultdict(float)
    counts: dict[str, int] = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in dev:
            durs[e["name"]] += e.get("dur", 0)
            counts[e["name"]] += 1
    rows = [
        {"name": name, "total_ms": round(d / 1e3, 3), "count": counts[name]}
        for name, d in sorted(durs.items(), key=lambda kv: -kv[1])[:top]
    ]
    return rows


def time_rounds(
    state: SimState,
    config: SimConfig,
    key: jax.Array,
    *,
    short: int = 2,
    long: int = 10,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
) -> dict:
    """Slope-timed per-round cost: (T(long) - T(short)) / (long - short).

    Compiles both scan lengths first, then times one execution of each.
    Returns seconds per round and rounds/sec, free of dispatch overhead.
    """
    def run(k: int) -> float:
        out = run_rounds(
            state, config, k, key, crash_rate=crash_rate, rejoin_rate=rejoin_rate
        )
        jax.block_until_ready(out[0])
        t0 = time.perf_counter()
        out = run_rounds(
            state, config, k, key, crash_rate=crash_rate, rejoin_rate=rejoin_rate
        )
        jax.block_until_ready(out[0])
        return time.perf_counter() - t0

    t_short, t_long = run(short), run(long)
    per_round = max((t_long - t_short) / (long - short), 1e-9)
    return {
        "seconds_per_round": per_round,
        "rounds_per_sec": 1.0 / per_round,
        "dispatch_overhead_s": max(t_short - short * per_round, 0.0),
    }


if __name__ == "__main__":
    import sys

    for row in op_breakdown(sys.argv[1] if len(sys.argv) > 1 else "/tmp/trace"):
        print(f"{row['total_ms']:10.2f} ms  x{row['count']:<5d} {row['name'][:90]}")
