"""Profiling — the observability the reference never had.

The reference's entire performance tooling is one wall-clock print in ``Get``
(reference: slave/slave.go:888-890).  Here: JAX profiler traces of the
compiled round program (open in Perfetto / TensorBoard) and a slope-based
round timer that is robust to fixed per-program dispatch overhead — on this
image the TPU is reached through a network tunnel whose per-call latency
dwarfs small kernels, so naive "time one call" numbers are garbage; timing
two scan lengths and fitting the slope isolates true per-round device time
(this is how the BASELINE kernel numbers were measured).
"""

from __future__ import annotations

import contextlib
import pathlib
import time
from typing import Iterator

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import SimState


@contextlib.contextmanager
def trace(log_dir: str | pathlib.Path) -> Iterator[None]:
    """``with trace("/tmp/trace"):`` — wraps jax.profiler.trace."""
    with jax.profiler.trace(str(log_dir)):
        yield


def time_rounds(
    state: SimState,
    config: SimConfig,
    key: jax.Array,
    *,
    short: int = 2,
    long: int = 10,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
) -> dict:
    """Slope-timed per-round cost: (T(long) - T(short)) / (long - short).

    Compiles both scan lengths first, then times one execution of each.
    Returns seconds per round and rounds/sec, free of dispatch overhead.
    """
    def run(k: int) -> float:
        out = run_rounds(
            state, config, k, key, crash_rate=crash_rate, rejoin_rate=rejoin_rate
        )
        jax.block_until_ready(out[0])
        t0 = time.perf_counter()
        out = run_rounds(
            state, config, k, key, crash_rate=crash_rate, rejoin_rate=rejoin_rate
        )
        jax.block_until_ready(out[0])
        return time.perf_counter() - t0

    t_short, t_long = run(short), run(long)
    per_round = max((t_long - t_short) / (long - short), 1e-9)
    return {
        "seconds_per_round": per_round,
        "rounds_per_sec": 1.0 / per_round,
        "dispatch_overhead_s": max(t_short - short * per_round, 0.0),
    }
