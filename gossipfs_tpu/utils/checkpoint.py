"""Checkpoint/resume for long simulation runs.

The reference has no checkpointing: a crashed master reconstructs metadata
from surviving nodes' registries (``rebuild_file_meta``, reference:
slave/slave.go:986-1043) and file durability comes from 4-way replication.
The TPU build's sim state is a small closed pytree — ``SimState`` plus the
PRNG key — so long 100k-member runs (SURVEY §5) checkpoint trivially through
orbax, which also handles device-sharded arrays (the 100k state lives
column-sharded across the mesh; orbax saves each shard from its device).

Resume is exact: ``run_rounds`` derives every round's randomness by folding
the key with ``state.round`` (core/rounds.py), so a restored (state, key)
pair continues the identical trajectory — asserted by
tests/test_checkpoint.py.
"""

from __future__ import annotations

import pathlib

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp
from jax.sharding import Mesh

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import SimState


def save_checkpoint(
    path: str | pathlib.Path, state: SimState, key: jax.Array
) -> None:
    """Write (state, key) under ``path`` (a directory, created fresh).

    ``hb_floor`` records the storage dtype's floor-sentinel value (0 for
    absolute int32 storage, which has no sentinels) IN the payload, so
    restore never has to infer the saved era from best-effort metadata —
    re-encoding a missed sentinel as an ordinary counter would fabricate
    heartbeat values (the zombie corner the rebase excludes).
    """
    path = pathlib.Path(path).resolve()
    floor = (
        0 if state.hb.dtype == jnp.int32 else int(jnp.iinfo(state.hb.dtype).min)
    )
    payload = {
        "state": state._asdict(),
        "key": key,
        "hb_floor": jnp.asarray(floor, jnp.int32),
    }
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, payload, force=True)


def _abstract_like(config: SimConfig, mesh: Mesh | None) -> dict:
    n = config.n
    shardings = None
    if mesh is not None:
        from gossipfs_tpu.parallel.mesh import state_shardings

        shardings = state_shardings(mesh)

    def spec(shape, dtype, sh):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    sh = shardings or SimState(
        hb=None, age=None, status=None, alive=None, round=None, hb_base=None
    )
    hb_dtype = {"int32": jnp.int32, "int16": jnp.int16, "int8": jnp.int8}[
        config.hb_dtype
    ]
    state = SimState(
        hb=spec((n, n), hb_dtype, sh.hb),
        age=spec((n, n), jnp.int8, sh.age),
        status=spec((n, n), jnp.int8, sh.status),
        alive=spec((n,), jnp.bool_, sh.alive),
        round=spec((), jnp.int32, sh.round),
        hb_base=spec((n,), jnp.int32, sh.hb_base),
    )
    return {
        "state": state._asdict(),
        # the key rides replicated so it composes with sharded state args
        "key": spec((2,), jnp.uint32, sh.round),
    }


def restore_checkpoint(
    path: str | pathlib.Path, config: SimConfig, mesh: Mesh | None = None
) -> tuple[SimState, jax.Array]:
    """Load (state, key) saved by ``save_checkpoint`` for this config's N.

    Pass the run's ``mesh`` to restore every array directly onto its run
    sharding ([N, N] tables column-sharded, vectors + key replicated) —
    without it, orbax commits everything to one device and mixing the result
    with mesh-sharded arrays in a jitted call is an error.
    """
    from gossipfs_tpu.config import AGE_CLAMP, REBASE_WINDOW

    path = pathlib.Path(path).resolve()
    abstract = _abstract_like(config, mesh)
    # Restore age and hb as int32 regardless of the saved dtype: orbax
    # silently casts to the target, so an int32 target is lossless for every
    # era (int8/int16 narrow lanes and legacy wide ones) — whereas a narrow
    # target would wrap out-of-range legacy values with no error.  Clamp /
    # renormalize + narrow afterwards.
    for lane in ("age", "hb"):
        spec = abstract["state"][lane]
        abstract["state"][lane] = jax.ShapeDtypeStruct(
            spec.shape, jnp.int32, sharding=spec.sharding
        )
    with ocp.StandardCheckpointer() as ckptr:
        def restore_legacy():
            # checkpoint predates the hb_base lane (int32-only era)
            legacy = {
                "state": {k: v for k, v in abstract["state"].items() if k != "hb_base"},
                "key": abstract["key"],
            }
            out = ckptr.restore(path, legacy)
            zeros = jnp.zeros((config.n,), dtype=jnp.int32)
            hb_sh = abstract["state"]["hb_base"].sharding
            if hb_sh is not None:
                zeros = jax.device_put(zeros, hb_sh)
            out["state"]["hb_base"] = zeros
            return out

        legacy_no_base = False
        has_floor = False
        probed_min: int | None = None
        try:
            meta = ckptr.metadata(path)
            tree = meta.item_metadata if hasattr(meta, "item_metadata") else meta
            tree = getattr(tree, "tree", tree)
            legacy_no_base = "hb_base" not in tree["state"]
            has_floor = "hb_floor" in tree
            probed_min = int(jnp.iinfo(tree["state"]["hb"].dtype).min)
        except Exception:
            pass  # metadata probe is best-effort; the payload field and the
            #       loud check below make sentinel decoding never guess
        if has_floor:
            abstract = dict(abstract)
            abstract["hb_floor"] = jax.ShapeDtypeStruct((), jnp.int32)
        if legacy_no_base:
            restored = restore_legacy()
        else:
            try:
                restored = ckptr.restore(path, abstract)
            except Exception:
                # probe said (or failed to say) the base lane exists but the
                # structured restore disagreed — one legacy retry, so a real
                # corruption error surfaces from a consistent code path
                restored = restore_legacy()
    restored["state"]["age"] = jnp.clip(
        restored["state"]["age"], 0, AGE_CLAMP
    ).astype(jnp.int8)
    # hb migration between storage modes: whatever era the checkpoint is
    # from, the true counter is stored_hb + hb_base[subject] (all-zero base
    # for absolute int32 storage), so reconstruct and re-encode for the
    # requested mode.  Counters above int16 range renormalize against a
    # fresh base instead of silently wrapping.
    true_hb = restored["state"]["hb"] + restored["state"]["hb_base"][None, :]
    # Floor sentinels from narrow-era checkpoints (stored == the saved
    # dtype's minimum under a positive base) carry NO counter value —
    # decoding them as ordinary counters would fabricate heartbeats
    # (suppressing detection for that lane).  Identify them up front for
    # BOTH re-encode targets; the floor value comes from the checkpoint
    # payload itself (save_checkpoint's ``hb_floor``) or, for pre-hb_floor
    # checkpoints, the metadata probe above.  A provably narrow-era
    # checkpoint with no identifiable floor is refused loudly.
    if has_floor:
        saved_min = int(restored.pop("hb_floor"))
        saved_min = saved_min if saved_min != 0 else None
    else:
        saved_min = probed_min
    narrow_era = bool(jnp.any(restored["state"]["hb_base"] > 0))
    if narrow_era and saved_min is None:
        raise ValueError(
            f"checkpoint at {path} uses narrow (rebased) heartbeat "
            "storage but carries no hb_floor field and its metadata "
            "dtype could not be read — cannot identify floor sentinels; "
            "refusing to fabricate counters"
        )
    if saved_min is None:  # absolute int32-era storage: no sentinels
        sentinel = jnp.zeros(restored["state"]["hb"].shape, dtype=bool)
    else:
        sentinel = (restored["state"]["hb"] == saved_min) & (
            restored["state"]["hb_base"][None, :] > 0
        )
    if config.hb_dtype != "int32":
        # Anchor the restore base exactly like the in-round rebase
        # (core/rounds._pre_tick): on the subject's own DIAGONAL counter —
        # the only legitimate maximum of the current incarnation.  Zombie
        # lanes above it re-encode at the int16 ceiling (out of gossip via
        # the view window clamp, still detectable) and neither they nor the
        # base can mute a rejoin.  Floor sentinels from int16-era
        # checkpoints (stored == -32768 under a positive base: unknown
        # counters, not values) stay sentinels — re-encoding them against a
        # LOWER base would otherwise fabricate ordinary counters.
        from gossipfs_tpu.config import INT8_REBASE_WINDOW

        tgt = jnp.int16 if config.hb_dtype == "int16" else jnp.int8
        info = jnp.iinfo(tgt)
        window = REBASE_WINDOW if config.hb_dtype == "int16" else INT8_REBASE_WINDOW
        n_ck = true_hb.shape[0]
        diag = true_hb[jnp.arange(n_ck), jnp.arange(n_ck)]
        new_base = jnp.maximum(diag + 1 - window, 0)
        restored["state"]["hb"] = jnp.where(
            sentinel,
            jnp.asarray(info.min, tgt),
            jnp.clip(true_hb - new_base[None, :], info.min, info.max).astype(tgt),
        )
        restored["state"]["hb_base"] = new_base
    else:
        # int32 target: sentinels have no storage-floor representation, so
        # quarantine them FAR above any reachable counter (rounds are the
        # only source of increments, so legitimate counters stay tiny).
        # The view rebase clamp excludes values more than a window above
        # the subject's diagonal from gossip, so quarantined lanes never
        # spread, age out at their holders, and stay detectable — exactly
        # the narrow modes' zombie semantics.
        restored["state"]["hb"] = jnp.where(
            sentinel, jnp.int32(2 ** 30), true_hb
        )
        restored["state"]["hb_base"] = jnp.zeros_like(restored["state"]["hb_base"])
    return SimState(**restored["state"]), restored["key"]
