"""Checkpoint/resume for long simulation runs.

The reference has no checkpointing: a crashed master reconstructs metadata
from surviving nodes' registries (``rebuild_file_meta``, reference:
slave/slave.go:986-1043) and file durability comes from 4-way replication.
The TPU build's sim state is a small closed pytree — ``SimState`` plus the
PRNG key — so long 100k-member runs (SURVEY §5) checkpoint trivially through
orbax, which also handles device-sharded arrays (the 100k state lives
column-sharded across the mesh; orbax saves each shard from its device).

Resume is exact: ``run_rounds`` derives every round's randomness by folding
the key with ``state.round`` (core/rounds.py), so a restored (state, key)
pair continues the identical trajectory — asserted by
tests/test_checkpoint.py.
"""

from __future__ import annotations

import pathlib

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp
from jax.sharding import Mesh

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import SimState


def save_checkpoint(
    path: str | pathlib.Path, state: SimState, key: jax.Array
) -> None:
    """Write (state, key) under ``path`` (a directory, created fresh)."""
    path = pathlib.Path(path).resolve()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"state": state._asdict(), "key": key}, force=True)


def _abstract_like(config: SimConfig, mesh: Mesh | None) -> dict:
    n = config.n
    shardings = None
    if mesh is not None:
        from gossipfs_tpu.parallel.mesh import state_shardings

        shardings = state_shardings(mesh)

    def spec(shape, dtype, sh):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    sh = shardings or SimState(hb=None, age=None, status=None, alive=None, round=None)
    state = SimState(
        hb=spec((n, n), jnp.int32, sh.hb),
        age=spec((n, n), jnp.int8, sh.age),
        status=spec((n, n), jnp.int8, sh.status),
        alive=spec((n,), jnp.bool_, sh.alive),
        round=spec((), jnp.int32, sh.round),
    )
    return {
        "state": state._asdict(),
        # the key rides replicated so it composes with sharded state args
        "key": spec((2,), jnp.uint32, sh.round),
    }


def restore_checkpoint(
    path: str | pathlib.Path, config: SimConfig, mesh: Mesh | None = None
) -> tuple[SimState, jax.Array]:
    """Load (state, key) saved by ``save_checkpoint`` for this config's N.

    Pass the run's ``mesh`` to restore every array directly onto its run
    sharding ([N, N] tables column-sharded, vectors + key replicated) —
    without it, orbax commits everything to one device and mixing the result
    with mesh-sharded arrays in a jitted call is an error.
    """
    from gossipfs_tpu.config import AGE_CLAMP

    path = pathlib.Path(path).resolve()
    abstract = _abstract_like(config, mesh)
    # Restore age as int32 regardless of the saved dtype: orbax silently
    # casts to the target, so an int32 target is lossless for both the new
    # int8 lane and legacy (pre-int8, unclamped) checkpoints — whereas an
    # int8 target would wrap legacy ages > 127 into negatives with no error.
    # Clamp + narrow afterwards; beyond AGE_CLAMP all ages behave identically
    # (config.py), so the clamp is a no-op for new-format checkpoints.
    new_age = abstract["state"]["age"]
    abstract["state"]["age"] = jax.ShapeDtypeStruct(
        new_age.shape, jnp.int32, sharding=new_age.sharding
    )
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)
    restored["state"]["age"] = jnp.clip(
        restored["state"]["age"], 0, AGE_CLAMP
    ).astype(jnp.int8)
    return SimState(**restored["state"]), restored["key"]
