"""Structured event log + grep — the observability layer.

Reference: every significant event appends a text line to ``Machine.log``
(reopening the file per call — logger/logger.go:28-44), and the distributed
grep RPC searches it (``TCPServer.Response``, server/server.go:55-72; the
report's stated test methodology).  Here events are structured (kind + round +
attributes) with a text rendering, the file handle stays open, and grep is a
method.  The sim emits the same event kinds the Go cluster logs, so log-grep
assertions port over.
"""

from __future__ import annotations

import json
import pathlib
import re


class EventLog:
    """Append-only structured log, in-memory with optional file mirroring."""

    def __init__(self, path: str | pathlib.Path | None = None):
        self.entries: list[dict] = []
        self._fh = open(path, "a", encoding="utf-8") if path is not None else None

    def write(self, message: str, **fields) -> None:
        entry = {"message": message, **fields}
        self.entries.append(entry)
        if self._fh is not None:
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()

    def grep(self, pattern: str) -> list[dict]:
        """Regex search over rendered messages (the MP1 remote-grep verb)."""
        rx = re.compile(pattern)
        return [e for e in self.entries if rx.search(e["message"])]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
