"""Structured event log + grep — the observability layer.

Reference: every MACHINE appends its own text lines to a local
``Machine.log`` (reopening the file per call — logger/logger.go:28-44), and
the distributed grep RPC searches each machine's log separately
(``TCPServer.Response``, server/server.go:55-72; the report's stated test
methodology greps ACROSS machines and compares what each observer saw).
Here events are structured (kind + round + attributes) with a text
rendering, the file handle stays open, and grep is a method.  The node
dimension survives: every entry carries the ``node`` that would have
written it to its own Machine.log (the detecting observer, the
re-replication source, the election winner, the put-handling master), so
:meth:`grep` with a node filter is the analog of grepping that one
machine's log, and :meth:`node_view` is the analog of reading it.  The sim
emits the same event kinds the Go cluster logs, so log-grep assertions
port over.
"""

from __future__ import annotations

import json
import pathlib
import re


class EventLog:
    """Append-only structured log, in-memory with optional file mirroring."""

    def __init__(self, path: str | pathlib.Path | None = None):
        self.entries: list[dict] = []
        self._fh = open(path, "a", encoding="utf-8") if path is not None else None

    def write(self, message: str, **fields) -> None:
        """Append an entry; ``node=<id>`` names the machine whose local log
        the reference would have written this line to."""
        entry = {"message": message, **fields}
        self.entries.append(entry)
        if self._fh is not None:
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()

    def grep(self, pattern: str, node: int | None = None) -> list[dict]:
        """Regex search over rendered messages (the MP1 remote-grep verb).

        ``node`` restricts the search to that machine's own log view — the
        reference's per-machine grep (server.go:55-72); None searches the
        whole cluster's stream.
        """
        rx = re.compile(pattern)
        return [
            e for e in self.entries
            if rx.search(e["message"])
            and (node is None or e.get("node") == node)
        ]

    def node_view(self, node: int) -> list[dict]:
        """Everything machine ``node`` wrote — its Machine.log, read back."""
        return [e for e in self.entries if e.get("node") == node]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
