"""SDFS-under-load benchmark — the TRAFFIC_r12.json artifact.

Two lanes, one document:

* **cosim lane** (full fidelity, CPU-pinned, small-N): the open-loop
  workload (``traffic/workload.py``) against the interactive CoSim —
  steady state, churn, writes racing a timed partition, and a rack-kill
  repair storm under a per-round repair budget.  Every run is
  flight-recorded; the document embeds BOTH durability accountings
  (harness ledger vs event replay, ``traffic/audit.py``) and their
  exact-match verdict — ``tools/verify_claims.py traffic_durability``
  re-runs the partition-race command and requires the match.

* **scale lane** (the >=100k-member requirement): the TENSORIZED planner
  (``traffic/planner.py``) drives placement + budgeted repair planning
  against evolving [N] alive masks at N=100,000+ — thousands of
  placements per round and the whole repair diff as one masked top-k,
  with steady/churn/partition/rack-storm mask schedules and measured
  wall-time per planning round.  No per-file Python anywhere in the
  per-round path.

    JAX_PLATFORMS=cpu python -m gossipfs_tpu.bench.traffic_bench --all \
        --out TRAFFIC_r12.json
    JAX_PLATFORMS=cpu python -m gossipfs_tpu.bench.traffic_bench \
        --partition-race --n 64 --trace /tmp/traffic.jsonl

Round 18 adds the ERASURE lane (``--erasure-matrix`` — the
ERASURE_r18.json artifact): the same four cosim scenarios in
``redundancy="stripe"`` mode (k data + m parity Reed-Solomon fragments,
gossipfs_tpu/erasure/) plus a replica-mode repair-storm twin at the
SAME failure schedule, so the document carries the measured
stripe-vs-replica repair-bandwidth ratio next to the durability
verdicts.  Every cosim row is redundancy-self-describing.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

from gossipfs_tpu.sdfs.types import STRIPE_K, STRIPE_M
from gossipfs_tpu.traffic.workload import WorkloadSpec


def default_spec(rate: float = 8.0, n_keys: int = 96, seed: int = 0,
                 redundancy: str = "replica", stripe_k: int = STRIPE_K,
                 stripe_m: int = STRIPE_M) -> WorkloadSpec:
    """The bench mix: 30% puts / 2% deletes / 68% gets, Zipf keys, the
    reference-shard size distribution with capped materialized bytes."""
    return WorkloadSpec(rate=rate, n_keys=n_keys, seed=seed,
                        redundancy=redundancy, stripe_k=stripe_k,
                        stripe_m=stripe_m)


# ---------------------------------------------------------------------------
# cosim lane
# ---------------------------------------------------------------------------


def cosim_lane(n: int, rounds: int, rate: float, seed: int,
               trace: str | None = None, only: str | None = None,
               redundancy: str = "replica", stripe_k: int = STRIPE_K,
               stripe_m: int = STRIPE_M) -> dict:
    from gossipfs_tpu.traffic import harness

    spec = default_spec(rate=rate, seed=seed, redundancy=redundancy,
                        stripe_k=stripe_k, stripe_m=stripe_m)
    out: dict = {}
    # single-run flags write PATH itself; --all suffixes per run
    t = lambda name: (  # noqa: E731
        (trace if only else f"{trace}.{name}") if trace else None)
    if only in (None, "steady"):
        out["steady"] = harness.steady_state(
            n, rounds, spec, seed=seed, trace=t("steady"))
    if only in (None, "churn"):
        out["churn"] = harness.churn(
            n, rounds, spec, seed=seed, trace=t("churn"))
    if only in (None, "partition_race"):
        out["partition_race"] = harness.partition_race(
            n, spec, seed=seed, trace=t("partition"))
    if only in (None, "repair_storm"):
        out["repair_storm"] = harness.repair_storm(
            n, spec, files=max(96, n * 2), rack=(n // 4, max(4, n // 8)),
            repair_budget=8, seed=seed, trace=t("storm"))
    for row in out.values():
        # artifact rows self-describe their redundancy mode
        row["redundancy"] = spec.redundancy
        if spec.redundancy == "stripe":
            row["stripe_k"], row["stripe_m"] = spec.stripe_k, spec.stripe_m
    return out


def erasure_matrix(n: int, rounds: int, rate: float, seed: int,
                   trace: str | None = None, stripe_k: int = STRIPE_K,
                   stripe_m: int = STRIPE_M) -> dict:
    """The ERASURE_r18 lane: the whole gray-failure scenario matrix in
    stripe mode, plus a replica repair-storm twin at the SAME failure
    schedule (same seed, same victim set — the master/introducer never
    dies in these scenarios, so the schedules coincide exactly) for the
    repair-bandwidth comparison."""
    from gossipfs_tpu.traffic import harness

    doc = cosim_lane(n, rounds, rate, seed, trace=trace,
                     redundancy="stripe", stripe_k=stripe_k,
                     stripe_m=stripe_m)
    doc["redundancy"] = "stripe"
    doc["stripe_k"], doc["stripe_m"] = stripe_k, stripe_m
    rspec = default_spec(rate=rate, seed=seed)
    twin = harness.repair_storm(
        n, rspec, files=max(96, n * 2), rack=(n // 4, max(4, n // 8)),
        repair_budget=8, seed=seed)
    twin["redundancy"] = "replica"
    doc["replica_storm_twin"] = twin
    sb = doc["repair_storm"]["repair_bytes_written"]
    sc = doc["repair_storm"]["repair_copies"]
    rb = twin["repair_bytes_written"]
    rc = twin["repair_copies"]
    doc["repair_bandwidth"] = {
        "stripe_bytes": sb,
        "stripe_units": sc,
        "replica_bytes": rb,
        "replica_units": rc,
        # bytes written per unit of lost redundancy repaired — the
        # ~k-fold erasure saving (a lost fragment re-encodes ceil(S/k)
        # row bytes where a lost replica re-copies all S) and what the
        # verify_claims.py erasure_durability claim pins against 1/k
        "per_unit_ratio": (round((sb / sc) / (rb / rc), 4)
                           if sc and rc and rb else None),
        # total traffic at the same failure schedule, reported honestly
        # but NOT the 1/k claim: the (k+m)-wide stripe exposes more
        # units to the same rack kill than R=4 replicas, so totals
        # scale by (k+m)/(R*k) — 0.375 at (4,2) vs the reference's R=4
        "total_ratio": round(sb / rb, 4) if rb else None,
        "bound_1_over_k": round(1.0 / stripe_k, 4),
    }
    scenarios = ("steady", "churn", "partition_race", "repair_storm")
    doc["losses_total"] = sum(
        doc[s]["durability"]["harness"]["lost"] for s in scenarios)
    doc["matches_all"] = all(
        doc[s]["durability"]["match"]
        and doc[s]["durability"]["monitor"]["match_events"]
        for s in scenarios)
    return doc


# ---------------------------------------------------------------------------
# scale lane: the tensorized planner at >= 100k members
# ---------------------------------------------------------------------------


def scale_lane(n: int = 100_000, files_per_round: int = 2048,
               rounds: int = 24, budget: int = 4096,
               churn_rate: float = 0.01, seed: int = 0) -> dict:
    """Placement + repair planning over live [N] masks at traffic scale.

    The mask schedule packs all four regimes into one run: steady
    placement, then 1%-per-round crash churn, then a half/half
    reachability partition window (acked-write accounting vs the WRITE
    quorum — imported, not re-derived), then a rack-sized correlated
    kill whose deficit drains at ``budget`` repairs per round.  The
    detector's view is modeled as ground truth delayed by t_fail rounds
    (the gossip layer's detection latency); at 100k members the real
    detector runs on the TPU lane (bench/frontier.py), and this lane
    consumes the same [N] mask shape it produces.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossipfs_tpu.traffic.planner import ReplicaTable

    t_fail = 5
    rng = np.random.default_rng(seed)
    capacity = files_per_round * rounds + 8
    table = ReplicaTable(capacity, n, seed=seed)
    alive_h = np.ones(n, dtype=bool)
    history = [alive_h.copy()]

    rack_lo, rack_size = n // 2, max(n // 100, 64)
    churn_start = rounds // 4
    part_start, part_end = rounds // 2, rounds // 2 + rounds // 6
    rack_round = (3 * rounds) // 4

    rows = []
    total_placed = 0
    backlog = 0
    for r in range(rounds):
        # ground-truth fault schedule
        if r >= churn_start:
            kill = rng.random(n) < churn_rate
            alive_h &= ~kill
        if r == rack_round:
            alive_h[rack_lo:rack_lo + rack_size] = False
        history.append(alive_h.copy())
        # the planner consumes the DETECTED view (t_fail rounds stale)
        view_h = history[max(0, len(history) - 1 - t_fail)]
        # reachability: ground truth, partition-confined in the window
        reach_h = alive_h.copy()
        partition_active = part_start <= r < part_end
        if partition_active:
            reach_h[n // 2:] = False  # master's side = [0, n/2)
        alive = jnp.asarray(view_h)  # the planner's (detection-lagged) view
        reach = jnp.asarray(reach_h)

        t0 = time.perf_counter()
        placed_rows = table.place(reach if partition_active else alive,
                                  files_per_round, method="sampled")
        pass_stats = table.plan_and_commit(alive, reach, budget)
        stats = table.stats(jnp.asarray(alive_h), reach)
        jax.block_until_ready(table.replicas)
        ms = (time.perf_counter() - t0) * 1e3
        total_placed += int((np.asarray(placed_rows) >= 0).all(axis=1).sum())
        backlog = pass_stats["repairs_pending"]
        rows.append({
            "round": r,
            "n_alive": int(alive_h.sum()),
            "phase": ("partition" if partition_active else
                      "rack_storm" if r >= rack_round else
                      "churn" if r >= churn_start else "steady"),
            "planner_ms": round(ms, 2),
            "files": table.n_files,
            **pass_stats,
            "write_quorum_reachable": stats["write_quorum_reachable"],
            "replica_histogram": stats["replica_histogram"],
        })

    # drain the rack storm's remaining backlog at budget/round
    drain_rounds = 0
    alive = jnp.asarray(alive_h)
    while backlog > 0 and drain_rounds < 64:
        pass_stats = table.plan_and_commit(alive, alive, budget)
        backlog = pass_stats["repairs_pending"]
        drain_rounds += 1
    final = table.stats(alive, alive)
    per_round_ms = [row["planner_ms"] for row in rows[1:]]  # row 0 compiles
    return {
        "metric": "tensorized placement/repair planning vs [N] alive masks",
        "n": n,
        "files_per_round": files_per_round,
        "rounds": rounds,
        "repair_budget": budget,
        "placed_total": total_placed,
        "planner_ms_median": round(sorted(per_round_ms)[
            len(per_round_ms) // 2], 2) if per_round_ms else None,
        "placements_per_sec": round(
            files_per_round * 1e3 / (sorted(per_round_ms)[
                len(per_round_ms) // 2]), 1) if per_round_ms else None,
        "storm_drain_rounds_post_run": drain_rounds,
        "final": final,
        "rows": rows,
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=64,
                   help="cosim-lane member count (CPU-pinned)")
    p.add_argument("--rounds", type=int, default=24)
    p.add_argument("--rate", type=float, default=8.0,
                   help="open-loop ops per round")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steady", action="store_true")
    p.add_argument("--churn", action="store_true")
    p.add_argument("--partition-race", action="store_true")
    p.add_argument("--repair-storm", action="store_true")
    p.add_argument("--redundancy", choices=("replica", "stripe"),
                   default="replica",
                   help="cosim-lane byte plane: 4 full replicas or k+m "
                        "Reed-Solomon fragments (gossipfs_tpu/erasure/)")
    p.add_argument("--stripe-k", type=int, default=STRIPE_K)
    p.add_argument("--stripe-m", type=int, default=STRIPE_M)
    p.add_argument("--erasure-matrix", action="store_true",
                   help="the ERASURE_r18 lane: all four cosim scenarios "
                        "in stripe mode + a replica repair-storm twin at "
                        "the same failure schedule (bandwidth ratio)")
    p.add_argument("--scale", action="store_true",
                   help="the tensorized-planner lane at --scale-n members")
    p.add_argument("--scale-n", type=int, default=100_000)
    p.add_argument("--scale-files", type=int, default=2048,
                   help="placements per round in the scale lane")
    p.add_argument("--scale-budget", type=int, default=4096)
    p.add_argument("--all", action="store_true",
                   help="all four cosim runs + the scale lane")
    p.add_argument("--trace", type=str, default=None, metavar="PATH",
                   help="flight-recorder stream(s); single-run flags "
                        "write PATH itself, --all writes PATH.<run>")
    p.add_argument("--out", type=str, default=None)
    args = p.parse_args(argv)

    picked = [k for k, v in (("steady", args.steady),
                             ("churn", args.churn),
                             ("partition_race", args.partition_race),
                             ("repair_storm", args.repair_storm)) if v]
    doc: dict = {
        "metric": "SDFS plane under open-loop load "
                  "(throughput, quorum latency, durability)",
        "workload": {
            "mix": "put 0.30 / delete 0.02 / get 0.68",
            "popularity": "zipf(1.1)",
            "sizes": "reference-shard magnitudes (64 KB..4 MB logical; "
                     "materialized bytes capped — BASELINE.md boundary)",
        },
    }
    red = dict(redundancy=args.redundancy, stripe_k=args.stripe_k,
               stripe_m=args.stripe_m)
    if args.erasure_matrix:
        doc["erasure_matrix"] = erasure_matrix(
            args.n, args.rounds, args.rate, args.seed, trace=args.trace,
            stripe_k=args.stripe_k, stripe_m=args.stripe_m)
    elif args.all or not (picked or args.scale):
        doc.update(cosim_lane(args.n, args.rounds, args.rate, args.seed,
                              trace=args.trace, **red))
        doc["scale"] = scale_lane(args.scale_n, args.scale_files,
                                  budget=args.scale_budget, seed=args.seed)
    else:
        for name in picked:
            doc.update(cosim_lane(args.n, args.rounds, args.rate, args.seed,
                                  trace=args.trace, only=name, **red))
        if args.scale:
            doc["scale"] = scale_lane(args.scale_n, args.scale_files,
                                      budget=args.scale_budget,
                                      seed=args.seed)
    out = json.dumps(doc)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    sys.exit(main())
