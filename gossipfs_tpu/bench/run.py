"""Benchmark runners for the five BASELINE.json configurations.

The reference's entire benchmark apparatus is one wall-clock print in ``Get``
(reference: slave/slave.go:888-890); BASELINE.json replaces it with the
north-star metrics: simulated gossip rounds/sec plus time-to-detect and
false-positive-rate curves.  This module turns a ``models.presets.Scenario``
into those numbers:

  python -m gossipfs_tpu.bench.run --scenario sim-1k
  python -m gossipfs_tpu.bench.run --scenario sim-10k-crash --n 2048 --rounds 60

Each run injects a handful of *tracked* deterministic crashes (the sim's
CTRL+C, reference: README.md:30) on top of the scenario's random churn so the
time-to-detect distribution is measured against known crash rounds, times the
compiled scan, and reports one JSON document.

Config 5 (``sim-100k-sdfs``) additionally drives the SDFS control plane off
the simulated membership (the slave.go:478 seam) at the reference's recovery
cadence: the detector advances in RECOVERY_DELAY-round chunks (8 rounds =
the sleep in Fail_recover, slave.go:1123), and between chunks the master's own
membership row feeds placement + repair planning — the co-sim equivalent of
`detect -> wait 8 heartbeats -> Get_Update_Meta -> Re_put`.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import MetricsCarry, RoundMetrics, run_rounds
from gossipfs_tpu.core.state import MEMBER, RoundEvents, SimState, init_state
from gossipfs_tpu.metrics.detection import summarize
from gossipfs_tpu.models import presets
from gossipfs_tpu.sdfs.cluster import SDFSCluster
from gossipfs_tpu.sdfs.types import RECOVERY_DELAY


def tracked_victims(n: int, track: int, introducer: int = 0,
                    n_live: int | None = None) -> list[int]:
    """The tracked-crash victim ids — the ONE derivation every engine's
    probe schedule shares (the tensor scan here, the socket campaign
    runners in campaigns/engines.py): ``track`` nodes spread evenly
    across the live id space, skipping the introducer."""
    live = n if n_live is None else n_live
    track = min(track, live - 1)
    stride = max(live // (track + 1), 1)
    nodes = [(introducer + (k + 1) * stride) % live for k in range(track)]
    return sorted({x for x in nodes if x != introducer})


def tracked_crash_events(
    cfg: SimConfig, rounds: int, track: int, at: int, n_live: int | None = None
) -> tuple[RoundEvents, dict[int, int], jnp.ndarray]:
    """Schedule ``track`` deterministic crashes at round ``at``.

    Nodes are spread evenly across the id space, skipping the introducer
    (crashing it would also sever rejoins, slave.go:22 SPOF).  Returns the
    stacked [rounds, N] event arrays, {node: crash_round} for the
    detection-latency report, and a ``churn_ok`` mask excluding the tracked
    nodes from random churn — a random rejoin would reset their
    detection/convergence carry mid-measurement (core/rounds._update_carry).

    ``n_live``: effective cohort for PADDED configs (the literal-N support,
    bench/frontier.py): tracked crashes spread over [0, n_live) only and
    the churn mask additionally excludes the permanently-dead pad ids past
    it — a random rejoin would otherwise resurrect a pad into the cohort.
    """
    n = cfg.n
    nodes = tracked_victims(n, track, cfg.introducer, n_live=n_live)
    crash = np.zeros((rounds, n), dtype=bool)
    at = min(at, rounds - 1)
    crash[at, nodes] = True
    zeros = jnp.zeros((rounds, n), dtype=bool)
    events = RoundEvents(crash=jnp.asarray(crash), leave=zeros, join=zeros)
    churn_ok = np.ones((n,), dtype=bool)
    churn_ok[nodes] = False
    # the introducer is exempt from RANDOM churn: joins die with it
    # (slave.go:22 SPOF, kept by design), so introducer-inclusive churn
    # collapses the population to ~zero and trivializes the scenario —
    # model the reference's "introducer VM stays up" deployment instead
    churn_ok[cfg.introducer] = False
    if n_live is not None:
        churn_ok[n_live:] = False
    return events, {node: at for node in nodes}, jnp.asarray(churn_ok)


def _runner(cfg: SimConfig, mesh):
    """run_rounds, or the shard_map variant on a real multi-device mesh.

    The pallas merge kernel has no GSPMD partitioning rule (plain jit
    would all-gather the full state around it every round), so sharded
    random-topology runs go through parallel.mesh.run_rounds_sharded.
    """
    if mesh is None or mesh.devices.size <= 1 or cfg.topology == "ring":
        return run_rounds
    from gossipfs_tpu.parallel.mesh import run_rounds_sharded

    return lambda state, cfg, rounds, key, **kw: run_rounds_sharded(
        state, cfg, rounds, key, mesh, **kw
    )


def _timed_run(
    state: SimState,
    cfg: SimConfig,
    rounds: int,
    key: jax.Array,
    events: RoundEvents,
    sc: presets.Scenario,
    churn_ok: jax.Array | None = None,
    mesh=None,
) -> tuple[SimState, MetricsCarry, RoundMetrics, float]:
    """Compile (warmup) then time one full scan; returns outputs + seconds."""
    runner = _runner(cfg, mesh)
    run = lambda: runner(
        state,
        cfg,
        rounds,
        key,
        events=events,
        crash_rate=sc.crash_rate,
        rejoin_rate=sc.rejoin_rate,
        churn_ok=churn_ok,
        # tracked_crash_events schedules crashes only: keep the lean event
        # path (no leave/join rewrites, no fail-matrix materialization)
        crash_only_events=True,
    )
    jax.block_until_ready(run())  # compile + warm caches
    t0 = time.perf_counter()
    final, carry, per_round = run()
    jax.block_until_ready(final)
    return final, carry, per_round, time.perf_counter() - t0


def run_cosim(
    sc: presets.Scenario,
    cfg: SimConfig,
    rounds: int,
    seed: int,
    mesh=None,
) -> dict:
    """Config-5 co-sim: SDFS control plane consuming the sim membership.

    Uses chunked advancement (one ``run_rounds`` scan per RECOVERY_DELAY
    rounds) instead of the interactive per-round ``CoSim.tick`` so the TPU
    never stalls on per-round host sync; the control plane reacts exactly at
    the cadence the reference does (8 heartbeats after detection,
    slave.go:1123).
    """
    from gossipfs_tpu.cosim import select_observer

    @jax.jit
    def membership_packet(state: SimState, observer) -> jnp.ndarray:
        """alive mask + observer's membership row as ONE device array, so
        each control-plane reaction costs a single host transfer (the
        per-chunk tunnel round-trips were a config-5 bottleneck)."""
        return jnp.concatenate(
            [state.alive, state.status[observer] == MEMBER]
        )

    cluster = SDFSCluster(cfg.n, seed=seed, introducer=cfg.introducer)
    for f in range(sc.n_files):
        cluster.put(f"file{f}.txt", b"payload-%d" % f, now=0)
    state = init_state(cfg)
    if mesh is not None:
        from gossipfs_tpu.parallel.mesh import shard_state

        state = shard_state(state, mesh)
    key = jax.random.PRNGKey(seed)
    # random churn spares the introducer (see tracked_crash_events): with it
    # dead no rejoin can ever land and the population decays to nothing
    churn_ok = jnp.asarray(
        np.arange(cfg.n) != cfg.introducer
    )
    # equal-size chunks only: num_rounds is a static jit arg on run_rounds, so
    # a ragged final chunk would trigger a second full XLA compilation
    chunk = RECOVERY_DELAY
    n_chunks = max(1, -(-rounds // chunk))
    repairs = 0
    elections = 0
    done = 0
    alive: list[int] = []
    runner = _runner(cfg, mesh)
    run_chunk = lambda st: runner(  # noqa: E731
        st, cfg, chunk, key, crash_rate=sc.crash_rate,
        rejoin_rate=sc.rejoin_rate, churn_ok=churn_ok,
    )[0]
    # warm up the chunk kernel AND the packet fetch so compile time stays
    # out of the timed region
    jax.block_until_ready(run_chunk(state))
    jax.block_until_ready(membership_packet(state, cluster.master_node))
    n = cfg.n

    def react(packet: np.ndarray, now: int, state, fetched_for: int) -> bool:
        """One control-plane reaction off a resolved membership packet
        (whose row was prefetched for observer ``fetched_for``).
        Returns False when the cluster is empty (stop)."""
        nonlocal repairs, elections, alive
        alive_mask, row = packet[:n], packet[n:]
        alive = np.nonzero(alive_mask)[0].tolist()
        if not alive:
            # feed the empty membership so the closing durability check
            # can't satisfy quorum against stores of dead nodes
            cluster.update_membership([], reachable=[], now=now)
            return False
        observer = select_observer(cluster.live, set(alive), cluster.master_node)
        if observer is None:
            return True
        if observer != fetched_for:
            # the prefetch guessed wrong (e.g. an election happened after
            # dispatch): refetch the actual observer's row, never consume a
            # dead master's frozen view
            row = np.asarray(membership_packet(state, observer))[n:]
        view = np.nonzero(row)[0]
        old_master = cluster.master_node
        cluster.update_membership(view.tolist(), reachable=alive, now=now)
        if cluster.master_node != old_master:
            elections += 1
        repairs += len(cluster.fail_recover())
        return True

    t0 = time.perf_counter()
    # pipelined chunks: the SDFS control plane consumes membership but
    # never feeds back into the detector state, so chunk k+1 (and its
    # membership packet) dispatches BEFORE chunk k's reaction runs — the
    # device streams while the host reacts, instead of a tunnel round-trip
    # serializing every RECOVERY_DELAY rounds.  Reactions still see each
    # chunk boundary's exact state, in order.
    pending = None  # (packet device-future, done_rounds, state, fetched_for)
    for _ in range(n_chunks):
        state = run_chunk(state)
        done += chunk
        fetched_for = cluster.master_node
        pkt = membership_packet(state, fetched_for)
        prev, pending = pending, (pkt, done, state, fetched_for)
        if prev is not None and not react(
            np.asarray(prev[0]), prev[1], prev[2], prev[3]
        ):
            pending = None
            break
    if pending is not None:
        react(np.asarray(pending[0]), pending[1], pending[2], pending[3])
    elapsed = time.perf_counter() - t0
    # durability: how many files still answer a quorum read at the end
    readable = sum(
        1 for f in range(sc.n_files) if cluster.get(f"file{f}.txt") is not None
    )
    return {
        "rounds": done,
        "elapsed_s": round(elapsed, 3),
        "rounds_per_sec": round(done / elapsed, 2) if elapsed else None,
        "files": sc.n_files,
        "files_readable": readable,
        "repair_plans": repairs,
        "elections": elections,
        "final_alive": len(alive),
    }


def run_scenario(
    sc: presets.Scenario | str,
    *,
    n_override: int | None = None,
    rounds_override: int | None = None,
    seed: int = 0,
    track: int = 4,
    crash_at: int = 10,
    mesh=None,
) -> dict:
    """Run one BASELINE scenario and return its report dict.

    ``n_override`` shrinks (or grows) the member count — fanout is rescaled
    for random topologies — so the 100k presets can be smoke-run on small
    hosts.  ``mesh``: optional ``jax.sharding.Mesh`` to shard the state over
    (see parallel/mesh.py).
    """
    if isinstance(sc, str):
        sc = presets.ALL[sc]
    cfg = sc.config
    if n_override is not None and n_override != cfg.n:
        fanout = (
            cfg.fanout if cfg.topology == "ring" else SimConfig.log_fanout(n_override)
        )
        cfg = dataclasses.replace(cfg, n=n_override, fanout=fanout)
    rounds = rounds_override or sc.rounds

    events, crash_rounds, churn_ok = tracked_crash_events(cfg, rounds, track, crash_at)
    state = init_state(cfg)
    if mesh is not None:
        from gossipfs_tpu.parallel.mesh import shard_state

        state = shard_state(state, mesh)
    key = jax.random.PRNGKey(seed)
    final, carry, per_round, elapsed = _timed_run(
        state, cfg, rounds, key, events, sc, churn_ok, mesh=mesh
    )
    report = summarize(carry, per_round, crash_rounds)

    result = {
        "scenario": sc.name,
        "n": cfg.n,
        "topology": cfg.topology,
        "fanout": cfg.fanout,
        "rounds": rounds,
        "crash_rate": sc.crash_rate,
        "rejoin_rate": sc.rejoin_rate,
        "platform": jax.devices()[0].platform,
        "devices": 1 if mesh is None else mesh.devices.size,
        "elapsed_s": round(elapsed, 4),
        "rounds_per_sec": round(rounds / elapsed, 2),
        # the reference advances 1 round per wall-clock second (main.go:27-33)
        "speedup_vs_realtime": round(rounds / elapsed, 2),
        "detection": report.as_dict(),
    }
    if sc.sdfs_cosim:
        result["cosim"] = run_cosim(sc, cfg, rounds, seed, mesh=mesh)
    return result


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", choices=sorted(presets.ALL), default="sim-1k")
    p.add_argument("--n", type=int, default=None, help="override member count")
    p.add_argument("--rounds", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--track", type=int, default=4, help="tracked crashes for TTD")
    p.add_argument("--shard", action="store_true", help="shard over all devices")
    p.add_argument("--out", type=str, default=None, help="also write JSON here")
    args = p.parse_args(argv)

    mesh = None
    if args.shard:
        from gossipfs_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
    result = run_scenario(
        args.scenario,
        n_override=args.n,
        rounds_override=args.rounds,
        seed=args.seed,
        track=args.track,
        mesh=mesh,
    )
    doc = json.dumps(result)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")


if __name__ == "__main__":
    sys.exit(main())
