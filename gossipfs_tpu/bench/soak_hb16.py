"""Long-horizon soak: narrow heartbeat storage vs exact int32, 50k rounds.

The narrow-storage optimizations (int16/int8 relative heartbeats + int8
gossip view, core/rounds.py) carry window invariants that unit tests
exercise only with synthetic counter shifts.  This soak validates them
end-to-end on real hardware: 50,000 rounds with continuous crash+rejoin
churn, where half the cluster (including the introducer) is churn-immune so
its counters cross the storage rebase windows — the int16 window (16,384
rounds) ~3 times, the int8 window (126 rounds) ~400 times — while the
churned half keeps exercising joins, detections, and merges against the
rebased columns.  The int8 mode is the headline benchmark's storage
(bench.py), so this soak is its long-horizon certification.

PASS criteria: int16 and int8 modes each agree exactly with int32 on
status, age, alive, per-chunk detection/convergence rounds, detection
counts, and the reconstructed true counters of every live MEMBER lane.

Run (TPU, ~8 min):   python -m gossipfs_tpu.bench.soak_hb16
One dtype only:      python -m gossipfs_tpu.bench.soak_hb16 --dtypes int8
Last recorded pass: 2026-07-30, v5e chip — max true hb 50,000,
int16 store_base 33,616 / int8 store_base 49,875, all comparisons equal.
"""

import argparse
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import MEMBER, init_state

key = jax.random.PRNGKey(0)
N = 4096  # small enough that both modes + comparisons run fast, large enough to be real
base_cfg = SimConfig(n=N, topology="random", fanout=SimConfig.log_fanout(N),
                     merge_kernel="pallas", view_dtype="int8", merge_block_c=16_384)

# half the cluster (including the introducer) is immune to churn: immune
# nodes live the full 50k rounds so their counters cross the storage rebase
# windows (store_base > 0) while the churnable half keeps exercising joins,
# detections, and merges against the rebased columns
CHURN_OK = jnp.arange(N) >= N // 2


def run_mode(hb_dtype):
    cfg = dataclasses.replace(base_cfg, hb_dtype=hb_dtype)
    state = init_state(cfg)
    outs = []
    for chunk in range(10):
        state, mc, pr = run_rounds(state, cfg, 5000, key, crash_rate=0.004,
                                   rejoin_rate=0.004, churn_ok=CHURN_OK)
        outs.append((np.asarray(mc.first_detect), np.asarray(mc.converged),
                     int(np.asarray(pr.true_detections).sum()),
                     int(np.asarray(pr.false_positives).sum())))
    return state, outs


def compare(tag, st32, o32, st, o):
    ok = True
    for c, (a, b) in enumerate(zip(o32, o)):
        for name, x, y in (("first_detect", a[0], b[0]), ("converged", a[1], b[1])):
            if not np.array_equal(x, y):
                ok = False
                print(f"[{tag}] chunk {c}: {name} DIVERGED ({np.sum(x != y)} entries)")
        if a[2:] != b[2:]:
            ok = False
            print(f"[{tag}] chunk {c}: detection counts diverged {a[2:]} vs {b[2:]}")
    same_status = np.array_equal(np.asarray(st32.status), np.asarray(st.status))
    same_age = np.array_equal(np.asarray(st32.age), np.asarray(st.age))
    live = np.asarray(st32.alive)[:, None] & (np.asarray(st32.status) == int(MEMBER))
    h32 = np.where(live, np.asarray(st32.hb_true()), -1)
    hn = np.where(live, np.asarray(st.hb_true()), -1)
    same_hb = np.array_equal(h32, hn)
    print(f"[{tag}] status equal: {same_status} | age equal: {same_age} | "
          f"live MEMBER hb_true equal: {same_hb} | max true hb: {h32.max()} | "
          f"store_base active: {int(np.asarray(st.hb_base).max())}")
    return ok and same_status and same_age and same_hb


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dtypes", nargs="*", default=["int16", "int8"],
                   choices=["int16", "int8"])
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    st32, o32 = run_mode("int32")
    print(f"int32 reference done in {time.perf_counter()-t0:.0f}s, "
          f"round={int(st32.round)}")
    all_ok = True
    for dtype in args.dtypes:
        t1 = time.perf_counter()
        st, o = run_mode(dtype)
        print(f"{dtype} done in {time.perf_counter()-t1:.0f}s")
        all_ok &= compare(dtype, st32, o32, st, o)
    print("SOAK", "PASS" if all_ok else "FAIL")
    assert all_ok


if __name__ == "__main__":
    main()
