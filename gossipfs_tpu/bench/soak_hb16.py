"""Long-horizon soak: int16 heartbeat storage vs exact int32, 50k rounds.

The narrow-storage optimizations (int16 relative heartbeats + int8 gossip
view, core/rounds.py) carry window invariants that unit tests exercise only
with synthetic counter shifts.  This soak validates them end-to-end on real
hardware: 50,000 rounds with continuous crash+rejoin churn, where half the
cluster (including the introducer) is churn-immune so its counters cross the
int16 rebase window (store_base ends > 33k) while the churned half keeps
exercising joins, detections, and merges against rebased columns.

PASS criteria: int16 and int32 modes agree exactly on status, age, alive,
per-chunk detection/convergence rounds, detection counts, and the
reconstructed true counters of every live MEMBER lane.

Run (TPU, ~4 min):  python -m gossipfs_tpu.bench.soak_hb16
Last recorded pass: 2026-07-30, v5e chip — max true hb 50,000,
store_base 33,616, all comparisons equal.
"""

import time
import numpy as np
import jax, jax.numpy as jnp
import dataclasses
from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import init_state, MEMBER
from gossipfs_tpu.core.rounds import run_rounds

key = jax.random.PRNGKey(0)
N = 4096  # small enough that both modes + comparisons run fast, large enough to be real
base_cfg = SimConfig(n=N, topology="random", fanout=SimConfig.log_fanout(N),
                     merge_kernel="pallas", view_dtype="int8", merge_block_c=16_384)

# half the cluster (including the introducer) is immune to churn: immune
# nodes live the full 50k rounds so their counters cross the int16 rebase
# window (store_base > 0) while the churnable half keeps exercising joins,
# detections, and merges against the rebased columns
CHURN_OK = jnp.arange(N) >= N // 2


def run_mode(hb_dtype):
    cfg = dataclasses.replace(base_cfg, hb_dtype=hb_dtype)
    state = init_state(cfg)
    outs = []
    for chunk in range(10):
        state, mc, pr = run_rounds(state, cfg, 5000, key, crash_rate=0.004,
                                   rejoin_rate=0.004, churn_ok=CHURN_OK)
        outs.append((np.asarray(mc.first_detect), np.asarray(mc.converged),
                     int(np.asarray(pr.true_detections).sum()),
                     int(np.asarray(pr.false_positives).sum())))
    return state, outs

def main():
    t0 = time.perf_counter()
    st32, o32 = run_mode("int32")
    st16, o16 = run_mode("int16")
    print(f"soak done in {time.perf_counter()-t0:.0f}s, round={int(st32.round)}")
    ok = True
    for c, (a, b) in enumerate(zip(o32, o16)):
        for name, x, y in (("first_detect", a[0], b[0]), ("converged", a[1], b[1])):
            if not np.array_equal(x, y):
                ok = False; print(f"chunk {c}: {name} DIVERGED ({np.sum(x!=y)} entries)")
        if a[2:] != b[2:]:
            ok = False; print(f"chunk {c}: detection counts diverged {a[2:]} vs {b[2:]}")
    print("status equal:", np.array_equal(np.asarray(st32.status), np.asarray(st16.status)))
    print("age equal:", np.array_equal(np.asarray(st32.age), np.asarray(st16.age)))
    live = np.asarray(st32.alive)[:, None] & (np.asarray(st32.status) == int(MEMBER))
    h32 = np.where(live, np.asarray(st32.hb_true()), -1)
    h16 = np.where(live, np.asarray(st16.hb_true()), -1)
    print("live MEMBER hb_true equal:", np.array_equal(h32, h16))
    print("max true hb:", h32.max(), "| store_base active:", int(np.asarray(st16.hb_base).max()))
    print("SOAK", "PASS" if (ok and np.array_equal(h32, h16)) else "FAIL")
    assert ok and np.array_equal(h32, h16)


if __name__ == "__main__":
    main()
