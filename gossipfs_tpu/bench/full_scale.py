"""Full-scale sharded correctness run: 100k-class N over an 8-way mesh.

BASELINE config 4 is 100k+ members on a v5e-8.  Multi-chip hardware is not
reachable from this environment, so this runner executes the EXACT
multi-chip program — ``parallel.mesh.run_rounds_sharded`` over an 8-device
mesh, subject-axis sharded — on 8 virtual CPU devices, and reports the
BASELINE metrics (time-to-detect, convergence, FPR) for tracked crashes.
Slow (one CPU core stands in for 8 chips) but it is the same compiled
program structure the v5e-8 runs.

    python -m gossipfs_tpu.bench.full_scale                  # default N=98,304
    # NOTE: one virtual round costs minutes of host CPU; FULLSCALE.json
    # records the largest completed run (use --n 32768 --rounds 12 for a
    # ~30 min validation pass)
    python -m gossipfs_tpu.bench.full_scale --n 65536 --rounds 18

Memory notes (125 GB host): the all-int8 state (3 B/entry, the headline
storage) at N=98,304 is 29 GB, built directly sharded (no host staging)
and donated into the scan, with the arc topology's windowed merge keeping
per-round traffic F-independent.  The peak CPU working set still reaches
~120 GB — the full N=131,072 exceeds the HOST (not the real mesh's
aggregate HBM; BASELINE.md carries that arithmetic), which is why the
default stops at 98,304.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def _force_cpu_mesh(n_devices: int) -> None:
    """Force this process onto an ``n_devices``-wide virtual CPU mesh.

    The container's sitecustomize registers the axon TPU backend at
    interpreter startup whenever ``PALLAS_AXON_POOL_IPS`` is set — BEFORE
    this function runs — so mutating ``os.environ`` alone is too late: the
    100k-class state would land on (and exhaust) the one real chip.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
        # XLA's CPU-collective rendezvous aborts the process when the 8
        # virtual devices' threads arrive at an all-reduce more than 40 s
        # apart.  On this 1-core host a 100k-class shard computes for
        # MINUTES between collectives, so the skew between timesliced
        # device threads routinely exceeds the default — this, not memory
        # or wall-clock, is what capped earlier full-scale artifacts at
        # N=32,768.  Raise warn/terminate to 12 h.
        " --xla_cpu_collective_call_warn_stuck_timeout_seconds=43200"
        " --xla_cpu_collective_call_terminate_timeout_seconds=43200"
        " --xla_cpu_collective_timeout_seconds=43200"
    ).strip()
    # sitecustomize has already imported jax and registered the axon
    # factory; deregister it before any backend initializes (same pattern
    # as tests/conftest.py) so the env mutation above actually takes
    import jax
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")


def run(n: int, rounds: int, crash_at: int, track: int, crash_rate: float,
        devices: int, seed: int) -> dict:
    import jax

    from gossipfs_tpu.bench.run import tracked_crash_events
    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.state import init_state
    from gossipfs_tpu.metrics.detection import summarize
    from gossipfs_tpu.parallel.mesh import (
        make_mesh,
        run_rounds_sharded,
        state_shardings,
    )

    cfg = SimConfig(
        n=n,
        topology="random_arc",
        fanout=SimConfig.log_fanout(n),
        remove_broadcast=False,
        fresh_cooldown=True,
        t_cooldown=12,
        merge_kernel="xla",   # virtual CPU mesh: the XLA arc window path
        view_dtype="int8",
        # all-int8 state (3 B/entry): at the full N=131,072 the int16-era
        # state was 69 GB and the run's host working set exceeded the
        # 125 GB box; int8 is also what the single-chip headline ships
        hb_dtype="int8",
    )
    mesh = make_mesh(devices)
    # build the state directly onto its shards — a host-staged [N, N] copy
    # would double peak memory at this scale
    state = jax.jit(
        lambda: init_state(cfg), out_shardings=state_shardings(mesh)
    )()
    events, crash_rounds, churn_ok = tracked_crash_events(
        cfg, rounds, track, crash_at
    )
    t0 = time.perf_counter()
    final, carry, per_round = run_rounds_sharded(
        state, cfg, rounds, jax.random.PRNGKey(seed), mesh,
        events=events, crash_rate=crash_rate, churn_ok=churn_ok, donate=True,
        crash_only_events=True,  # tracked_crash_events schedules crashes only
    )
    jax.block_until_ready(carry)
    elapsed = time.perf_counter() - t0
    report = summarize(carry, per_round, crash_rounds)
    ttd_f = [v for v in report.ttd_first.values() if v >= 0]
    ttd_c = [v for v in report.ttd_converged.values() if v >= 0]
    return {
        "metric": "full-scale sharded correctness run (BASELINE config 4 program)",
        "n": n,
        "shards": devices,
        "columns_per_shard": n // devices,
        "fanout": cfg.fanout,
        "topology": cfg.topology,
        "rounds": rounds,
        "crash_churn": crash_rate,
        "tracked_crashes": len(crash_rounds),
        "detected": len(ttd_f),
        "ttd_first_median": statistics.median(ttd_f) if ttd_f else None,
        "ttd_first_max": max(ttd_f) if ttd_f else None,
        "ttd_converged_median": statistics.median(ttd_c) if ttd_c else None,
        "ttd_converged_max": max(ttd_c) if ttd_c else None,
        "false_positive_rate": report.false_positive_rate,
        "wall_seconds": round(elapsed, 1),
        "rounds_per_sec": round(rounds / elapsed, 4),
        "backend": "virtual CPU mesh (1 host core standing in for 8 chips)",
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=98_304)
    p.add_argument("--rounds", type=int, default=18)
    p.add_argument("--crash-at", type=int, default=3)
    p.add_argument("--track", type=int, default=8)
    p.add_argument("--crash-rate", type=float, default=0.01)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    args = p.parse_args(argv)
    _force_cpu_mesh(args.devices)
    result = run(args.n, args.rounds, args.crash_at, args.track,
                 args.crash_rate, args.devices, args.seed)
    print(json.dumps(result))
    if args.out:
        # the committed artifact keeps ONE canonical filename: the newest
        # run is "current", superseded runs accumulate in "history" (a
        # round-5 review found the obvious filename holding a stale run
        # while the newest hid in a suffixed file)
        doc = {"current": result, "history": []}
        if os.path.exists(args.out):
            with open(args.out) as f:
                prev = json.load(f)
            if "current" in prev:
                doc["history"] = [prev["current"]] + prev.get("history", [])
            else:  # legacy single-run file
                doc["history"] = [prev]
        # atomic replace: these runs cost hours — a kill mid-write must
        # not destroy the accumulated artifact
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(doc) + "\n")
        os.replace(tmp, args.out)


if __name__ == "__main__":
    sys.exit(main())
