"""SDFS operation-latency benchmark — the reference report's perf section.

The reference's published performance results (report.pdf "Performance" /
"Analysis"; BASELINE.md "Published claims") are insert/update/read latency
curves over file size at 4 and 8 machines, with three qualitative claims:

  1. insert ~ update, read slightly less — a write pushes R=4 replicas
     (quorum-acked), a read pulls one copy;
  2. latency grows with file size;
  3. latency is governed by the replica count, not the cluster size
     ("no significant difference between 4 machines and 8 machines").

This runner reproduces those curves on the TPU build's SDFS plane
(sdfs/cluster.py — same placement/quorum/versioning logic, in-process byte
stores standing in for the reference's sshpass/scp hop) and checks the three
claims mechanically:

  python -m gossipfs_tpu.bench.sdfs_ops
  python -m gossipfs_tpu.bench.sdfs_ops --sizes 65536 1048576 4194304
  python -m gossipfs_tpu.bench.sdfs_ops --trace /tmp/sdfs_ops.jsonl

The workload mirrors the reference repo's checked-in Wikipedia-dump shards
(file1..10.txt, ~3-4 MB each) with deterministic pseudo-random payloads of
the same magnitudes.

``--trace PATH`` streams every measured operation through the flight
recorder (``obs/``) as ``client_op`` rows under the self-describing
``gossipfs-obs/v1`` header — the round-10 convention every other bench
follows — so ``tools/timeline.py`` ingests the artifact directly (it
attaches the client-op latency rollup to the analysis).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

from gossipfs_tpu.sdfs.cluster import SDFSCluster
from gossipfs_tpu.sdfs.types import STRIPE_K, STRIPE_M

DEFAULT_SIZES = (65_536, 1_048_576, 4_194_304)  # 64 KB, 1 MB, 4 MB
CLUSTERS = (4, 8)                               # the report's two settings
# stripe mode needs n >= k+m ack-able holders, so its two settings scale
# up while keeping the same 2x cluster-size contrast
STRIPE_CLUSTERS = (8, 16)
REPS = 7


def _payload(size: int, seed: int) -> bytes:
    # cheap deterministic bytes; avoids numpy/jax so the measured time is
    # purely the SDFS data plane
    chunk = (seed.to_bytes(4, "little") * (4096 // 4 + 1))[:4096]
    return (chunk * (size // 4096 + 1))[:size]


def _time(fn) -> float:
    t0 = time.perf_counter()
    ok = fn()
    dt = time.perf_counter() - t0
    assert ok is not False and ok is not None, "operation failed"
    return dt


def run(sizes=DEFAULT_SIZES, clusters=None, reps=REPS,
        trace: str | None = None, redundancy: str = "replica",
        stripe_k: int = STRIPE_K, stripe_m: int = STRIPE_M) -> dict:
    # Reps interleave across cluster sizes (and rep 0 is a discarded
    # warmup) so host-load drift perturbs the 4- and 8-node measurements
    # equally; best-of-reps is the noise-robust latency estimator.  The
    # sequential-medians version was flaky under concurrent load.
    if clusters is None:
        clusters = STRIPE_CLUSTERS if redundancy == "stripe" else CLUSTERS
    recorder = None
    if trace is not None:
        from gossipfs_tpu.obs.recorder import FlightRecorder

        recorder = FlightRecorder(
            trace, source="sdfs_ops", sizes=list(sizes),
            clusters=list(clusters), reps=reps, redundancy=redundancy,
        )
    built = {
        n_nodes: SDFSCluster(n_nodes, seed=7, redundancy=redundancy,
                             stripe_k=stripe_k, stripe_m=stripe_m)
        for n_nodes in clusters
    }
    samples: dict[tuple[int, int], dict[str, list[float]]] = {
        (n_nodes, size): {"insert": [], "update": [], "read": []}
        for n_nodes in built
        for size in sizes
    }
    for size in sizes:
        for r in range(reps + 1):
            for n_nodes, cluster in built.items():
                name = f"file-{size}-{r}.txt"
                data = _payload(size, r)
                now = 1000 * (r + 1) * (size % 977 + 1)
                ins = _time(lambda: cluster.put(name, data, now=now))
                upd = _time(
                    lambda: cluster.put(name, data, now=now + 1, confirm=lambda: True)
                )
                rd = _time(lambda: cluster.get(name))
                if recorder is not None:
                    from gossipfs_tpu.obs.schema import Event

                    for op, dt in (("insert", ins), ("update", upd),
                                   ("read", rd)):
                        recorder.emit(Event(
                            round=r, observer=-1, subject=-1,
                            kind="client_op",
                            detail={"op": op, "file": name, "bytes": size,
                                    "ms": round(dt * 1e3, 4), "ok": True,
                                    "nodes": n_nodes,
                                    "warmup": r == 0},
                        ))
                if r > 0:
                    cell = samples[(n_nodes, size)]
                    cell["insert"].append(ins)
                    cell["update"].append(upd)
                    cell["read"].append(rd)
    if recorder is not None:
        recorder.close()
    rows = [
        {
            "nodes": n_nodes,
            "size_bytes": size,
            # self-describing redundancy (stripe rows carry their shape)
            "redundancy": redundancy,
            **({"stripe_k": stripe_k, "stripe_m": stripe_m}
               if redundancy == "stripe" else {}),
            "insert_ms": round(min(cell["insert"]) * 1e3, 4),
            "update_ms": round(min(cell["update"]) * 1e3, 4),
            "read_ms": round(min(cell["read"]) * 1e3, 4),
        }
        for (n_nodes, size), cell in samples.items()
    ]

    def med(metric, pred):
        vals = [r[metric] for r in rows if pred(r)]
        return statistics.median(vals)

    big = max(sizes)
    small = min(sizes)
    claims = {
        # 1: writes (R-replica push) cost more than reads (single pull)
        "write_exceeds_read_at_large_files": (
            med("insert_ms", lambda r: r["size_bytes"] == big)
            > med("read_ms", lambda r: r["size_bytes"] == big)
        ),
        # 2: latency grows with file size
        "latency_grows_with_size": (
            med("insert_ms", lambda r: r["size_bytes"] == big)
            > med("insert_ms", lambda r: r["size_bytes"] == small)
        ),
        # 3: replica count, not cluster size, governs latency (<= 2x gap
        # between the small and 2x-larger clusters at the largest size)
        "cluster_size_insignificant": (
            0.5
            < (
                med("insert_ms",
                    lambda r: r["nodes"] == min(clusters)
                    and r["size_bytes"] == big)
                / max(
                    med(
                        "insert_ms",
                        lambda r: r["nodes"] == max(clusters)
                        and r["size_bytes"] == big,
                    ),
                    1e-9,
                )
            )
            < 2.0
        ),
    }
    return {"rows": rows, "redundancy": redundancy,
            "reference_claims_reproduced": claims}


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", type=int, nargs="+", default=list(DEFAULT_SIZES))
    p.add_argument("--reps", type=int, default=REPS)
    p.add_argument("--trace", type=str, default=None, metavar="PATH",
                   help="flight-recorder client_op stream (self-describing "
                        "gossipfs-obs/v1 header; timeline.py-ingestable)")
    p.add_argument("--redundancy", choices=("replica", "stripe"),
                   default="replica",
                   help="byte plane under test; stripe uses the 8/16-node "
                        "settings (n must exceed k+m)")
    p.add_argument("--stripe-k", type=int, default=STRIPE_K)
    p.add_argument("--stripe-m", type=int, default=STRIPE_M)
    args = p.parse_args(argv)
    print(json.dumps(run(sizes=tuple(args.sizes), reps=args.reps,
                         trace=args.trace, redundancy=args.redundancy,
                         stripe_k=args.stripe_k, stripe_m=args.stripe_m)))


if __name__ == "__main__":
    sys.exit(main())
