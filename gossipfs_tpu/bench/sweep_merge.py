"""Sweep pallas merge-kernel tile parameters on the real chip.

Produced the merge_block_r/merge_block_c/merge_slots defaults in config.py
(see BASELINE.md): the kernel is DMA-descriptor-issue bound once the view is
int16, so large column blocks win until the output block exhausts VMEM.

Run: JAX_PLATFORMS=axon python -m gossipfs_tpu.bench.sweep_merge
"""

from __future__ import annotations

import argparse
import itertools

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.utils.profiling import time_rounds

N = 16_384


def timed(cfg: SimConfig, key: jax.Array) -> float:
    # slope-based timing (utils/profiling.py) — single-call timings carry the
    # axon tunnel's per-dispatch offset and aren't comparable to BASELINE.md
    return time_rounds(init_state(cfg), cfg, key, crash_rate=0.01)[
        "rounds_per_sec"
    ]


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hb-dtype", choices=("int32", "int16", "int8"),
                   default="int32")
    p.add_argument("--elementwise", nargs="*", choices=("lanes", "swar"),
                   default=["lanes"],
                   help="epilogue formulations to sweep (swar needs "
                        "--hb-dtype int8; see config.SimConfig.elementwise)")
    args = p.parse_args(argv)

    key = jax.random.PRNGKey(0)
    results = []
    for (br, bc, slots), ew in itertools.product(
        itertools.product((64, 128, 256), (4096, 8192, 16384), (2, 4, 8)),
        args.elementwise,
    ):
        tag = f"br={br} bc={bc} slots={slots} ew={ew}"
        try:
            cfg = SimConfig(
                n=N, topology="random", fanout=SimConfig.log_fanout(N),
                remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
                merge_kernel="pallas", merge_block_r=br, merge_block_c=bc,
                merge_slots=slots, hb_dtype=args.hb_dtype,
                view_dtype="int8" if args.hb_dtype == "int8" else "int16",
                elementwise=ew,
            )
            rps = timed(cfg, key)
        except Exception as e:  # VMEM exhaustion at large out blocks
            print(f"{tag}: FAIL {type(e).__name__}", flush=True)
            continue
        results.append((rps, tag))
        print(f"{tag}: {rps:.1f} rounds/s", flush=True)
    if not results:
        print("no configuration succeeded")
        return
    rps, tag = max(results)
    print(f"best: {rps:.1f} rounds/s at {tag}")


if __name__ == "__main__":
    main()
