"""The reference's ACTUAL benchmark workload, across a real process wire.

BASELINE config 1 names the ``file1-10.txt`` payloads (multi-MB Wikipedia
dump shards; ``file5.txt`` 4.0 MB and ``file10.txt`` 3.2 MB survive in the
reference checkout) as the put/get benchmarking workload the report's
latency charts were measured on (reference: README.md workload,
server/server.go:123-131).  ``bench/sdfs_ops.py`` reproduces the report's
qualitative claims with synthetic in-process payloads; THIS runner pushes
the reference's real file bytes through the gRPC shim's Put/Get against a
live server — base64-framed protobuf over HTTP/2, the 64 MB message cap
(shim/wire.py) doing the work it exists for — and exercises the
crash -> detection -> re-replication repair path on the same multi-MB
shard, verifying byte integrity end to end.

    python -m gossipfs_tpu.bench.wire_ops
    python -m gossipfs_tpu.bench.wire_ops --files /path/a.bin /path/b.bin

Prints one JSON document; rows land in BASELINE.md beside the synthetic
curves.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import statistics
import time

DEFAULT_FILES = (
    "/root/reference/file5.txt",
    "/root/reference/file10.txt",
)


def _ms(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return (time.perf_counter() - t0) * 1e3


def run(files=DEFAULT_FILES, n: int = 16, reps: int = 5) -> dict:
    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.cosim import CoSim
    from gossipfs_tpu.shim.client import ShimClient
    from gossipfs_tpu.shim.service import ShimServer

    sim = CoSim(SimConfig(n=n))
    server = ShimServer(sim).start()
    client = ShimClient(server.address, timeout=120.0)
    rows = []
    repair = None
    try:
        client.advance(2)
        for path in files:
            path = pathlib.Path(path)
            data = path.read_bytes()
            name = path.name
            inserts, updates, reads = [], [], []
            for r in range(reps):
                # each rep inserts a fresh name (first put = insert), then
                # updates it (confirmed overwrite), then reads it back
                rname = f"{r}_{name}"
                inserts.append(_ms(lambda: client.put(rname, data)))
                updates.append(_ms(lambda: client.put(rname, data, confirm=True)))
                blob = None

                def read():
                    nonlocal blob
                    blob = client.get(rname)

                reads.append(_ms(read))
                assert blob == data, "wire round-trip must be byte-identical"
            rows.append({
                "file": name,
                "size_bytes": len(data),
                "insert_ms_min": round(min(inserts), 2),
                "insert_ms_median": round(statistics.median(inserts), 2),
                "update_ms_min": round(min(updates), 2),
                "update_ms_median": round(statistics.median(updates), 2),
                "read_ms_min": round(min(reads), 2),
                "read_ms_median": round(statistics.median(reads), 2),
            })

        # repair path: crash a replica of the big shard, advance past
        # detection (t_fail=5) + recovery delay (8), confirm the replica
        # set healed and the bytes still read back identical over the wire
        path = pathlib.Path(files[0])
        data = path.read_bytes()
        name = f"repair_{path.name}"
        client.put(name, data)
        before = client.ls(name)
        victim = before[0]
        client.crash(victim)
        client.advance(16)
        after = client.ls(name)
        blob = client.get(name)
        repair = {
            "file": name,
            "size_bytes": len(data),
            "crashed_replica": victim,
            "replicas_before": before,
            "replicas_after": after,
            "healed": victim not in after and len(after) == len(before),
            "bytes_identical_after_repair": blob == data,
            "re_replications_logged": len(client.grep("Re-replicated")),
        }
    finally:
        client.close()
        server.stop()
    return {"nodes": n, "rows": rows, "repair": repair}


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--files", nargs="*", default=list(DEFAULT_FILES))
    p.add_argument("--n", type=int, default=16)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--out", type=str, default=None)
    args = p.parse_args(argv)
    doc = json.dumps(run(args.files, n=args.n, reps=args.reps), indent=2)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")


if __name__ == "__main__":
    main()
