"""Per-round timing + bandwidth utilization across merge-kernel configs.

    python -m gossipfs_tpu.bench.roundprof            # default N=16384
    python -m gossipfs_tpu.bench.roundprof --n 8192 --rounds 50

For each named configuration this prints ms/round, rounds/s, and the
bandwidth-utilization block (the MFU analog for this bandwidth-bound
workload): HBM bytes the round's program moves (modeled per path from the
lane dtypes — see :func:`round_bytes`), achieved GB/s against the chip's
peak, and the protocol's information-floor bytes (each hb/age/status entry
read once + written once — no program that advances the whole cluster's
state can move less), whose implied round time is the headline's ceiling.
The XLA-remainder cost is the gap between a config's round time and its
merge kernel's standalone time (utils/profiling.op_breakdown attributes
it op-by-op).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state


def base_config(n: int) -> SimConfig:
    return SimConfig(
        n=n,
        topology="random",
        fanout=SimConfig.log_fanout(n),
        remove_broadcast=False,
        fresh_cooldown=True,
        t_cooldown=12,
        merge_kernel="xla",
        view_dtype="int8",
        merge_block_c=16_384,
        hb_dtype="int16",
    )


def variants(n: int) -> dict[str, SimConfig]:
    cfg = base_config(n)
    out = {
        "xla": cfg,
        "pallas_gather": dataclasses.replace(cfg, merge_kernel="pallas"),
        # all-int8 XLA rounds, widened vs SWAR packed-word elementwise
        # (ops/swar.py) — these two run compiled on ANY backend, so the
        # lanes-vs-swar elementwise delta is measurable even off-TPU
        "xla_hb8": dataclasses.replace(cfg, hb_dtype="int8"),
        "xla_hb8_swar": dataclasses.replace(
            cfg, hb_dtype="int8", elementwise="swar"),
    }
    from gossipfs_tpu.ops.merge_pallas import STRIPE_BLOCK_C, stripe_supported

    if stripe_supported(n, cfg.fanout):
        out["pallas_stripe"] = dataclasses.replace(
            cfg, merge_kernel="pallas_stripe", merge_block_c=STRIPE_BLOCK_C
        )
        out["arc_stripe"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="pallas_stripe",
            merge_block_c=STRIPE_BLOCK_C,
        )
        out["stripe_hb8"] = dataclasses.replace(
            cfg, merge_kernel="pallas_stripe", merge_block_c=STRIPE_BLOCK_C,
            hb_dtype="int8",
        )
        out["arc_hb8"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="pallas_stripe",
            merge_block_c=STRIPE_BLOCK_C, hb_dtype="int8",
        )
        out["arc_hb8_xla"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="xla", hb_dtype="int8",
        )
        out["rr"] = dataclasses.replace(
            cfg, merge_kernel="pallas_rr", merge_block_c=STRIPE_BLOCK_C,
            hb_dtype="int8", merge_block_r=256,
        )
        out["rr_arc"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="pallas_rr",
            merge_block_c=STRIPE_BLOCK_C, hb_dtype="int8", merge_block_r=256,
        )
        out["rr_arc_resident"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="pallas_rr",
            merge_block_c=2048, hb_dtype="int8", merge_block_r=256,
            rr_resident="on",
        )
        # the round-5 headline: resident parked lanes + TILE-ALIGNED arcs
        # (group max rides the view build; the shift-doubling window-max
        # is gone) — bench.py's exact config
        out["rr_arc_al_resident"] = dataclasses.replace(
            cfg, topology="random_arc", fanout=16, arc_align=8,
            merge_kernel="pallas_rr",
            merge_block_c=2048, hb_dtype="int8", merge_block_r=512,
            rr_resident="on",
        )
        # the round-6 headline candidate: the same resident aligned-arc
        # kernel with the SWAR packed-word elementwise stages (4 subjects
        # per i32 VPU op) — the delta vs rr_arc_al_resident is the
        # recovered share of the ~7 ms/round VPU wall the round-5 stub
        # bisection measured
        out["rr_arc_al_resident_swar"] = dataclasses.replace(
            out["rr_arc_al_resident"], elementwise="swar",
        )
    return out


def suspicion_variants(n: int, interpret: bool = True) -> dict[str, SimConfig]:
    """Round-11 fast-path A/B: suspicion-on vs -off on the SAME kernel.

    The rows the committed ROUNDPROF_r11.jsonl artifact carries (CPU:
    ``--suspicion --n 2048``): the fused SWIM lifecycle must ride the
    resident-round kernel at ~no cost — the acceptance bar is
    suspicion-on within 1.2x of suspicion-off on the same kernel config
    — while the XLA pair gives the compiled-epilogue delta on any
    backend.  ``interpret=False`` is the on-chip form (the next TPU
    session's probe_rr_suspicion runs the same A/B compiled).
    """
    from gossipfs_tpu.suspicion.params import SuspicionParams

    sus = SuspicionParams(t_suspect=2)
    xla = dataclasses.replace(
        base_config(n), hb_dtype="int8", elementwise="swar", t_fail=3,
    )
    rr = SimConfig(
        n=n, topology="random_arc", fanout=-(-SimConfig.log_fanout(n) // 8) * 8,
        arc_align=8, remove_broadcast=False, fresh_cooldown=True,
        t_cooldown=12, t_fail=3,
        merge_kernel="pallas_rr_interpret" if interpret else "pallas_rr",
        merge_block_c=min(2048, n // 2), view_dtype="int8", hb_dtype="int8",
        merge_block_r=128, rr_resident="on", elementwise="swar",
    )
    return {
        "xla_swar": xla,
        "xla_swar_sus": dataclasses.replace(xla, suspicion=sus),
        "rr_swar": rr,
        "rr_swar_sus": dataclasses.replace(rr, suspicion=sus),
    }


# v5e HBM peak (one chip): 819 GB/s
HBM_PEAK_GBS = 819.0


def round_bytes(cfg: SimConfig) -> dict:
    """Modeled HBM bytes per round, by phase, for a config's chosen path.

    The model counts matrix ([N, N]-lane) traffic only — per-subject
    vectors, edges, and RNG are O(N·F) and three orders of magnitude
    smaller.  Byte counts per phase follow each path's actual program:

    * ``floor``: the PROTOCOL's information floor — each entry's minimal
      wire (hb byte + the age|status packed byte the rr path proves
      sufficient) read once + written once, i.e. 4·N² bytes — the same
      for every row so ceilings are comparable across configs; paths
      carrying wider state pay their surplus in the phase bytes, not in a
      redefined floor.
    * ``pallas_rr``: the resident-round kernel's wire is TWO bytes per
      entry (hb int8 + the age|status packed byte); it reads each lane
      stripe twice (view build + receiver sweep) — ONCE in resident mode,
      which parks the ticked lanes in VMEM — and writes once, plus the
      [N, nc·LANE] int16 per-receiver count side output (written by the
      kernel, re-read by the scan's reduce).
    * ``pallas_stripe`` / ``pallas``: separate XLA tick+view pass (3 lane
      reads, 3 lane writes + 1 view write), kernel (view read — F-fold
      for the gather kernel's per-row DMAs, once for the stripe — + 3
      lane reads + 3 lane writes), member-count pass (1 status read).
    * ``xla``: as stripe but the merge's view read is F-fold (gather).
    """
    n = cfg.n
    nn = n * n
    hb_b = {"int32": 4, "int16": 2, "int8": 1}[cfg.hb_dtype]
    view_b = {"int32": 4, "int16": 2, "int8": 1}[cfg.view_dtype]
    lanes_rw = nn * (hb_b + 1 + 1)  # hb + age + status, one crossing each
    floor = 2 * nn * 2  # minimal wire (2 B/entry packed), read + write
    f = cfg.fanout
    arc = cfg.topology == "random_arc"
    if cfg.merge_kernel.startswith("pallas_rr"):
        from gossipfs_tpu.ops.merge_pallas import LANE, rr_resident_supported

        nc = n // cfg.merge_block_c
        packed = nn * 2  # hb int8 + age|status packed into one byte
        resident = cfg.rr_resident != "off" and rr_resident_supported(
            n, cfg.fanout, cfg.merge_block_c,
            arc_align=(cfg.arc_align
                       if cfg.topology == "random_arc" else 1),
            block_r=cfg.merge_block_r,
            rotate=cfg.rr_rotate != "off",
        )
        phases = {
            "view_build_read": packed,
            # resident lanes park the ticked lanes in VMEM: the receiver
            # sweep re-reads nothing from HBM (round 5)
            "receiver_read": 0 if resident else packed,
            "lane_write": packed,
            # int16 side output (kernel write + scan re-read) — the int8
            # narrowing shipped in round 4; modeling it at 4 B overstated
            # rr bandwidth rows ~2% (round-5 advisor)
            "recv_count_side": 2 * n * nc * LANE * 2,
        }
        total = sum(phases.values())
        return {"phases": phases, "total": total, "floor": floor}
    else:
        merge_view_reads = nn * view_b if arc else f * nn * view_b
        if cfg.merge_kernel.startswith("pallas_stripe"):
            merge_view_reads = nn * view_b  # stripe resident: one crossing
        phases = {
            "tick_view_pass": 2 * lanes_rw + nn * view_b,
            "merge_kernel": merge_view_reads + 2 * lanes_rw,
            "member_count_pass": nn,
        }
    total = sum(phases.values())
    return {"phases": phases, "total": total, "floor": floor}


def bandwidth_row(cfg: SimConfig, seconds_per_round: float) -> dict:
    b = round_bytes(cfg)
    gbs = b["total"] / seconds_per_round / 1e9
    floor_s = b["floor"] / (HBM_PEAK_GBS * 1e9)
    return {
        "modeled_bytes_per_round": b["total"],
        "achieved_gb_per_s": round(gbs, 1),
        "pct_of_peak_hbm": round(100.0 * gbs / HBM_PEAK_GBS, 1),
        "floor_bytes_per_round": b["floor"],
        "floor_implied_ceiling_rounds_per_sec": round(1.0 / floor_s, 1),
        "phase_bytes": b["phases"],
    }


def time_config(cfg: SimConfig, rounds: int, reps: int = 3) -> float:
    key = jax.random.PRNGKey(0)
    state = init_state(cfg)
    st, _, _ = run_rounds(state, cfg, rounds, key, crash_rate=0.01)
    jax.block_until_ready(st)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        st, _, _ = run_rounds(state, cfg, rounds, key, crash_rate=0.01)
        jax.block_until_ready(st)
        best = min(best, time.perf_counter() - t0)
    return best / rounds


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=16_384)
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--only", nargs="*", default=None)
    p.add_argument("--suspicion", action="store_true",
                   help="round-11 fast-path A/B rows: suspicion-on vs "
                        "-off on the same kernel config (XLA/SWAR "
                        "compiled pair + rr pair; rr rows run the "
                        "interpret kernel off-TPU — the ROUNDPROF_r11 "
                        "artifact's command is --suspicion --n 2048)")
    p.add_argument("--compiled-rr", action="store_true",
                   help="with --suspicion: compiled pallas_rr rows "
                        "(TPU) instead of the interpret form")
    args = p.parse_args(argv)

    # self-describing header row (obs.schema.ROUNDPROF_SCHEMA): committed
    # ROUNDPROF_*.jsonl artifacts name their schema, tool, and shape, so
    # old and new profiles are distinguishable and tools/timeline.py can
    # ingest them; per-row elementwise/rr_rotate stay authoritative
    from gossipfs_tpu.obs import schema as obs_schema

    print(json.dumps({
        "schema": obs_schema.ROUNDPROF_SCHEMA, "tool": "roundprof",
        "n": args.n, "rounds": args.rounds,
        **({"mode": "suspicion_ab"} if args.suspicion else {}),
        "backend": jax.default_backend(),
    }), flush=True)

    table = (suspicion_variants(args.n, interpret=not args.compiled_rr)
             if args.suspicion else variants(args.n))
    rows = {}
    for name, cfg in table.items():
        if args.only and name not in args.only:
            continue
        per_round = time_config(cfg, args.rounds)
        rows[name] = {
            "ms_per_round": round(per_round * 1e3, 3),
            "rounds_per_sec": round(1.0 / per_round, 1),
            "elementwise": cfg.elementwise,
            "rr_rotate": cfg.rr_rotate,
            "merge_kernel": cfg.merge_kernel,
            "suspicion": cfg.suspicion is not None,
            "backend": jax.default_backend(),
            **bandwidth_row(cfg, per_round),
        }
        print(json.dumps({"config": name, "n": args.n, **rows[name]}), flush=True)


if __name__ == "__main__":
    main()
