"""Per-round timing across merge-kernel configurations (TPU tuning aid).

    python -m gossipfs_tpu.bench.roundprof            # default N=16384
    python -m gossipfs_tpu.bench.roundprof --n 8192 --rounds 50

Prints ms/round and rounds/s for each named configuration so kernel work
(ops/merge_pallas.py) can be attributed: the XLA-remainder cost is the gap
between a config's round time and its merge kernel's standalone time.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state


def base_config(n: int) -> SimConfig:
    return SimConfig(
        n=n,
        topology="random",
        fanout=SimConfig.log_fanout(n),
        remove_broadcast=False,
        fresh_cooldown=True,
        t_cooldown=12,
        merge_kernel="xla",
        view_dtype="int8",
        merge_block_c=16_384,
        hb_dtype="int16",
    )


def variants(n: int) -> dict[str, SimConfig]:
    cfg = base_config(n)
    out = {
        "xla": cfg,
        "pallas_gather": dataclasses.replace(cfg, merge_kernel="pallas"),
    }
    from gossipfs_tpu.ops.merge_pallas import STRIPE_BLOCK_C, stripe_supported

    if stripe_supported(n, cfg.fanout):
        out["pallas_stripe"] = dataclasses.replace(
            cfg, merge_kernel="pallas_stripe", merge_block_c=STRIPE_BLOCK_C
        )
        out["arc_stripe"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="pallas_stripe",
            merge_block_c=STRIPE_BLOCK_C,
        )
        out["stripe_hb8"] = dataclasses.replace(
            cfg, merge_kernel="pallas_stripe", merge_block_c=STRIPE_BLOCK_C,
            hb_dtype="int8",
        )
        out["arc_hb8"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="pallas_stripe",
            merge_block_c=STRIPE_BLOCK_C, hb_dtype="int8",
        )
        out["arc_hb8_xla"] = dataclasses.replace(
            cfg, topology="random_arc", merge_kernel="xla", hb_dtype="int8",
        )
    return out


def time_config(cfg: SimConfig, rounds: int, reps: int = 3) -> float:
    key = jax.random.PRNGKey(0)
    state = init_state(cfg)
    st, _, _ = run_rounds(state, cfg, rounds, key, crash_rate=0.01)
    jax.block_until_ready(st)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        st, _, _ = run_rounds(state, cfg, rounds, key, crash_rate=0.01)
        jax.block_until_ready(st)
        best = min(best, time.perf_counter() - t0)
    return best / rounds


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=16_384)
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--only", nargs="*", default=None)
    args = p.parse_args(argv)

    rows = {}
    for name, cfg in variants(args.n).items():
        if args.only and name not in args.only:
            continue
        per_round = time_config(cfg, args.rounds)
        rows[name] = {
            "ms_per_round": round(per_round * 1e3, 3),
            "rounds_per_sec": round(1.0 / per_round, 1),
        }
        print(json.dumps({"config": name, "n": args.n, **rows[name]}), flush=True)


if __name__ == "__main__":
    main()
