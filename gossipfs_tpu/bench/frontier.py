"""Single-chip capacity frontier: N=65,536 on the resident-round kernel.

The rr kernel's resident view stripe is N x merge_block_c bytes of VMEM;
at the narrow width (merge_block_c=1024, ops/merge_pallas.RR_BLOCK_CS)
N=65,536 fits — 4.3 BILLION tracked membership entries on one chip, at
2 B/entry on the packed wire (8.6 GB of state, updated in place).

What bounds this entry point is HBM at *initialization*: a SimState's
three [N, N] int8 lanes plus their blocked copies exceed the chip before
the scan starts, so this bench builds the stripe-major PACKED lanes
directly inside one jit (zeros + a constant pack byte — the fully-joined
cohort) and calls the scan core (core/rounds._scan_rounds_rr_packed).

    python -m gossipfs_tpu.bench.frontier                # N=65,536
    python -m gossipfs_tpu.bench.frontier --n 49152      # cross-check

Prints one JSON line with measured rounds/s and the BASELINE detection
metrics (TTD first/converged, FPR) for 8 tracked crashes under 1% churn.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def pad_quantum(block_c: int, topology: str) -> int:
    """Admissible-N quantum of the rr kernel: N must be a multiple of the
    stripe width (and, for arcs, of ARC_CHUNK)."""
    import math

    from gossipfs_tpu.ops import merge_pallas

    q = block_c
    if topology == "random_arc":
        q = math.lcm(q, merge_pallas.ARC_CHUNK)
    return q


def run(n: int, rounds: int, block_c: int, crash_at: int, track: int,
        crash_rate: float, seed: int, topology: str, block_r: int,
        arc_align: int = 1, fanout: int | None = None,
        elementwise: str = "lanes", rr_rotate: str = "auto",
        trace: str | None = None) -> dict:
    import jax
    import numpy as np

    from gossipfs_tpu.bench.run import tracked_crash_events
    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core import rounds as R
    from gossipfs_tpu.metrics.detection import summarize
    from gossipfs_tpu.ops import merge_pallas

    # Literal-N support (e.g. the BASELINE-named 100,000): pad up to the
    # next admissible aligned size with permanently-dead pad nodes — never
    # members anywhere, excluded from tracked crashes, churn and metrics
    # (rr_packed_init's member_mask; zero kernel changes).  100,000 at
    # block_c=1024 runs as n_padded=100,352 with 352 pads.
    quantum = pad_quantum(block_c, topology)
    n_pad = -(-n // quantum) * quantum
    padded = n_pad != n

    over = dict(topology=topology, merge_block_r=block_r,
                arc_align=arc_align, elementwise=elementwise,
                rr_rotate=rr_rotate)
    if fanout:
        over["fanout"] = fanout
    elif arc_align > 1:
        # aligned arcs need fanout % align == 0: round log2(N) up
        lf = SimConfig.log_fanout(n_pad)
        over["fanout"] = -(-lf // arc_align) * arc_align
    # else: packed_rr's own default, log_fanout of the (padded) n it gets
    cfg = SimConfig.packed_rr(n_pad, block_c, **over)
    events, crash_rounds, churn_ok = tracked_crash_events(
        cfg, rounds, track, crash_at, n_live=n if padded else None
    )
    member_mask = np.arange(n_pad) < n if padded else None

    @jax.jit
    def go(key, events, churn_ok):
        hb4, as4, alive, hb_base, rnd, counts = R.rr_packed_init(
            cfg, member_mask=member_mask
        )
        out = R._scan_rounds_rr_packed(
            hb4, as4, alive, hb_base, rnd, cfg, key, events,
            crash_rate, churn_ok, counts0=counts,
        )
        # lanes stay on device; only the [N]-vector liveness and the
        # metrics leave (alive feeds the flight recorder's ground truth)
        return out[2], out[7], out[8]

    key = jax.random.PRNGKey(seed)
    alive, mcarry, per_round = go(key, events, churn_ok)
    jax.block_until_ready(mcarry)
    t0 = time.perf_counter()
    alive, mcarry, per_round = go(key, events, churn_ok)
    jax.block_until_ready(mcarry)
    elapsed = time.perf_counter() - t0

    report = summarize(mcarry, per_round, crash_rounds,
                       n_effective=n if padded else None)
    trace_events = None
    if trace:
        # post-scan decode (obs/recorder.py): consumes the outputs the
        # summarize call above already transferred — the timed scan and
        # the rr kernel never see the flag
        from gossipfs_tpu.obs.recorder import write_trace

        trace_events = write_trace(
            trace, per_round, mcarry, n=n_pad, source="frontier",
            crash_rounds=crash_rounds, alive=alive,
            n_effective=n if padded else None,
            topology=topology, merge_block_c=block_c,
            elementwise=elementwise, rr_rotate=rr_rotate,
        )
    ttd_f = [v for v in report.ttd_first.values() if v >= 0]
    ttd_c = [v for v in report.ttd_converged.values() if v >= 0]
    import statistics
    return {
        "metric": "single-chip capacity frontier (resident-round kernel, "
                  "packed 2 B/entry wire)",
        "n": n,
        "n_padded": n_pad,
        "pad_nodes": n_pad - n,
        "entries": n_pad * n_pad,
        "merge_block_c": block_c,
        "fanout": cfg.fanout,
        "arc_align": arc_align,
        "topology": topology,
        "rounds": rounds,
        "crash_churn": crash_rate,
        "elementwise": elementwise,
        # self-describing artifact fields: which rr layouts ran, and the
        # shape's row-budget accounting (ring-rotated + compacted flags —
        # the round-9 layouts that admit wider stripes at every N)
        "rr_rotate": rr_rotate,
        "merge_block_r": block_r,
        "row_budget_bytes": (
            merge_pallas.rr_align_scratch_bytes(
                n_pad, cfg.fanout, block_c, arc_align,
                rotate=rr_rotate != "off")
            + merge_pallas.rr_flags_bytes(
                n_pad, block_c, block_r=block_r, arc_align=arc_align,
                rotate=rr_rotate != "off")
        ) if arc_align > 1 else None,
        "tracked_crashes": len(crash_rounds),
        "detected": len(ttd_f),
        "ttd_first_median": statistics.median(ttd_f) if ttd_f else None,
        "ttd_first_max": max(ttd_f) if ttd_f else None,
        "ttd_converged_median": statistics.median(ttd_c) if ttd_c else None,
        "false_positive_rate": report.false_positive_rate,
        "seconds_per_round": round(elapsed / rounds, 4),
        "rounds_per_sec": round(rounds / elapsed, 2),
        **({"trace": trace, "trace_events": trace_events} if trace else {}),
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=65_536)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--block-c", type=int, default=1024)
    p.add_argument("--block-r", type=int, default=256)
    p.add_argument("--crash-at", type=int, default=3)
    p.add_argument("--track", type=int, default=8)
    p.add_argument("--crash-rate", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--topology", type=str, default="random")
    p.add_argument("--arc-align", type=int, default=1,
                   help="tile-aligned arc bases (random_arc only)")
    p.add_argument("--fanout", type=int, default=None)
    p.add_argument("--elementwise", choices=("lanes", "swar"),
                   default="lanes",
                   help="packed-word SWAR elementwise (ops/swar.py) vs "
                        "the widened default")
    p.add_argument("--rr-rotate", choices=("auto", "off"), default="auto",
                   help="ring-rotated view build + LANE-compacted flags "
                        "(round 9) vs the full-T/replicated layouts — "
                        "same bits, different VMEM row cost")
    p.add_argument("--trace", type=str, default=None, metavar="PATH",
                   help="write the run's flight-recorder event stream "
                        "(obs/ JSONL; analyze with tools/timeline.py) — "
                        "decoded post-scan, the rr kernel is untouched")
    args = p.parse_args(argv)
    print(json.dumps(run(args.n, args.rounds, args.block_c, args.crash_at,
                         args.track, args.crash_rate, args.seed,
                         args.topology, args.block_r,
                         arc_align=args.arc_align, fanout=args.fanout,
                         elementwise=args.elementwise,
                         rr_rotate=args.rr_rotate, trace=args.trace)))


if __name__ == "__main__":
    sys.exit(main())
