"""Time-to-detect and false-positive-rate curves — the BASELINE artifacts.

BASELINE.json's metric is "time-to-detect and FPR curves for 100k members"
— detection quality as a function of scale, not a single point.  This
runner sweeps N with the north-star protocol settings (random fanout
log2 N, gossip-only dissemination, fresh cooldown), injects tracked
crashes, and emits one JSON document with a row per N:

  python -m gossipfs_tpu.bench.curves                 # default sweep
  python -m gossipfs_tpu.bench.curves --ns 1024 4096 16384 --out CURVES.json

Each row: median/max time-to-first-detection and to cluster-wide
convergence over the tracked crashes, plus the background FPR under 1%
random crash churn.  The sweep shows the protocol property that makes
random-fanout gossip the scalable mode: detection latency stays ~t_fail
rounds while N grows 16x (the ring parity mode, by contrast, storms —
tests/test_rounds.py's emergent-false-positive test).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys

import jax

from gossipfs_tpu.bench.run import tracked_crash_events
from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.metrics.detection import summarize

DEFAULT_NS = (1024, 4096, 16384)
ROUNDS = 60
CRASH_AT = 10
TRACK = 8


def sweep(ns=DEFAULT_NS, rounds=ROUNDS, crash_rate=0.01, seed=0,
          topology="random", donate=False, hb_dtype="int16",
          time_rounds=False, arc_align=1, fanout=None,
          trace=None, monitor=False) -> dict:
    """``topology`` sweeps "random" (iid fanout) or "random_arc" (windowed
    arc senders) — the arc rows must match the iid rows within noise, which
    is the protocol-equivalence evidence for the fast arc merge kernel.
    ``donate=True`` runs the buffer-donating scan — required for the
    single-chip capacity points (N >= 32,768), whose state would not
    otherwise fit.  ``hb_dtype="int8"`` is the all-int8 state (3 B per
    tracked membership entry) that pushes the frontier to N=49,152.
    ``time_rounds=True`` adds a measured rounds/s per row (a second run on
    a fresh state, so compile time and the donated first state are
    excluded).  ``trace`` writes each row's flight-recorder event stream
    (obs/schema.py JSONL; ``tools/timeline.py`` re-derives this row's
    TTD/FPR from it alone) — to ``trace`` itself for a single N, to
    ``{trace}.n{N}`` per row otherwise.  ``monitor=True`` streams each
    row's decoded events through the online invariant monitor
    (obs/monitor.py) and stamps its verdict into the row."""
    import time as _time

    from gossipfs_tpu.core.rounds import run_rounds_donate

    runner = run_rounds_donate if donate else run_rounds
    rows = []
    for n in ns:
        cfg = SimConfig(
            n=n,
            topology=topology,
            # aligned arcs need fanout % align == 0: round log2(N) up so
            # --arc-align works without an explicit --fanout
            fanout=fanout or (
                -(-SimConfig.log_fanout(n) // arc_align) * arc_align
            ),
            arc_align=arc_align,
            remove_broadcast=False,
            fresh_cooldown=True,
            t_cooldown=12,
            merge_kernel="pallas",
            view_dtype="int8",
            hb_dtype=hb_dtype,
            merge_block_c=16_384,
        )
        events, crash_rounds, churn_ok = tracked_crash_events(
            cfg, rounds, TRACK, CRASH_AT
        )
        # tracked_crash_events schedules crashes only: the static promise
        # keeps the lean event path (no [N, N] fail matrix, in-kernel
        # detection stats) — required headroom at the capacity points
        final, carry, per_round = runner(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(seed),
            events=events, crash_rate=crash_rate, churn_ok=churn_ok,
            crash_only_events=True,
        )
        report = summarize(carry, per_round, crash_rounds)
        trace_path = None
        if trace:
            from gossipfs_tpu.obs.recorder import write_trace

            trace_path = trace if len(ns) == 1 else f"{trace}.n{n}"
            write_trace(
                trace_path, per_round, carry, n=n, source="curves",
                crash_rounds=crash_rounds, alive=final.alive,
                suspicion=cfg.suspicion is not None,
                topology=topology, fanout=cfg.fanout,
            )
        monitor_doc = None
        if monitor:
            from gossipfs_tpu.obs.monitor import monitor_verdict
            from gossipfs_tpu.obs.recorder import decode_scan

            evs = decode_scan(per_round, carry, n=n,
                              crash_rounds=crash_rounds,
                              alive=final.alive,
                              suspicion=cfg.suspicion is not None)
            monitor_doc = monitor_verdict(evs, n=n)
            del monitor_doc["violations"]  # counts in the row; evidence
            # belongs to --trace artifacts
        rps = None
        if time_rounds:
            # free the measurement run's final state before allocating the
            # timing run's — at the capacity points only one full state
            # (plus the round's working set) fits in HBM
            jax.block_until_ready(final)
            del final, carry, per_round
            st2 = init_state(cfg)
            jax.block_until_ready(st2)
            t0 = _time.perf_counter()
            out2, _, _ = runner(
                st2, cfg, rounds, jax.random.PRNGKey(seed),
                events=events, crash_rate=crash_rate, churn_ok=churn_ok,
                crash_only_events=True,
            )
            jax.block_until_ready(out2)
            rps = round(rounds / (_time.perf_counter() - t0), 2)
            del out2
        ttd_f = [v for v in report.ttd_first.values() if v >= 0]
        ttd_c = [v for v in report.ttd_converged.values() if v >= 0]
        rows.append(
            {
                "n": n,
                "fanout": cfg.fanout,
                "hb_dtype": hb_dtype,
                "rounds_per_sec": rps,
                "tracked_crashes": len(crash_rounds),
                "detected": len(ttd_f),
                "ttd_first_median": statistics.median(ttd_f) if ttd_f else None,
                "ttd_first_max": max(ttd_f) if ttd_f else None,
                "ttd_converged_median": statistics.median(ttd_c) if ttd_c else None,
                "ttd_converged_max": max(ttd_c) if ttd_c else None,
                "false_positive_rate": report.false_positive_rate,
                **({"trace": trace_path} if trace_path else {}),
                **({"monitor": monitor_doc} if monitor_doc else {}),
            }
        )
    return {
        "metric": "time-to-detect & FPR vs N (rounds; 1 round == 1 s reference time)",
        # per-row fanout is authoritative (rows[i]['fanout']); the header
        # names the rule: explicit, or log2(N) rounded up to the alignment
        "protocol": f"{topology} "
                    f"fanout={fanout if fanout else 'log2(N)'}"
                    f"{' rounded up to align=' + str(arc_align) if arc_align > 1 and not fanout else ''}"
                    f"{' align=' + str(arc_align) if arc_align > 1 and fanout else ''}"
                    ", gossip-only dissemination, t_fail=5",
        "crash_churn": crash_rate,
        "rows": rows,
    }


def _kernel_overrides(n: int, merge_kernel: str, elementwise: str) -> dict:
    """SimConfig overrides for a --merge-kernel/--elementwise passthrough.

    Round 11 (fast-path unification): scenario and suspicion rows run on
    ANY merge kernel, so the A/B sweeps accept the kernel knobs.  The
    rr/SWAR forms pull in the all-int8 state they require (config.py
    gates); merge_block_c picks the largest admissible stripe width at
    this n.
    """
    kw: dict = dict(merge_kernel=merge_kernel, elementwise=elementwise)
    if merge_kernel.startswith("pallas_rr") or elementwise == "swar":
        kw.update(view_dtype="int8", hb_dtype="int8")
    elif merge_kernel.startswith("pallas"):
        kw.update(view_dtype="int8", hb_dtype="int16",
                  merge_block_c=16_384)
    if merge_kernel.startswith("pallas_rr"):
        from gossipfs_tpu.ops.merge_pallas import RR_BLOCK_CS

        admissible = [c for c in RR_BLOCK_CS if n % c == 0 and c <= n]
        if not admissible:
            raise SystemExit(
                f"--merge-kernel {merge_kernel} needs n divisible by an "
                f"rr stripe width {RR_BLOCK_CS} (got n={n}); pick a "
                "power-of-two n >= 512 or use --merge-kernel xla"
            )
        kw["merge_block_c"] = max(admissible)
    return kw


def partition_sweep(ns=(1024,), seed=0, split_at=5,
                    merge_kernel="xla", elementwise="lanes") -> dict:
    """Scenario-engine partition rows — the committed netsplit artifact.

    Per N: split the cohort into halves for ``t_fail + t_cooldown +
    diameter + slack`` rounds, crash one tracked node inside EACH side
    mid-split, heal, and reduce the per-round device stats
    (metrics.detection.partition_round_stats) plus the detection events
    into a PartitionReport.  The claims the rows pin:

      * ``cross_hb_advances == 0`` — zero cross-partition heartbeat
        propagation while the split holds (the edge filter is airtight);
      * ``split_brain_rounds`` ~ t_fail + t_cooldown + diameter — how
        long the two sides' views diverge before both accept the split;
      * partition-local detection keeps working: the same-side tracked
        crash is detected in ~t_fail rounds (``local_ttd``);
      * ``reconverge_rounds <= reconverge_bound`` (t_fail + gossip
        diameter) — after heal the views knit back purely by gossip.

    CPU-feasible at N=1024-4096; tools/verify_claims.py re-runs the
    N=1024 row as the ``partition_reconv`` claim.  ``merge_kernel`` /
    ``elementwise`` (round 11): the configured kernel knobs — scenario
    runs no longer force the XLA merge.  NOTE: this sweep steps the
    interactive SimDetector lane, which runs scenario-armed rounds on
    the XLA-oracle form regardless (detector/sim.py); the knobs here
    select the config the bulk/fast paths would run and are primarily
    for the suspicion sweep's A/B — kept symmetric for completeness.
    """
    import math

    import jax.numpy as jnp
    import numpy as np

    from gossipfs_tpu.detector.sim import SimDetector
    from gossipfs_tpu.metrics.detection import (
        partition_round_stats,
        summarize_partition,
    )
    from gossipfs_tpu.scenarios import split_halves

    rows = []
    for n in ns:
        fanout = SimConfig.log_fanout(n)
        cfg = SimConfig(
            n=n,
            topology="random",
            fanout=fanout,
            remove_broadcast=False,   # scenario runs are gossip-only
            fresh_cooldown=True,      # (scenarios/tensor.py gating)
            t_cooldown=6,
            **_kernel_overrides(n, merge_kernel, elementwise),
        )
        diameter = math.ceil(math.log(n) / math.log(fanout + 1))
        split_len = cfg.t_fail + cfg.t_cooldown + diameter + 8
        heal_at = split_at + split_len
        bound = cfg.t_fail + diameter
        horizon = heal_at + bound + 8

        det = SimDetector(cfg, seed=seed)
        sc = split_halves(n, start=split_at, end=heal_at)
        det.load_scenario(sc)
        pid = sc.partitions[0].pid(n)
        pid_dev = jnp.asarray(pid)
        stats = jax.jit(partition_round_stats)

        # one tracked crash per side, two rounds into the split: the
        # partition-local TTD/FPR evidence
        crash_a = n // 4
        crash_b = n // 2 + n // 4
        crash_rounds = {crash_a: split_at + 2, crash_b: split_at + 2}
        series = []
        for _ in range(horizon):
            if int(det.state.round) == split_at + 2:
                det.crash(crash_a)
                det.crash(crash_b)
            det.advance(1)
            row = np.asarray(stats(det.state, pid_dev))
            series.append({
                "round": int(det.state.round),
                "cross_members": int(row[0]),
                "cross_hb_max": int(row[1]),
                "cross_complete": bool(row[2]),
                "complete": bool(row[3]),
                "n_alive": int(row[4]),
            })
        report = summarize_partition(
            series, det.drain_events(), pid, split_at, heal_at,
            crash_rounds=crash_rounds,
        )
        rows.append({
            "n": n,
            "fanout": fanout,
            "split_at": split_at,
            "heal_at": heal_at,
            "split_rounds": split_len,
            "reconverge_bound": bound,
            **report.as_dict(),
        })
    return {
        "metric": "netsplit behavior vs N (scenario engine; rounds, "
                  "1 round == 1 s reference time)",
        "protocol": "random fanout=log2(N), gossip-only dissemination, "
                    "t_fail=5, t_cooldown=6; half/half partition with "
                    "heal, one tracked crash per side",
        "rows": rows,
    }


def sweep_t_fail(n=4096, t_fails=(3, 5, 8, 12), t_suspects=(0, 2),
                 rounds=ROUNDS, seed=0) -> dict:
    """The deployment knobs: detection latency vs false-positive tradeoff.

    The reference hardcodes t_fail = 5 s (slave.go:24); this sweep shows
    what that choice buys — and, since the suspicion subsystem
    (suspicion/), what the SECOND knob buys: each row is (t_fail,
    t_suspect, TTD, FPR) at fixed N under 1% crash churn, the two-knob
    surface an operator would tune against.  ``t_suspect=0`` rows are the
    legacy single-knob curve (suspicion off); suspicion rows run the XLA
    fallback path (suspicion.with_suspicion) with refutation counts
    attached, so the knee analysis covers where SUSPECT+refute moves it.
    """
    from gossipfs_tpu.suspicion import SuspicionParams, with_suspicion

    rows = []
    for t_fail in t_fails:
        for t_sus in t_suspects:
            cfg = SimConfig(
                n=n,
                topology="random",
                fanout=SimConfig.log_fanout(n),
                remove_broadcast=False,
                fresh_cooldown=True,
                t_fail=t_fail,
                t_cooldown=max(12, t_fail + 4),
                merge_kernel="pallas",
                view_dtype="int8",
                hb_dtype="int16",
                merge_block_c=16_384,
            )
            if t_sus:
                cfg = with_suspicion(cfg, SuspicionParams(t_suspect=t_sus))
            events, crash_rounds, churn_ok = tracked_crash_events(
                cfg, rounds, TRACK, CRASH_AT
            )
            final, carry, per_round = run_rounds(
                init_state(cfg), cfg, rounds, jax.random.PRNGKey(seed),
                events=events, crash_rate=0.01, churn_ok=churn_ok,
            )
            report = summarize(carry, per_round, crash_rounds)
            ttd_f = [v for v in report.ttd_first.values() if v >= 0]
            rows.append(
                {
                    "t_fail": t_fail,
                    "t_suspect": t_sus,
                    "ttd_first_median": statistics.median(ttd_f) if ttd_f else None,
                    "false_positive_rate": report.false_positive_rate,
                    "suspects_entered": report.suspects_entered,
                    "refutations": report.refutations,
                    "fp_suppressed": report.fp_suppressed,
                }
            )
    return {"metric": "TTD vs FPR over (t_fail, t_suspect) — the "
                      "reference's 5 s knob plus the SWIM suspicion knob",
            "n": n, "rows": rows}


def suspicion_sweep(ns=(1024,), rounds=ROUNDS, seed=0, t_fail_fast=3,
                    t_suspect=2, t_fail_base=5, loss_rate=0.9,
                    loss_frac=16, merge_kernel="xla",
                    elementwise="lanes") -> dict:
    """Suspicion A/B — the committed SUSPECT artifact (suspicion/).

    Per N, two fault regimes x three detector modes:

      * regimes: (a) the standard 1% random crash churn; (b) a PR-2
        Bernoulli-loss scenario — 1/``loss_frac`` of the cohort loses
        ``loss_rate`` of its OUTGOING datagrams for the whole horizon
        (scenarios/: the partial-failure class that manufactures exactly
        the transient staleness suspicion exists to absorb);
      * modes: ``t_fail=5`` baseline (the reference knee), ``t_fail=3``
        raw (the FP storm the --t-fail-sweep documents), and
        ``t_fail=3 + t_suspect=2`` — SWIM suspicion at the fast knob.

    The claims the rows pin (tools/verify_claims.py ``suspicion_fpr``
    re-runs this command): with suspicion at t_fail=3, median TTD-first
    stays <= t_fail + t_suspect (the t_fail=5-class latency) while FPR
    stays within 10x of the t_fail=5 baseline instead of the raw-t3
    storm; and under the loss scenario suspicion-on FPR is strictly
    below suspicion-off at the same t_fail.  CPU-feasible at N=1024.

    ``merge_kernel`` / ``elementwise`` (round 11): the rows run on the
    CONFIGURED kernel — suspicion and scenario runs no longer force the
    XLA merge, so e.g. ``--merge-kernel pallas_rr_interpret
    --elementwise swar`` drives the fused fast path through the same
    A/B (Bernoulli-loss rows need a per-edge topology: 'random' here).
    """
    import dataclasses as _dc

    from gossipfs_tpu.scenarios import FaultScenario, LinkFault
    from gossipfs_tpu.scenarios.tensor import compile_tensor
    from gossipfs_tpu.suspicion import SuspicionParams

    rows = []
    for n in ns:
        base_kw = dict(
            n=n, topology="random", fanout=SimConfig.log_fanout(n),
            remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
            **_kernel_overrides(n, merge_kernel, elementwise),
        )
        # lossy senders: the first n/loss_frac nodes drop loss_rate of
        # their outgoing gossip (asymmetric: their inbound is fine) —
        # their entries at everyone else go stale in bursts
        lossy = tuple(range(max(n // loss_frac, 1)))
        loss_sc = FaultScenario(
            name="lossy-senders", n=n,
            link_faults=(LinkFault(start=0, end=rounds, rate=loss_rate,
                                   src=lossy, dst=tuple(range(n))),),
        )
        for fault in ("churn", "loss"):
            for mode, t_fail, sus in (
                ("baseline-t5", t_fail_base, None),
                ("raw-t3", t_fail_fast, None),
                ("suspect-t3", t_fail_fast,
                 SuspicionParams(t_suspect=t_suspect)),
            ):
                cfg = SimConfig(
                    **base_kw, t_fail=t_fail,
                )
                if sus is not None:
                    # round 11: arm the lifecycle ON the configured
                    # kernel (dataclasses.replace, not the deprecated
                    # with_suspicion oracle substitution) — identical
                    # configs on the default xla/lanes knobs, so the
                    # committed SUSPECT_r08 rows stay reproducible
                    cfg = _dc.replace(cfg, suspicion=sus)
                events, crash_rounds, churn_ok = tracked_crash_events(
                    cfg, rounds, TRACK, CRASH_AT
                )
                kw: dict = dict(events=events, churn_ok=churn_ok,
                                crash_only_events=True)
                if fault == "churn":
                    kw["crash_rate"] = 0.01
                else:
                    kw["scenario"] = compile_tensor(loss_sc)
                final, carry, per_round = run_rounds(
                    init_state(cfg), cfg, rounds, jax.random.PRNGKey(seed),
                    **kw,
                )
                report = summarize(carry, per_round, crash_rounds)
                ttd_f = [v for v in report.ttd_first.values() if v >= 0]
                ttd_s = [v for v in report.ttd_suspect.values() if v >= 0]
                s2c = [v for v in report.suspect_to_confirm.values()
                       if v >= 0]
                rows.append({
                    "n": n,
                    "fault": fault,
                    "mode": mode,
                    "t_fail": t_fail,
                    "t_suspect": sus.t_suspect if sus else 0,
                    "tracked_crashes": len(crash_rounds),
                    "detected": len(ttd_f),
                    "ttd_first_median": statistics.median(ttd_f) if ttd_f else None,
                    "ttd_first_max": max(ttd_f) if ttd_f else None,
                    "ttd_suspect_median": statistics.median(ttd_s) if ttd_s else None,
                    "suspect_to_confirm_median": statistics.median(s2c) if s2c else None,
                    "false_positive_rate": report.false_positive_rate,
                    "false_positives": report.false_positives,
                    "suspects_entered": report.suspects_entered,
                    "refutations": report.refutations,
                    "fp_suppressed": report.fp_suppressed,
                })
    return {
        "metric": "suspicion A/B: TTD & FPR, suspicion-on vs -off "
                  "(rounds; 1 round == 1 s reference time)",
        "protocol": f"random fanout=log2(N), gossip-only dissemination; "
                    f"modes t_fail={t_fail_base} | t_fail={t_fail_fast} raw"
                    f" | t_fail={t_fail_fast}+t_suspect={t_suspect}; "
                    f"faults: 1% crash churn | Bernoulli loss rate="
                    f"{loss_rate} on 1/{loss_frac} of senders",
        "rows": rows,
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ns", type=int, nargs="+", default=list(DEFAULT_NS))
    p.add_argument("--rounds", type=int, default=ROUNDS)
    p.add_argument("--topology", choices=["random", "random_arc"],
                   default="random")
    p.add_argument("--hb-dtype", choices=["int32", "int16", "int8"],
                   default="int16")
    p.add_argument("--time-rounds", action="store_true",
                   help="add measured rounds/s per row (second run)")
    p.add_argument("--donate", action="store_true",
                   help="buffer-donating scan (needed for N=32768 single-chip)")
    p.add_argument("--arc-align", type=int, default=1,
                   help="tile-aligned arc bases (random_arc only)")
    p.add_argument("--fanout", type=int, default=None,
                   help="override fanout (default log2(N))")
    p.add_argument("--t-fail-sweep", action="store_true",
                   help="sweep the (t_fail, t_suspect) knob surface at "
                        "fixed N instead of N")
    p.add_argument("--suspicion", action="store_true",
                   help="suspicion A/B rows (suspicion-on vs -off under "
                        "crash churn and a Bernoulli-loss scenario) — "
                        "the SUSPECT artifact")
    p.add_argument("--partition", action="store_true",
                   help="scenario-engine netsplit rows (split-brain "
                        "duration, view divergence, reconvergence) "
                        "instead of the TTD/FPR sweep")
    p.add_argument("--trace", type=str, default=None, metavar="PATH",
                   help="write each row's flight-recorder event stream "
                        "(obs/ JSONL; analyze with tools/timeline.py) — "
                        "TTD/FPR sweep rows only")
    p.add_argument("--monitor", action="store_true",
                   help="stream each row's decoded events through the "
                        "online invariant monitor (obs/monitor.py) and "
                        "stamp its verdict into the row — TTD/FPR sweep "
                        "rows only")
    p.add_argument("--merge-kernel", type=str, default="xla",
                   help="merge kernel for the --suspicion/--partition "
                        "rows (round 11: suspicion + scenarios run on "
                        "every kernel; e.g. pallas_rr_interpret for the "
                        "CPU form of the fused fast path)")
    p.add_argument("--elementwise", choices=["lanes", "swar"],
                   default="lanes",
                   help="elementwise form for the --suspicion/"
                        "--partition rows (swar = the packed-word fast "
                        "path; pulls in the all-int8 state)")
    p.add_argument("--out", type=str, default=None)
    args = p.parse_args(argv)
    if args.partition:
        doc = json.dumps(partition_sweep(
            ns=tuple(args.ns), merge_kernel=args.merge_kernel,
            elementwise=args.elementwise))
    elif args.suspicion:
        doc = json.dumps(suspicion_sweep(
            ns=tuple(args.ns), rounds=args.rounds,
            merge_kernel=args.merge_kernel,
            elementwise=args.elementwise))
    elif args.t_fail_sweep:
        doc = json.dumps(sweep_t_fail(rounds=args.rounds))
    else:
        doc = json.dumps(sweep(ns=tuple(args.ns), rounds=args.rounds,
                               topology=args.topology, donate=args.donate,
                               hb_dtype=args.hb_dtype,
                               time_rounds=args.time_rounds,
                               arc_align=args.arc_align,
                               fanout=args.fanout, trace=args.trace,
                               monitor=args.monitor))
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")


if __name__ == "__main__":
    sys.exit(main())
