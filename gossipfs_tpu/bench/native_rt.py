"""Native C++ engine benchmark: real-socket gossip faster than real time.

The reference's runtime is pinned to 1 round/s by its hardcoded 1 s
heartbeat driver (main.go:27-33).  The C++ epoll engine (native/engine.cc)
runs the same protocol over real localhost UDP datagrams with a
configurable period — this runner measures how much faster than the
reference's wall clock the native runtime sustains the full protocol
(send/receive/merge/detect per node per round), and checks a crash is
still detected in t_fail rounds:

  python -m gossipfs_tpu.bench.native_rt
  python -m gossipfs_tpu.bench.native_rt --n 48 --period 0.004

Prints one JSON line {n, period_s, rounds, elapsed_s, rounds_per_sec,
vs_reference, detection_rounds}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run(n: int = 32, period: float = 0.005, rounds: int = 200) -> dict:
    from gossipfs_tpu.native import NativeUdpDetector

    cluster = NativeUdpDetector(n, period=period, fresh_cooldown=True)
    try:
        warm = 12  # converge membership + pass the hb grace
        cluster.advance(warm)
        victim = n // 2
        crash_round = cluster.round
        cluster.crash(victim)
        t0 = time.perf_counter()
        cluster.advance(rounds)
        elapsed = time.perf_counter() - t0
        events = [e for e in cluster.drain_events() if e.subject == victim]
        detection_rounds = (
            min(e.round for e in events) - crash_round if events else -1
        )
        rps = rounds / elapsed
        return {
            "n": n,
            "period_s": period,
            "rounds": rounds,
            "elapsed_s": round(elapsed, 3),
            "rounds_per_sec": round(rps, 1),
            # the reference's driver advances 1 round per wall-clock second
            "vs_reference": round(rps, 1),
            "detection_rounds": detection_rounds,
        }
    finally:
        cluster.close()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=32)
    p.add_argument("--period", type=float, default=0.005)
    p.add_argument("--rounds", type=int, default=200)
    args = p.parse_args(argv)
    print(json.dumps(run(n=args.n, period=args.period, rounds=args.rounds)))


if __name__ == "__main__":
    sys.exit(main())
