"""The reference's real 10-VM README workflow on the process cluster.

Runs the exact scenario the reference's report measures (README.md:8-30,
main.go:14-35; report.pdf "Performance"): a 10-node cluster, ``put`` /
update / ``get`` of a 5 MB and a 10 MB file (the report's file5/file10
workload), ``ls``/``store`` listings, then a kill -9 of a replica holder
mid-workload and a byte-identity check on the post-repair ``get``.  Every
node is a real OS process with its own UDP gossip socket, RPC server,
store directory, and log (deploy/node.py) — the same topology the
reference ran across VMs, on localhost.

Prints one JSON line with insert/update/read wall-times per size plus
detection/repair seconds — the quantitative version of the report's
qualitative latency claims (insert ~ update, read slightly less, latency
grows with file size, flat in cluster size).

    python -m gossipfs_tpu.bench.ref_workflow            # full sizes
    python -m gossipfs_tpu.bench.ref_workflow --mb5 1 --mb10 2   # smoke
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import time

from gossipfs_tpu.deploy.launcher import Cluster
from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR


def run(n: int = 10, mb5: int = 5, mb10: int = 10, period: float = 0.5,
        root: str | None = None, timeout: float = 120.0) -> dict:
    # period 0.5 s (vs the tests' 0.1-0.2): ten Python gossip processes
    # plus multi-MB transfers on a often-loaded 1-core host can starve a
    # node past the t_fail*period failure timeout, false-positive the
    # master, and elect mid-put (observed; the commit then lands on a
    # plan-less new master and is refused).  The reference's own period
    # is 1 s with a 5 s timeout — 0.5 s keeps detection honest at half
    # the reference's latency while tolerating scheduler jitter.
    f5 = os.urandom(mb5 * 1024 * 1024)
    f10 = os.urandom(mb10 * 1024 * 1024)
    c = Cluster(n, period=period, root=root, rpc_timeout=60.0)
    own_root = root is None  # Cluster made its (prefixed) tempdir: clean it
    out: dict = {"metric": "reference 10-node README workflow "
                           "(real processes, localhost)",
                 "n": n, "file5_mb": mb5, "file10_mb": mb10,
                 "period_s": period}
    try:
        t0 = time.monotonic()
        c.start(timeout=timeout)
        out["boot_s"] = round(time.monotonic() - t0, 2)

        def timed(fn):
            t = time.monotonic()
            r = fn()
            return r, round(time.monotonic() - t, 3)

        ok, out["insert5_s"] = timed(lambda: c.client(1).put("file5.txt", f5))
        assert ok
        ok, out["insert10_s"] = timed(
            lambda: c.client(2).put("file10.txt", f10))
        assert ok
        # update = re-put within the 60 s window: the writer pre-confirms
        # the overwrite (the reference's stdin prompt, server.go:155-177)
        f5b = os.urandom(len(f5))
        ok, out["update5_s"] = timed(
            lambda: c.client(3).put("file5.txt", f5b, confirm=True))
        assert ok
        got, out["read5_s"] = timed(lambda: c.client(4).get("file5.txt"))
        assert got == f5b
        got, out["read10_s"] = timed(lambda: c.client(5).get("file10.txt"))
        assert got == f10

        holders5 = c.client(1).ls("file5.txt")
        holders10 = c.client(1).ls("file10.txt")
        assert len(holders5) == REPLICATION_FACTOR
        assert len(holders10) == REPLICATION_FACTOR
        stored = c.client(holders5[0]).store(holders5[0])
        assert "file5.txt" in stored

        # kill -9 a non-master replica holder mid-workload and read
        # through the failure window (the reference's CTRL+C crash)
        victim = next(h for h in holders5 if h != 0)
        observer = next(i for i in range(n) if i not in (victim, 0))
        c.kill9(victim)
        got, out["read5_during_failure_s"] = timed(
            lambda: c.client(observer).get("file5.txt"))
        assert got == f5b  # quorum survives 1 of 4 holders dying
        out["detect_s"] = round(
            c.wait_detected(victim, observer, timeout=timeout), 2)
        out["repair_s"] = round(
            c.wait_repaired("file5.txt", observer, REPLICATION_FACTOR,
                            timeout=timeout), 2)
        healed = set(c.client(observer).ls("file5.txt"))
        assert victim not in healed and len(healed) == REPLICATION_FACTOR
        got, out["read5_post_repair_s"] = timed(
            lambda: c.client(observer).get("file5.txt"))
        assert got == f5b
        out["post_repair_byte_identical"] = True
        out["ok"] = True
    finally:
        c.stop()
        if own_root:
            # ~60-80 MB of random replica payloads per run otherwise
            # accumulate in anonymous tempdirs
            shutil.rmtree(c.root, ignore_errors=True)
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=10)
    p.add_argument("--mb5", type=int, default=5)
    p.add_argument("--mb10", type=int, default=10)
    p.add_argument("--period", type=float, default=0.5)
    args = p.parse_args(argv)
    print(json.dumps(run(n=args.n, mb5=args.mb5, mb10=args.mb10,
                         period=args.period)))


if __name__ == "__main__":
    main()
