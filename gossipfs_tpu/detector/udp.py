"""Real-socket parity implementation: N gossip nodes over localhost UDP.

BASELINE config 1 is "10-node UDP gossip on localhost (Go-parity path)".  This
is a faithful re-implementation of the reference's wire behavior — full
member-list push to ring neighbours every period, ``<#ENTRY#>``/``<#INFO#>``
list framing and ``addr<CMD>VERB`` control datagrams (reference:
slave/slave.go:365-385, 293, 218), max-merge with local timestamping
(slave.go:414-440), timeout detection with hb<=1 grace (slave.go:460-482),
REMOVE broadcast (slave.go:338-363) and fail-list cooldown with the entry's
*original* timestamp (slave.go:276-286) — built on asyncio datagram endpoints
instead of goroutines, with a configurable period so tests run at 20x
real-time.  It satisfies the same FailureDetector interface as the TPU sim,
which is the whole point: consumers can't tell them apart.
"""

from __future__ import annotations

import asyncio
import random
import time

from gossipfs_tpu.detector.api import DetectionEvent

ENTRY_SEP = "<#ENTRY#>"
FIELD_SEP = "<#INFO#>"
CMD_SEP = "<CMD>"
# Delta-piggyback frame marker (protocol_spec.DELTA_GOSSIP wire_mark):
# a delta payload is the full-list wire format prefixed by this token;
# the receiver strips it and runs the SAME hardened per-entry max-merge.
DELTA_MARK = "<#DELTA#>"


class _Member:
    __slots__ = ("hb", "ts", "ver")

    def __init__(self, hb: float, ts: float, ver: int = 0):
        self.hb = int(hb)
        self.ts = ts
        # monotone change version (delta gossip): stamped from the
        # owner node's counter whenever this entry materially changes —
        # add, heartbeat/incarnation advance, self bump.  Per-peer
        # cursors compare against it to pick the changed-first slice.
        self.ver = ver


class _NodeProtocol(asyncio.DatagramProtocol):
    def __init__(self, node: "UdpNode"):
        self.node = node

    def datagram_received(self, data: bytes, addr) -> None:
        self.node.handle(data.decode(), addr)


class UdpNode:
    """One gossip process: UDP endpoint + heartbeat task."""

    def __init__(self, cluster: "UdpCluster", idx: int, port: int):
        self.cluster = cluster
        self.idx = idx
        self.port = port
        self.addr = f"127.0.0.1:{port}"
        self.alive = False
        self.members: dict[str, _Member] = {}
        self.fail_list: dict[str, float] = {}  # addr -> entry's last ts
        self.transport: asyncio.DatagramTransport | None = None
        self._hb_task: asyncio.Task | None = None
        # protocol rounds THIS node has ticked — the node's own logical
        # clock.  Deploy logs stamp it so latency assertions count
        # protocol rounds instead of widenable wall-clock windows, and it
        # stalls exactly when the process is starved (unlike wall time).
        self.rounds = 0
        self.last_tick_error: Exception | None = None
        # suspicion subsystem (suspicion/): per-node suspect table, armed
        # when the cluster (or deploy _Env) carries SuspicionParams.
        # (params, runtime) pair so a mid-run re-arm rebuilds the table
        self._sus: tuple[object, object] | None = None
        self._last_refute_t = float("-inf")  # rate-limits REFUTE broadcasts
        # per-node stream for the random-push topology draw (the
        # north-star campaign profile; unused in the reference ring mode)
        self._rng = random.Random(0x5EED ^ (idx * 2654435761))
        # delta gossip state (protocol_spec DELTA_GOSSIP): the node's
        # monotone change counter, the per-peer change cursors (last
        # version pushed to that peer), and the round-robin refresh
        # cursor over the stable tail
        self._ver = 0
        self._sent_ver: dict[str, int] = {}
        self._refresh_pos = 0

    def _suspicion(self):
        """The armed SuspicionRuntime, tracking the host's params."""
        params = getattr(self.cluster, "suspicion", None)
        if params is None:
            self._sus = None
            return None
        if self._sus is None or self._sus[0] is not params:
            from gossipfs_tpu.suspicion.runtime import SuspicionRuntime

            self._sus = (params, SuspicionRuntime(params))
        return self._sus[1]

    def _obs(self, kind: str, subject_addr: str, **detail) -> None:
        """Flight-recorder seam (obs/): the host — the in-process
        UdpCluster or the deploy daemon's _Env — decides whether a
        recorder/structured log is armed and stamps its own round clock.
        A host without the hook costs one getattr per event site."""
        hook = getattr(self.cluster, "record_obs", None)
        if hook is not None:
            hook(kind, self.idx, subject_addr, **detail)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: _NodeProtocol(self), local_addr=("127.0.0.1", self.port)
        )
        self.alive = True
        self.members = {self.addr: _Member(0, self._now())}
        self._ver = 0
        self._sent_ver = {}
        self._refresh_pos = 0
        self._hb_task = asyncio.create_task(self._heartbeat_loop())

    def stop(self, graceful: bool = False) -> None:
        """graceful=False models CTRL+C (crash-stop, README.md:30)."""
        if graceful and self.alive:
            msg = f"{self.addr}{CMD_SEP}LEAVE"
            for peer in list(self.members):
                if peer != self.addr:
                    self._send(peer, msg)
        self.alive = False
        if self._hb_task:
            self._hb_task.cancel()
        if self.transport:
            self.transport.close()
            self.transport = None

    def _now(self) -> float:
        return time.monotonic()

    def _send(self, peer_addr: str, msg: str) -> None:
        if self.transport is None:
            return
        # scenario engine send hook (scenarios/): the cluster (or the
        # deploy daemon's _Env) decides per datagram whether an armed
        # fault rule — partition, Bernoulli link loss, slow sender —
        # drops it.  Dropping HERE models the network, so heartbeats,
        # JOIN/LEAVE/REMOVE verbs and list pushes are all affected alike.
        allowed = getattr(self.cluster, "message_allowed", None)
        if allowed is not None and not allowed(self.idx, peer_addr):
            return
        # wire accounting (the delta-gossip A/B surface): payload bytes
        # actually handed to the transport, split full-list vs delta
        account = getattr(self.cluster, "account_send", None)
        if account is not None:
            account(msg)
        host, port = peer_addr.rsplit(":", 1)
        self.transport.sendto(msg.encode(), (host, int(port)))

    # -- wire codec (slave.go:365-385) -------------------------------------
    def _encode(self) -> str:
        return ENTRY_SEP.join(
            f"{a}{FIELD_SEP}{m.hb}{FIELD_SEP}{m.ts}" for a, m in self.members.items()
        )

    def _bump(self) -> int:
        """Advance the node's change counter (delta gossip versioning)."""
        self._ver += 1
        return self._ver

    def _encode_delta(self, peer: str) -> str:
        """One bounded delta frame for ``peer`` — the protocol_spec
        DELTA_GOSSIP entry-selection rule: entries whose version
        advanced past the per-peer cursor, most recently changed first,
        then round-robin refresh of the stable tail in any leftover
        capacity, capped at ``delta_entries``.  A peer with no cursor
        yet (first contact) gets the full list instead."""
        c = self.cluster
        cursor = self._sent_ver.get(peer)
        self._sent_ver[peer] = self._ver
        if cursor is None:
            return self._encode()
        cap = c.delta_entries
        changed = [(a, m) for a, m in self.members.items() if m.ver > cursor]
        changed.sort(key=lambda am: am[1].ver, reverse=True)
        picks = changed[:cap]
        if len(picks) < cap and len(self.members) > len(picks):
            # round-robin refresh of the stable tail
            addrs = sorted(self.members)
            seen = {a for a, _ in picks}
            taken = 0
            for k in range(len(addrs)):
                if len(picks) >= cap:
                    break
                a = addrs[(self._refresh_pos + k) % len(addrs)]
                if a not in seen:
                    picks.append((a, self.members[a]))
                    seen.add(a)
                taken = k + 1
            self._refresh_pos = (self._refresh_pos + taken) % len(addrs)
        return DELTA_MARK + ENTRY_SEP.join(
            f"{a}{FIELD_SEP}{m.hb}{FIELD_SEP}{m.ts}" for a, m in picks
        )

    @staticmethod
    def _decode(payload: str) -> list[tuple[str, int, float | None]]:
        out = []
        for chunk in payload.split(ENTRY_SEP):
            parts = chunk.split(FIELD_SEP)
            if len(parts) >= 2:
                # wire-derived fields are untrusted: skip entries whose hb
                # does not parse instead of aborting the whole datagram —
                # the native codec's DecodeMembers semantics.  The old
                # raise lost every VALID entry sharing a datagram with one
                # bad chunk (conformance malformed_codec: a refuting
                # incarnation advance rides with a truncated entry; losing
                # it confirms a live node dead — the committed
                # regressions/conformance_malformed_udp.json repro)
                try:
                    hb = int(float(parts[1]))
                except ValueError:
                    continue
                # the wire ts (delta mode merges it on EQUAL counters);
                # an unparsable ts degrades the entry to hb-only, it
                # does not drop it
                ts = None
                if len(parts) >= 3:
                    try:
                        ts = float(parts[2])
                    except ValueError:
                        ts = None
                out.append((parts[0], hb, ts))
        return out

    # -- receive dispatch (GetMsg, slave.go:207-248) ------------------------
    def handle(self, payload: str, src) -> None:
        if not self.alive:
            return
        if CMD_SEP in payload:
            arg, verb = payload.split(CMD_SEP, 1)
            if verb == "JOIN":
                self._add_member(arg)
            elif verb in ("LEAVE", "REMOVE"):
                self._remove_member(arg)
            elif verb == "SUSPECT":
                self._on_suspect(arg)
            elif verb == "REFUTE":
                self._on_refute(arg)
        elif payload.startswith(DELTA_MARK):
            # delta frame: strip the marker and run the SAME hardened
            # per-entry max-merge — a truncated or replayed delta
            # degrades to a smaller merge, never a protocol error
            self._merge(self._decode(payload[len(DELTA_MARK):]))
        else:
            self._merge(self._decode(payload))

    # -- suspicion wire verbs (SWIM suspect/refute, suspicion/) -------------
    def _on_suspect(self, addr: str) -> None:
        """A peer broadcast ``addr<CMD>SUSPECT``.

        If the suspect is ME: refute by INCARNATION BUMP — advance my own
        heartbeat counter past whatever the suspicion was based on and
        broadcast a REFUTE carrying it (SWIM's alive message; the next
        list pushes carry the bumped counter too).  Otherwise adopt the
        suspicion: an observer whose OWN entry is already stale inherits
        the earlier suspect-start and confirms sooner than its local
        timer alone would.  An observer whose entry is still fresh
        discards the adoption at its next tick — local freshness IS
        refuting evidence (SWIM's alive-over-suspect rule), and honoring
        a foreign timer across it would let a later staleness confirm
        without serving any suspect window.
        """
        rt = self._suspicion()
        if rt is None:
            return
        if addr == self.addr:
            me = self.members.get(self.addr)
            if me is None:
                return
            now = self._now()
            if now - self._last_refute_t < self.cluster.period:
                # k observers suspecting the same episode each broadcast
                # SUSPECT to everyone, so k*(N-1) copies land here; one
                # bump + one REFUTE broadcast per period answers the
                # whole episode (SWIM refutes once per incarnation)
                # instead of amplifying to O(k*N) datagrams
                return
            self._last_refute_t = now
            me.hb += 1
            me.ts = now
            me.ver = self._bump()
            msg = f"{self.addr}{FIELD_SEP}{me.hb}{CMD_SEP}REFUTE"
            for peer in list(self.members):
                if peer != self.addr:
                    self._send(peer, msg)
        elif addr in self.members:
            rt.adopt(addr, self._now())

    def _on_refute(self, arg: str) -> None:
        """``addr<#INFO#>hb<CMD>REFUTE``: the suspect's alive message.

        Receiving it at all proves the sender was alive a datagram ago:
        adopt the bumped incarnation, stamp fresh, and cancel any pending
        suspicion.  A confirmed (fail-listed) entry is NOT resurrected —
        the cooldown suppression wins, as it does for list gossip
        (slave.go:430-439); the node rejoins through the introducer.
        """
        parts = arg.split(FIELD_SEP)
        addr = parts[0]
        hb = int(float(parts[1])) if len(parts) > 1 else 0
        m = self.members.get(addr)
        if m is None:
            return
        if hb > m.hb:
            m.hb = hb
            m.ver = self._bump()
        m.ts = self._now()
        rt = self._suspicion()
        if rt is not None and rt.refute(addr):
            self._obs("refute", addr)

    def _add_member(self, addr: str) -> None:
        """Introducer path: append + push full list to everyone
        (addNewMember, slave.go:250-274)."""
        if addr not in self.members:
            self.members[addr] = _Member(0, self._now(), self._bump())
        msg = self._encode()
        for peer in list(self.members):
            if peer != self.addr:
                self._send(peer, msg)

    def _remove_member(self, addr: str) -> None:
        """Move the entry onto the fail list (removeMember, slave.go:276-286).

        Faithful mode keeps the entry's existing (stale) timestamp, which
        gives detector-removed entries a near-zero cooldown; when message
        latency + scheduling jitter is non-trivial relative to the period,
        that sustains an endemic re-add/re-detect limit cycle (observed both
        here and in the tensor sim).  fresh_cooldown stamps removal time
        instead, restoring a real suppression window.
        """
        member = self.members.pop(addr, None)
        if member is not None and addr not in self.fail_list:
            self.fail_list[addr] = (
                self._now() if self.cluster.fresh_cooldown else member.ts
            )
            self._obs("remove", addr)
        if self._sus is not None:
            # removed for any reason (LEAVE, a peer's REMOVE): forget the
            # pending suspicion (a confirm already popped it, uncounted)
            self._sus[1].drop(addr)

    def _merge(self, remote: list[tuple[str, int, float | None]]) -> None:
        """Anti-entropy max-merge with local stamping (slave.go:414-440)."""
        now = self._now()
        rt = self._sus[1] if self._sus is not None else None
        delta_mode = getattr(self.cluster, "delta", False)
        for addr, hb, wire_ts in remote:
            local = self.members.get(addr)
            if local is not None:
                if hb > local.hb:
                    local.hb = hb
                    local.ts = now
                    local.ver = self._bump()
                    if rt is not None and rt.refute(addr):
                        # refute-by-advance: a fresher counter observed
                        # while SUSPECT cancels the pending failure
                        self._obs("refute", addr)
                elif (delta_mode and hb == local.hb
                      and wire_ts is not None and wire_ts > local.ts):
                    # delta mode only: freshness rides the wire on EQUAL
                    # counters (the native Merge's twin).  Bounded frames
                    # break the full-list assumption that every round
                    # max-merges fanout fresh draws — after a synchronized
                    # anti-entropy round most nodes hold the SAME hb for
                    # an entry, so the next full push carries no advance
                    # and local-stamp-only ts ages toward t_fail on a
                    # QUIET cluster.  Max-merging the wire ts closes it
                    # without breaking crash detection (a crashed node's
                    # copies converge to a constant max, so staleness
                    # still grows globally); clamped to now so a forged
                    # future ts cannot suppress detection.
                    local.ts = min(wire_ts, now)
            elif addr not in self.fail_list:
                self.members[addr] = _Member(hb, now, self._bump())

    # -- heartbeat tick (HeartBeat, slave.go:499-544) -----------------------
    async def _heartbeat_loop(self) -> None:
        period = self.cluster.period
        while self.alive:
            await asyncio.sleep(period)
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001
                # a tick that throws must not silently kill the heartbeat
                # task: the node would freeze mid-protocol (peers see its
                # counter stop at the last pushed value — and if that is
                # still within the hb<=1 grace, slave.go:468, it becomes
                # PERMANENTLY undetectable).  Record and keep ticking.
                self.last_tick_error = e

    def tick(self) -> None:
        c = self.cluster
        now = self._now()
        if not self.alive:
            return
        self.rounds += 1
        if len(self.members) < c.min_group:
            for m in self.members.values():
                m.ts = now  # refresh-only (slave.go:504-509)
            return
        me = self.members.get(self.addr)
        if me is not None:
            me.hb += 1
            me.ts = now
            me.ver = self._bump()
        # detection (slave.go:460-482); with suspicion armed (suspicion/)
        # a stale member passes through SUSPECT first: the first stale
        # tick broadcasts SUSPECT (so the subject can actively refute by
        # incarnation bump — see _on_suspect), and only t_suspect more
        # periods of silence confirm the removal.  The confirm keeps the
        # reference's REMOVE broadcast; a refresh before it (list gossip
        # advance or a REFUTE) cancels the suspicion in _merge/_on_refute.
        t_fail = c.t_fail * c.period
        rt = self._suspicion()
        for addr in list(self.members):
            if addr == self.addr:
                continue
            m = self.members[addr]
            stale = m.hb > 1 and m.ts < now - t_fail
            if not stale:
                if rt is not None:
                    # a genuinely-refuted suspicion was already popped
                    # (and counted) by _merge/_on_refute when the fresh
                    # evidence arrived; anything left here is a
                    # peer-disseminated adoption for an entry that was
                    # never stale locally — clear it WITHOUT counting a
                    # refutation (no evidence-of-life event happened)
                    rt.drop(addr)
                continue
            if rt is not None:
                if rt.suspect(addr, now):
                    self._obs("suspect", addr)
                    msg = f"{addr}{CMD_SEP}SUSPECT"
                    if c.push == "random":
                        # campaign profile: bounded dissemination
                        # (protocol_spec new_suspect/campaign, shared
                        # with the native engine) — the SUBJECT always
                        # hears (its active incarnation-bump refute is
                        # the point) plus fanout random peers, O(fanout)
                        # per new suspicion like every other push in
                        # this mode.  The all-peers broadcast below is
                        # O(suspects x N) per round: at cohort sizes a
                        # rack outage makes every observer suspect the
                        # whole rack in one tick.
                        self._send(addr, msg)
                        peers = [a for a in self.members
                                 if a != self.addr and a != addr]
                        for peer in self._rng.sample(
                                peers, min(c.fanout, len(peers))):
                            self._send(peer, msg)
                    else:
                        # reference-faithful ring mode: all-peers
                        # broadcast, kept verbatim for the small-n
                        # udp-parity lane
                        for peer in list(self.members):
                            if peer != self.addr:
                                self._send(peer, msg)
                    continue
                window = rt.t_suspect_window(c.period, len(self.members))
                if not rt.expired(addr, now, window):
                    # periodic re-notification (round 16, shared with the
                    # native engine): the original SUSPECT broadcast may
                    # have been sent into a fault window — a rack outage
                    # drops it, so the subject never learns and the
                    # post-heal refute wave rides passive list gossip
                    # alone, leaking a bi-modal heal-race FP burst.  One
                    # subject-only datagram per suspect per tick triggers
                    # the active incarnation-bump refute the moment the
                    # subject is reachable again; the REFUTE broadcast is
                    # rate-limited on the subject's side, so k
                    # re-notifiers cost one bump per period.
                    self._send(addr, f"{addr}{CMD_SEP}SUSPECT")
                    continue
                rt.confirm(addr)
            # detection first, then the removal it causes — the same
            # confirm -> remove causal order the tensor engine's events
            # carry (the flight-recorder parity tests compare sequences)
            c.record_detection(self.idx, addr)
            self._remove_member(addr)
            if c.remove_broadcast:
                msg = f"{addr}{CMD_SEP}REMOVE"
                for peer in list(self.members):
                    if peer != self.addr:
                        self._send(peer, msg)
        # fail-list cooldown (slave.go:484-497)
        t_cool = c.t_cooldown * c.period
        for addr in list(self.fail_list):
            if self.fail_list[addr] < now - t_cool:
                del self.fail_list[addr]
        # membership refresh push.  Delta mode (protocol_spec
        # membership_refresh/delta, round 20): every anti_entropy_every-th
        # round — cluster-round aligned, all nodes tick on the same
        # clock — pushes the FULL list so a lost delta can never wedge
        # convergence; every other round sends a bounded per-peer delta
        # frame (_encode_delta: changed-first, rr tail, capped).
        anti_entropy = (not c.delta
                        or self.rounds % c.anti_entropy_every == 0)
        full_msg = self._encode() if anti_entropy else None

        def refresh(peer: str) -> str:
            if anti_entropy:
                if c.delta:
                    # a full list covers everything: advance the cursor
                    self._sent_ver[peer] = self._ver
                return full_msg
            return self._encode_delta(peer)

        if c.push == "random":
            # north-star / campaign push topology: fanout random listed
            # peers per tick (the tensor engine's topology='random' —
            # event propagation in O(log N) rounds instead of the ring's
            # O(N) position walk; see UdpCluster's push notes)
            peers = [a for a in self.members if a != self.addr]
            for peer in self._rng.sample(peers,
                                         min(c.fanout, len(peers))):
                self._send(peer, refresh(peer))
            return
        # ring push to list positions self-1, self+1, self+2 (slave.go:515-542)
        ordered = sorted(self.members)
        if self.addr not in ordered:
            return  # removed-self edge case: no push targets defined
        i = ordered.index(self.addr)
        n = len(ordered)
        for off in (-1, 1, 2):
            peer = ordered[(i + off) % n]
            if peer != self.addr:
                self._send(peer, refresh(peer))


class UdpCluster:
    """FailureDetector over real localhost sockets (asyncio-driven)."""

    def __init__(
        self,
        n: int,
        base_port: int = 18000,
        period: float = 0.05,
        t_fail: int = 5,
        t_cooldown: int = 5,
        min_group: int = 4,
        fresh_cooldown: bool = False,
        scenario=None,
        suspicion=None,
        push: str = "ring",
        fanout: int | None = None,
        remove_broadcast: bool = True,
        delta: bool = False,
        delta_entries: int = 16,
        anti_entropy_every: int = 4,
    ):
        self.n = n
        self.period = period
        self.t_fail = t_fail
        self.t_cooldown = t_cooldown
        self.min_group = min_group
        self.fresh_cooldown = fresh_cooldown
        # protocol-mode knobs (round 14): the reference defaults are the
        # Go-parity wire behavior — ring-position pushes + the REMOVE
        # broadcast.  ``push="random"`` + ``remove_broadcast=False`` is
        # the NORTH-STAR profile the tensor campaigns run (SimConfig
        # topology='random' + gossip-only dissemination): fanout random
        # peers per tick, removal by local timeout only.  The ring walks
        # an event (a heal's fresh counter, a refutation) ~3 positions
        # per tick, so any fault longer than t_fail - n/3 rounds storms
        # distant observers BY TOPOLOGY at campaign cohort sizes; the
        # random push propagates in O(log N) rounds like the tensor
        # engine, which is what makes cross-engine verdict agreement a
        # protocol comparison instead of a topology artifact
        # (campaigns/engines.py).
        if push not in ("ring", "random"):
            raise ValueError(f"unknown push mode: {push!r}")
        self.push = push
        self.fanout = fanout if fanout is not None else max(
            2, (n - 1).bit_length())
        self.remove_broadcast = remove_broadcast
        # delta-piggyback dissemination (round 20, protocol_spec
        # DELTA_GOSSIP): per-round refresh pushes carry a bounded
        # changed-first + rr-tail slice instead of the full O(N) list,
        # with a cluster-round-aligned full-list anti-entropy push every
        # anti_entropy_every rounds.  The cadence must stay strictly
        # below t_fail (the contract constraint): a receiver's last
        # refresh of a live entry is then at most anti_entropy_every
        # rounds old, so delta mode cannot manufacture staleness.
        if delta and anti_entropy_every >= t_fail:
            raise ValueError(
                f"anti_entropy_every={anti_entropy_every} must stay "
                f"strictly below t_fail={t_fail} (protocol_spec "
                "DELTA_GOSSIP constraint — a refresh gap past the "
                "detection window manufactures false positives)")
        self.delta = delta
        self.delta_entries = delta_entries
        self.anti_entropy_every = anti_entropy_every
        # wire accounting (the delta A/B surface): cumulative payload
        # bytes handed to sendto + the full-list vs delta frame split
        self._bytes_sent = 0
        self._frames_full = 0
        self._frames_delta = 0
        # suspicion subsystem (suspicion/): SuspicionParams or None; the
        # nodes read it every tick, so (dis)arming mid-run takes effect
        # on their next heartbeat
        self.suspicion = suspicion
        self.nodes = [UdpNode(self, i, base_port + i) for i in range(n)]
        self._addr_to_idx = {node.addr: i for i, node in enumerate(self.nodes)}
        self._events: list[DetectionEvent] = []
        self._round = 0
        self.introducer = 0
        # flight recorder (obs/) + cumulative vitals counters (events
        # drain, so the `metrics` surface needs its own accounting)
        self._recorder = None
        self._det_total = 0
        self._fp_total = 0
        # scenario engine (scenarios/): armed rule table + the cluster
        # round it was armed at (rule windows are arming-relative)
        self._scn_runtime = None
        self._scn_round0 = 0
        if scenario is not None:
            self.load_scenario(scenario)

    # -- scenario engine ----------------------------------------------------
    def load_scenario(self, scenario) -> None:
        """Arm a scenarios.FaultScenario; windows count from NOW (the
        current cluster round).  Same rule table and semantics as the
        tensor sim's edge filter and the deploy daemons' pushed table."""
        from gossipfs_tpu.scenarios.runtime import ScenarioRuntime

        if scenario.n != self.n:
            raise ValueError(
                f"scenario is for n={scenario.n}, cluster has n={self.n}"
            )
        self._scn_runtime = ScenarioRuntime(scenario)
        self._scn_round0 = self._round
        self._rec_cluster("scenario_arm", -1, name=scenario.name,
                          horizon=scenario.horizon)

    def clear_scenario(self) -> None:
        if self._scn_runtime is not None:
            self._rec_cluster("scenario_clear", -1)
        self._scn_runtime = None

    def scenario_status(self) -> dict | None:
        if self._scn_runtime is None:
            return None
        return self._scn_runtime.status(self._round - self._scn_round0)

    # -- suspicion subsystem ------------------------------------------------
    def load_suspicion(self, params) -> None:
        """Arm a suspicion.SuspicionParams on every node (None disarms);
        takes effect on each node's next heartbeat tick."""
        self.suspicion = params

    def clear_suspicion(self) -> None:
        self.suspicion = None

    def suspects(self, observer: int) -> list[int]:
        """Node ids the observer currently holds SUSPECT."""
        sus = self.nodes[observer]._sus
        if sus is None:
            return []
        return sorted(
            self._addr_to_idx[a] for a in sus[1].suspects
            if a in self._addr_to_idx
        )

    def suspicion_status(self) -> dict | None:
        """Cluster-wide suspicion vitals: per-node live suspect counts +
        cumulative lifecycle totals — the tensor sim's document shape
        (SimDetector.suspicion_status) minus ``fp_suppressed``, which
        needs the ground-truth aliveness only the sim has per refute (a
        consumer reading the real-socket engine must not mistake an
        unknowable for a zero)."""
        if self.suspicion is None:
            return None
        counts: dict[int, int] = {}
        entered = refutations = confirms = 0
        for i, node in enumerate(self.nodes):
            if node._sus is None:
                continue
            rt = node._sus[1]
            if node.alive and rt.suspects:
                counts[i] = len(rt.suspects)
            entered += rt.entered
            refutations += rt.refutations
            confirms += rt.confirms
        return {
            "enabled": True,
            "t_suspect": self.suspicion.t_suspect,
            "lh_multiplier": self.suspicion.lh_multiplier,
            "suspect_counts": counts,
            "suspects_now": sum(counts.values()),
            "suspects_entered": entered,
            "refutations": refutations,
            "confirms": confirms,
        }

    def account_send(self, msg: str) -> None:
        """The UdpNode._send accounting hook (wire-plane vitals)."""
        self._bytes_sent += len(msg)
        if msg.startswith(DELTA_MARK):
            self._frames_delta += 1
        elif CMD_SEP not in msg:
            self._frames_full += 1

    def message_allowed(self, src: int, peer_addr: str) -> bool:
        """The UdpNode._send hook: False = the armed scenario drops it."""
        rt = self._scn_runtime
        if rt is None:
            return True
        dst = self._addr_to_idx.get(peer_addr)
        if dst is None:
            return True
        return not rt.drops(src, dst, self._round - self._scn_round0)

    # -- flight recorder (obs/) ---------------------------------------------
    def attach_recorder(self, recorder) -> None:
        """Arm an obs.FlightRecorder on the UdpNode tick/receive seams."""
        self._recorder = recorder

    def record_obs(self, kind: str, observer: int, subject_addr: str,
                   **detail) -> None:
        """UdpNode._obs lands here; the cluster stamps its round clock."""
        if self._recorder is None:
            return
        from gossipfs_tpu.obs.schema import Event

        subject = self._addr_to_idx.get(subject_addr, -1)
        self._recorder.emit(Event(round=self._round, observer=observer,
                                  subject=subject, kind=kind,
                                  detail=detail))

    def _rec_cluster(self, kind: str, subject: int, **detail) -> None:
        if self._recorder is None:
            return
        from gossipfs_tpu.obs.schema import Event

        self._recorder.emit(Event(round=self._round, observer=-1,
                                  subject=subject, kind=kind,
                                  detail=detail))

    def vitals(self) -> dict:
        """The uniform counter set (obs.schema.VITALS_FIELDS).  This
        engine knows ground-truth aliveness (in-process), so
        false_positives is live; ``fp_suppressed`` stays absent — the
        per-refute ground truth only the sim has (rendered n/a)."""
        doc = {
            "engine": "udp",
            "round": self._round,
            "n_alive": len(self.alive_nodes()),
            "detections": self._det_total,
            "false_positives": self._fp_total,
            "bytes_sent": self._bytes_sent,
            "frames_full": self._frames_full,
            "frames_delta": self._frames_delta,
        }
        sus = self.suspicion_status()
        if sus is not None:
            doc.update({k: sus[k] for k in (
                "suspects_now", "suspects_entered", "refutations",
                "confirms") if k in sus})
        mon = getattr(self._recorder, "monitor", None)
        if mon is not None:
            # a MonitorRecorder attached: the live invariant verdict
            # (absent otherwise -> rendered n/a, the round-8 rule)
            doc["invariant_violations"] = len(mon.violations)
        return doc

    def record_detection(self, observer: int, subject_addr: str) -> None:
        subject = self._addr_to_idx.get(subject_addr)
        if subject is None:
            # a wire-learned address outside the cluster (a stray
            # datagram from a port-space neighbour merged a ghost
            # member): the removal already happened at the caller —
            # nothing to account.  Raising here aborted the observer's
            # tick at the detection step EVERY period (the ghost stays
            # stale), which froze its pushes and stormed the cluster
            # with real FPs; the native engine's IdxOf guard is the
            # same contract.
            return
        fp = self.nodes[subject].alive
        self._det_total += 1
        self._fp_total += int(fp)
        self._events.append(
            DetectionEvent(
                round=self._round,
                observer=observer,
                subject=subject,
                false_positive=fp,
            )
        )
        self.record_obs("confirm", observer, subject_addr,
                        false_positive=bool(fp))

    # -- async lifecycle ----------------------------------------------------
    async def start_all(self) -> None:
        for node in self.nodes:
            await node.start()
        # everyone joins through the introducer (slave.go:288-308)
        intro = self.nodes[self.introducer]
        for node in self.nodes:
            if node.idx != self.introducer:
                node._send(intro.addr, f"{node.addr}{CMD_SEP}JOIN")
        await asyncio.sleep(self.period)

    def seed_full_membership(self) -> None:
        """Start from the fully-joined steady state the tensor engine's
        ``init_state`` models: every node lists everyone at hb 0 with a
        fresh local stamp (inside the hb<=1 detection grace, exactly
        like the sim's hb_grace).  The protocol boot instead funnels
        N-1 JOINs through the introducer — an O(N^2) full-list push
        burst that takes minutes (and drops datagrams) at campaign
        cohort sizes; the campaign runner (campaigns/engines.py) seeds
        and lets the counters flow for a couple of periods instead."""
        now = time.monotonic()
        addrs = [node.addr for node in self.nodes]
        for node in self.nodes:
            if node.alive:
                node.members = {a: _Member(0, now) for a in addrs}

    async def run(self, rounds: int, emit_round_ticks: bool = False) -> None:
        """Advance the cluster clock ``rounds`` heartbeat periods.

        ``emit_round_ticks`` (round 14, the socket campaign runner):
        emit one ``round_tick`` schema event per period carrying the
        ground truth this in-process engine KNOWS — n_alive and the
        period's detection/false-positive deltas (plus the suspicion
        counters when armed) — so a recorded udp stream feeds the
        streaming monitor's rolling-FPR invariant exactly like a tensor
        trace.  ``fp_suppressed`` stays absent (per-refute ground truth
        is sim-only — the n/a-not-0 rule).
        """
        for _ in range(rounds):
            det0, fp0 = self._det_total, self._fp_total
            sus0 = self.suspicion_status() if emit_round_ticks else None
            await asyncio.sleep(self.period)
            if emit_round_ticks:
                det_d = self._det_total - det0
                fp_d = self._fp_total - fp0
                detail = {
                    "n_alive": len(self.alive_nodes()),
                    "true_detections": det_d - fp_d,
                    "false_positives": fp_d,
                }
                sus1 = self.suspicion_status()
                if sus0 is not None and sus1 is not None:
                    detail["suspects_entered"] = (
                        sus1["suspects_entered"] - sus0["suspects_entered"])
                    detail["refutations"] = (
                        sus1["refutations"] - sus0["refutations"])
                self._rec_cluster("round_tick", -1, **detail)
            self._round += 1

    # -- FailureDetector verbs (used inside the event loop) -----------------
    def crash(self, node: int) -> None:
        self.nodes[node].stop(graceful=False)
        self._rec_cluster("crash", node)
        self._rec_cluster("hb_freeze", node)

    def leave(self, node: int) -> None:
        self.nodes[node].stop(graceful=True)
        self._rec_cluster("leave", node)

    async def join(self, node: int) -> None:
        """(Re)start a node's process and send JOIN to the introducer
        (slave.go:288-308).  Lost if the introducer is down — SPOF kept."""
        n = self.nodes[node]
        if not n.alive:
            await n.start()
        n._send(self.nodes[self.introducer].addr, f"{n.addr}{CMD_SEP}JOIN")
        self._rec_cluster("join", node)

    def membership(self, observer: int) -> list[int]:
        return sorted(
            self._addr_to_idx[a]
            for a in self.nodes[observer].members
            if a in self._addr_to_idx
        )

    def alive_nodes(self) -> list[int]:
        return [i for i, node in enumerate(self.nodes) if node.alive]

    def drain_events(self) -> list[DetectionEvent]:
        out, self._events = self._events, []
        return out

    def stop_all(self) -> None:
        for node in self.nodes:
            if node.alive:
                node.stop()


class UdpDetector:
    """Synchronous FailureDetector facade over UdpCluster.

    Runs the asyncio event loop on a background thread so the UDP parity path
    is drop-in interchangeable with detector/sim.SimDetector — same verbs,
    same views, real datagrams underneath.  ``advance(r)`` blocks for r
    heartbeat periods of wall time (this detector runs in real time; the sim
    runs as fast as the chip allows — that asymmetry is the whole point).
    """

    def __init__(self, n: int, **cluster_kwargs):
        import concurrent.futures
        import threading

        self.cluster = UdpCluster(n, **cluster_kwargs)
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)
        self._thread.start()
        self._call(self.cluster.start_all()).result(timeout=30)
        self._futures = concurrent.futures

    def _call(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def _sync(self, fn, *args):
        async def run():
            return fn(*args)

        return self._call(run()).result(timeout=30)

    # -- FailureDetector protocol ------------------------------------------
    def join(self, node: int) -> None:
        self._call(self.cluster.join(node)).result(timeout=30)

    def leave(self, node: int) -> None:
        self._sync(self.cluster.leave, node)

    def crash(self, node: int) -> None:
        self._sync(self.cluster.crash, node)

    def advance(self, rounds: int = 1) -> None:
        self._call(self.cluster.run(rounds)).result(timeout=30 + rounds)

    def membership(self, observer: int) -> list[int]:
        return self._sync(self.cluster.membership, observer)

    def alive_nodes(self) -> list[int]:
        return self._sync(self.cluster.alive_nodes)

    def drain_events(self):
        return self._sync(self.cluster.drain_events)

    # -- scenario engine (executed on the cluster's own loop thread) --------
    def load_scenario(self, scenario) -> None:
        self._sync(self.cluster.load_scenario, scenario)

    def clear_scenario(self) -> None:
        self._sync(self.cluster.clear_scenario)

    def scenario_status(self):
        return self._sync(self.cluster.scenario_status)

    # -- observability (same thread discipline) -----------------------------
    def attach_recorder(self, recorder) -> None:
        self._sync(self.cluster.attach_recorder, recorder)

    def vitals(self) -> dict:
        return self._sync(self.cluster.vitals)

    # -- suspicion subsystem (same thread discipline) -----------------------
    def load_suspicion(self, params) -> None:
        self._sync(self.cluster.load_suspicion, params)

    def clear_suspicion(self) -> None:
        self._sync(self.cluster.clear_suspicion)

    def suspicion_status(self):
        return self._sync(self.cluster.suspicion_status)

    def suspects(self, observer: int) -> list[int]:
        return self._sync(self.cluster.suspects, observer)

    def close(self) -> None:
        self._sync(self.cluster.stop_all)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)
