"""TPU-sim implementation of the FailureDetector interface.

Wraps the batched round kernel (core/rounds.py) behind the per-node verbs the
CLI / SDFS shim consume.  Interactive path: one jitted ``gossip_round`` per
``advance``; bulk experiments should call ``core.rounds.run_rounds`` directly
(scan, no per-round host sync).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import gossip_round
from gossipfs_tpu.core.state import MEMBER, RoundEvents, SimState, init_state
from gossipfs_tpu.detector.api import DetectionEvent


class SimDetector:
    """N simulated gossip nodes advanced one tensor step per heartbeat."""

    def __init__(
        self,
        config: SimConfig,
        member_mask: np.ndarray | None = None,
        seed: int = 0,
    ):
        self.config = config
        self.state: SimState = init_state(
            config, None if member_mask is None else jnp.asarray(member_mask)
        )
        self._key = jax.random.PRNGKey(seed)
        self._pending_crash: set[int] = set()
        self._pending_leave: set[int] = set()
        self._pending_join: set[int] = set()
        self._events: list[DetectionEvent] = []

    # -- event verbs -------------------------------------------------------
    def _check(self, node: int) -> int:
        if not 0 <= node < self.config.n:
            raise ValueError(f"node id {node} out of range [0, {self.config.n})")
        return node

    def join(self, node: int) -> None:
        self._pending_join.add(self._check(node))

    def leave(self, node: int) -> None:
        self._pending_leave.add(self._check(node))

    def crash(self, node: int) -> None:
        self._pending_crash.add(self._check(node))

    # -- time --------------------------------------------------------------
    def advance(self, rounds: int = 1) -> None:
        n = self.config.n
        for _ in range(rounds):
            ev = RoundEvents(
                crash=self._mask(self._pending_crash),
                leave=self._mask(self._pending_leave),
                join=self._mask(self._pending_join),
            )
            self._pending_crash.clear()
            self._pending_leave.clear()
            self._pending_join.clear()
            k = jax.random.fold_in(self._key, int(self.state.round))
            if self.config.topology == "ring":
                edges = None
            else:
                from gossipfs_tpu.core.topology import random_in_edges

                edges = random_in_edges(k, n, self.config.fanout)
            round_idx = int(self.state.round)
            self.state, _, fail = gossip_round(self.state, ev, edges, self.config)
            alive = np.asarray(self.state.alive)
            for obs, subj in np.argwhere(np.asarray(fail)):
                self._events.append(
                    DetectionEvent(
                        round=round_idx,
                        observer=int(obs),
                        subject=int(subj),
                        false_positive=bool(alive[subj]),
                    )
                )

    def _mask(self, nodes: set[int]) -> jax.Array:
        m = np.zeros((self.config.n,), dtype=bool)
        if nodes:
            m[list(nodes)] = True
        return jnp.asarray(m)

    def advance_bulk(self, rounds: int, snapshot_every: int | None = None):
        """Advance many rounds as one compiled scan (no per-round host sync).

        With ``snapshot_every``, returns a ``utils.snapshot.SnapshotBuffer``
        that an in-scan host callback feeds every k rounds: because jax
        dispatch is asynchronous this call returns while the device is
        still scanning, and other threads (the gRPC shim) read
        ``buffer.latest()`` for a consistent mid-run membership view
        (SURVEY §7.4's async boundary).  Pending crash/leave/join verbs are
        applied on the first round.
        """
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import RoundEvents as RE

        n = self.config.n
        first = np.zeros((rounds, n), dtype=bool)
        events = RE(
            crash=jnp.asarray(first).at[0].set(self._mask(self._pending_crash)),
            leave=jnp.asarray(first).at[0].set(self._mask(self._pending_leave)),
            join=jnp.asarray(first).at[0].set(self._mask(self._pending_join)),
        )
        self._pending_crash.clear()
        self._pending_leave.clear()
        self._pending_join.clear()
        buffer = None
        snapshot = None
        if snapshot_every is not None:
            from gossipfs_tpu.utils.snapshot import SnapshotBuffer

            buffer = SnapshotBuffer()
            snapshot = (buffer, snapshot_every)
        start_round = int(self.state.round)
        self.state, mcarry, _ = run_rounds(
            self.state, self.config, rounds, self._key, events=events,
            snapshot=snapshot,
        )
        # the per-round path records one DetectionEvent per (observer,
        # subject) firing; inside a compiled scan the full fail matrix never
        # reaches the host, so bulk advancement synthesizes one aggregate
        # event per newly-detected subject from the metrics carry
        # (observer=-1 marks it cluster-level)
        first = np.asarray(mcarry.first_detect)
        alive = np.asarray(self.state.alive)
        for subj in np.nonzero((first >= start_round) & (first < start_round + rounds))[0]:
            self._events.append(
                DetectionEvent(
                    round=int(first[subj]),
                    observer=-1,
                    subject=int(subj),
                    false_positive=bool(alive[subj]),
                )
            )
        return buffer

    # -- views -------------------------------------------------------------
    def membership(self, observer: int) -> list[int]:
        row = np.asarray(self.state.status[observer])
        return [int(j) for j in np.nonzero(row == int(MEMBER))[0]]

    def alive_nodes(self) -> list[int]:
        return [int(j) for j in np.nonzero(np.asarray(self.state.alive))[0]]

    def drain_events(self) -> list[DetectionEvent]:
        out, self._events = self._events, []
        return out
