"""TPU-sim implementation of the FailureDetector interface.

Wraps the batched round kernel (core/rounds.py) behind the per-node verbs the
CLI / SDFS shim consume.  Interactive path: one jitted ``gossip_round`` per
``advance``; bulk path: ``advance_bulk`` scans the horizon in compiled
chunks pipelined from a background thread, publishing membership snapshots
between chunks (SURVEY §7.4's async boundary, tunnel-safe — no host
callbacks).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import (
    gossip_round,
    gossip_round_donate,
    gossip_round_scenario,
    run_rounds,
)
from gossipfs_tpu.core.state import (
    MEMBER,
    SUSPECT,
    RoundEvents,
    SimState,
    init_state,
)
from gossipfs_tpu.detector.api import DetectionEvent
from gossipfs_tpu.utils.snapshot import Snapshot, SnapshotBuffer


class SimDetector:
    """N simulated gossip nodes advanced one tensor step per heartbeat."""

    def __init__(
        self,
        config: SimConfig,
        member_mask: np.ndarray | None = None,
        seed: int = 0,
        donate: bool = False,
    ):
        """``donate=True``: each interactive ``advance`` consumes the
        previous state's buffers (core.rounds.gossip_round_donate) — the
        detector must be the state's exclusive owner (don't hold
        references to ``det.state`` across an advance).  This is what
        fits the interactive path at the N=49,152 capacity point."""
        self.config = config
        self.donate = donate
        self.state: SimState = init_state(
            config, None if member_mask is None else jnp.asarray(member_mask)
        )
        self._key = jax.random.PRNGKey(seed)
        self._pending_crash: set[int] = set()
        self._pending_leave: set[int] = set()
        self._pending_join: set[int] = set()
        self._events: list[DetectionEvent] = []
        # bulk-scan results whose event synthesis is deferred until someone
        # actually reads events (np.asarray on the carry would otherwise
        # block the dispatching call until the whole scan finishes)
        self._pending_bulk: list[tuple[int, int, object, SimState]] = []
        self._bulk_thread: threading.Thread | None = None
        self._bulk_error: BaseException | None = None
        # one buffer reused across advance_bulk calls: a fresh buffer per
        # call would be a fresh object in any cache key and, more
        # importantly, readers hold a reference to THE buffer, not to one
        # call's buffer
        self._snap_buffer: SnapshotBuffer | None = None
        # armed fault scenario (scenarios/): declarative schedule, its
        # compiled tensor rule table, and the XLA-fallback config the
        # scenario rounds execute (scenarios.tensor module docstring)
        self._scenario = None
        self._scn_tensor = None
        self._scn_config: SimConfig | None = None
        # suspicion accounting (config.suspicion, suspicion/): cumulative
        # lifecycle counters for the `suspicion status` surface — fed by
        # the per-round RoundMetrics both advance paths already produce
        self._sus_totals = {"suspects_entered": 0, "refutations": 0,
                            "fp_suppressed": 0, "confirms": 0}
        # flight recorder (obs/): when attached, the interactive path
        # emits schema events per round (the evaluation lane — gated
        # host polling) and bulk scans decode post-hoc (obs.recorder.
        # decode_scan; the compiled program is untouched either way)
        self._recorder = None
        self._rec_suspects: set[int] = set()
        self._rec_removed: set[int] = set()

    # -- flight recorder (obs/) --------------------------------------------
    def attach_recorder(self, recorder) -> None:
        """Arm an obs.FlightRecorder: every subsequent round emits the
        schema's lifecycle events.  Interactive rounds poll the state for
        suspect/remove transitions (O(N^2) host reads — the evaluation
        lane, like suspicion itself); bulk scans decode their existing
        outputs instead, off the device hot path."""
        self._recorder = recorder
        self._rec_suspects = set()
        self._rec_removed = set()

    def _rec_emit(self, round_idx: int, kind: str, subject: int,
                  observer: int = -1, **detail) -> None:
        from gossipfs_tpu.obs.schema import Event

        self._recorder.emit(Event(round=round_idx, observer=observer,
                                  subject=subject, kind=kind,
                                  detail=detail))

    def _record_interactive_round(
        self, round_idx: int, metrics, af, fo,
        crashed: set[int], left: set[int], joined: set[int],
    ) -> None:
        """One interactive round's schema events (recorder armed only)."""
        for s in sorted(crashed):
            self._rec_emit(round_idx, "crash", s)
            self._rec_emit(round_idx, "hb_freeze", s)
            self._rec_removed.discard(s)
        for s in sorted(left):
            self._rec_emit(round_idx, "leave", s)
            self._rec_removed.discard(s)
        for s in sorted(joined):
            self._rec_emit(round_idx, "join", s)
            self._rec_removed.discard(s)
        sus_on = self.config.suspicion is not None
        detail = {
            "n_alive": int(metrics.n_alive),
            "true_detections": int(metrics.true_detections),
            "false_positives": int(metrics.false_positives),
        }
        if sus_on:
            detail.update(
                suspects_entered=int(metrics.suspects_entered),
                refutations=int(metrics.refutations),
                fp_suppressed=int(metrics.fp_suppressed),
            )
        self._rec_emit(round_idx, "round_tick", -1, **detail)

        st = np.asarray(self.state.status)
        alive = np.asarray(self.state.alive)
        if af is not None:
            for subj in np.nonzero(np.asarray(af))[0]:
                self._rec_emit(round_idx, "confirm", int(subj),
                               observer=int(np.asarray(fo)[subj]),
                               false_positive=bool(alive[subj]))
        if sus_on:
            now_sus = set(np.nonzero((st == int(SUSPECT)).any(axis=0))[0]
                          .tolist())
            for s in sorted(now_sus - self._rec_suspects):
                self._rec_emit(round_idx, "suspect", s)
            confirmed = (set(np.nonzero(np.asarray(af))[0].tolist())
                         if af is not None else set())
            # a refutation is evidence of life: the entry must be BACK
            # as a MEMBER somewhere, and not because of a same-round
            # leave/crash verb — suspects that merely got dropped
            # (LEAVE marks them FAILED, a remove expires them) were
            # never refuted, and emitting one would contradict the
            # round_tick counters (UdpNode's drop-vs-refute split)
            member_any = (st == int(MEMBER)).any(axis=0)
            for s in sorted(self._rec_suspects - now_sus - confirmed):
                if member_any[s] and s not in crashed and s not in left:
                    self._rec_emit(round_idx, "refute", s)
            self._rec_suspects = now_sus
        # cluster-wide removal (the convergence event): a dead subject no
        # live observer still lists — mirrors _update_carry's all_dropped
        held = ((st == int(MEMBER)) | (st == int(SUSPECT)))
        held &= alive[:, None]
        np.fill_diagonal(held, False)
        gone = set(np.nonzero(~held.any(axis=0) & ~alive)[0].tolist())
        for s in sorted(gone - self._rec_removed):
            self._rec_emit(round_idx, "remove", s)
        self._rec_removed |= gone

    # -- scenario engine ---------------------------------------------------
    def load_scenario(self, scenario) -> None:
        """Arm a scenarios.FaultScenario: rule windows count from the
        CURRENT round.  Scenario rounds run the XLA-merge fallback config
        (same protocol arithmetic — scenarios/tensor.py documents the
        rr/pallas gating); loading replaces any previous scenario."""
        from gossipfs_tpu.scenarios import tensor as scn_tensor

        if scenario.n != self.config.n:
            raise ValueError(
                f"scenario is for n={scenario.n}, detector has "
                f"n={self.config.n}"
            )
        # arc capability checks need the rule tables (Bernoulli loss has
        # no group form; partition sides must be align-group-closed), so
        # the config-only check inside xla_fallback_config is not enough
        scn_tensor.require_scenario_config(self.config, scenario)
        self._join_bulk()
        self._scn_config = scn_tensor.xla_fallback_config(self.config)
        self._scn_tensor = scn_tensor.compile_tensor(
            scenario, round0=int(self.state.round)
        )
        self._scenario = scenario
        if self._recorder is not None:
            self._rec_emit(int(self.state.round), "scenario_arm", -1,
                           name=scenario.name, horizon=scenario.horizon)

    def clear_scenario(self) -> None:
        if self._scenario is not None and self._recorder is not None:
            self._rec_emit(int(self.state.round), "scenario_clear", -1)
        self._scenario = self._scn_tensor = self._scn_config = None

    def scenario_status(self) -> dict | None:
        """Status document for the armed scenario (None when unarmed)."""
        if self._scenario is None:
            return None
        return self._scenario.status(
            int(self.state.round) - int(self._scn_tensor.round0)
        )

    # -- event verbs -------------------------------------------------------
    def _check(self, node: int) -> int:
        if not 0 <= node < self.config.n:
            raise ValueError(f"node id {node} out of range [0, {self.config.n})")
        return node

    def join(self, node: int) -> None:
        self._pending_join.add(self._check(node))

    def leave(self, node: int) -> None:
        self._pending_leave.add(self._check(node))

    def crash(self, node: int) -> None:
        self._pending_crash.add(self._check(node))

    # -- time --------------------------------------------------------------
    def _join_bulk(self) -> None:
        """Wait for an in-flight bulk scan before touching state mutably.

        Re-raises any exception the pipeline thread hit (a silently-failed
        chunk would otherwise leave the detector frozen at the pre-bulk
        round while callers believe it advanced).
        """
        t = self._bulk_thread
        if t is not None and t.is_alive():
            t.join()
        self._bulk_thread = None
        err, self._bulk_error = self._bulk_error, None
        if err is not None:
            raise RuntimeError("bulk advancement failed mid-scan") from err

    def advance(self, rounds: int = 1) -> None:
        self._join_bulk()
        # events from any finished bulk scan precede this call's, chronologically
        self._resolve_pending_bulk()
        n = self.config.n
        for _ in range(rounds):
            round_idx = int(self.state.round)
            scn_on = self._scenario is not None
            if scn_on and self._pending_join and self._scenario.active_at(
                round_idx - int(self._scn_tensor.round0)
            ):
                # the join path is an instantaneous introducer row/column
                # rewrite, not transport messages — it cannot be filtered
                # by the active fault rules, so it would teleport across a
                # partition.  Refuse rather than simulate wrong dynamics.
                raise NotImplementedError(
                    "join during an active scenario window is not "
                    "transport-filtered; advance past the fault windows "
                    "(or clear_scenario) before joining"
                )
            ev = RoundEvents(
                crash=self._mask(self._pending_crash),
                leave=self._mask(self._pending_leave),
                join=self._mask(self._pending_join),
            )
            rec_verbs = None
            if self._recorder is not None:
                rec_verbs = (set(self._pending_crash),
                             set(self._pending_leave),
                             set(self._pending_join))
            self._pending_crash.clear()
            self._pending_leave.clear()
            self._pending_join.clear()
            k = jax.random.fold_in(self._key, round_idx)
            cfg = self._scn_config if scn_on else self.config
            if cfg.topology == "ring":
                edges = None  # derived in-round from the membership tables
            else:
                from gossipfs_tpu.core import topology

                edges = topology.in_edges(cfg, k, None)
            if scn_on:
                # scenario rounds: the XLA-fallback config + per-edge drop
                # filter (scenarios/tensor.py).  No donate variant — the
                # scenario path is the interactive/parity lane, not the
                # capacity frontier
                self.state, metrics, any_fail, first_obs = (
                    gossip_round_scenario(
                        self.state, ev, edges, cfg, self._scn_tensor,
                        jax.random.fold_in(k, 0x5CE),
                    )
                )
            else:
                step = gossip_round_donate if self.donate else gossip_round
                self.state, metrics, any_fail, first_obs = step(
                    self.state, ev, edges, cfg
                )
            if self.config.suspicion is not None:
                # suspicion is an interactive/evaluation lane (XLA-gated),
                # so the extra scalar transfers per round are acceptable
                self._accumulate_suspicion(
                    int(metrics.suspects_entered), int(metrics.refutations),
                    int(metrics.fp_suppressed),
                    int(metrics.true_detections)
                    + int(metrics.false_positives),
                )
            eventful = bool(jnp.any(any_fail))
            if rec_verbs is not None:
                # recorder armed: the evaluation lane reads the round's
                # observables every round anyway (round_tick needs them)
                self._record_interactive_round(
                    round_idx, metrics,
                    any_fail if eventful else None, first_obs, *rec_verbs,
                )
            if not eventful:
                # quiet round: one scalar transfer
                continue
            # eventful round: the per-subject vectors the round computes
            # anyway — O(N) host bytes instead of the [N, N] fail matrix
            # (the round-2 review's last interactive-path flag).  One event
            # per newly-detected subject, attributed to the lowest-index
            # firing observer — the same first-observer semantics as bulk
            # advancement (and effectively the reference's, whose first
            # detector's REMOVE broadcast preempts the others).
            af = np.asarray(any_fail)
            fo = np.asarray(first_obs)
            alive = np.asarray(self.state.alive)
            for subj in np.nonzero(af)[0]:
                self._events.append(
                    DetectionEvent(
                        round=round_idx,
                        observer=int(fo[subj]),
                        subject=int(subj),
                        false_positive=bool(alive[subj]),
                    )
                )

    def _accumulate_suspicion(self, entered: int, refuted: int,
                              fp_sup: int, confirms: int) -> None:
        t = self._sus_totals
        t["suspects_entered"] += entered
        t["refutations"] += refuted
        t["fp_suppressed"] += fp_sup
        t["confirms"] += confirms

    def _accumulate_suspicion_bulk(self, per_round) -> None:
        """Fold a scan's stacked RoundMetrics into the lifecycle totals.

        Called from :meth:`_resolve_pending_bulk` — i.e. only once the
        scan's results are being read anyway — so the blocking
        np.asarray never serializes the bulk dispatch or the snapshot
        pipeline's two-deep in-flight window.
        """
        self._accumulate_suspicion(
            int(np.asarray(per_round.suspects_entered).sum()),
            int(np.asarray(per_round.refutations).sum()),
            int(np.asarray(per_round.fp_suppressed).sum()),
            int(np.asarray(per_round.true_detections).sum())
            + int(np.asarray(per_round.false_positives).sum()),
        )

    def _mask(self, nodes: set[int]) -> jax.Array:
        m = np.zeros((self.config.n,), dtype=bool)
        if nodes:
            m[list(nodes)] = True
        return jnp.asarray(m)

    def _first_round_events(self, rounds: int) -> RoundEvents:
        n = self.config.n
        zeros = np.zeros((rounds, n), dtype=bool)
        ev = RoundEvents(
            crash=jnp.asarray(zeros).at[0].set(self._mask(self._pending_crash)),
            leave=jnp.asarray(zeros).at[0].set(self._mask(self._pending_leave)),
            join=jnp.asarray(zeros).at[0].set(self._mask(self._pending_join)),
        )
        self._pending_crash.clear()
        self._pending_leave.clear()
        self._pending_join.clear()
        return ev

    def advance_bulk(self, rounds: int, snapshot_every: int | None = None):
        """Advance many rounds as compiled scans (no per-round host sync).

        Without ``snapshot_every``: one scan, dispatched asynchronously;
        event synthesis is deferred to ``drain_events`` so this call
        returns while the device is still working.

        With ``snapshot_every``: the horizon is split into chunks of that
        many rounds (bit-identical to one long scan — the metrics carry
        threads through) and a background thread pipelines them two deep,
        publishing a ``utils.snapshot.Snapshot`` to the returned buffer as
        each chunk completes.  Other threads (the gRPC shim) read
        ``buffer.latest()`` for a consistent mid-run membership view; the
        detector's ``state`` also advances chunk by chunk, so direct reads
        see the freshest *completed* state.  Pending crash/leave/join verbs
        are applied on the first round.  No host callbacks are involved, so
        this works over a remote-PJRT TPU tunnel.
        """
        self._join_bulk()
        start_round = int(self.state.round)
        if (
            self._scenario is not None
            and self._pending_join
            and self._scenario.active_at(
                start_round - int(self._scn_tensor.round0)
            )
        ):
            # same teleport refusal as the interactive path (see advance)
            raise NotImplementedError(
                "join during an active scenario window is not "
                "transport-filtered"
            )
        # ground-truth verbs this bulk scan applies on its first round —
        # captured for the recorder BEFORE _first_round_events clears the
        # pending sets, so a bulk trace carries the same crash/leave/join
        # rows the interactive path emits (timeline.py derives TTD from
        # the crash rows)
        verbs = (set(self._pending_crash), set(self._pending_leave),
                 set(self._pending_join))
        events = self._first_round_events(rounds)

        if snapshot_every is None:
            self.state, mcarry, per_round = run_rounds(
                self.state, self.config, rounds, self._key, events=events,
                scenario=self._scn_tensor,
            )
            self._pending_bulk.append(
                (start_round, rounds, mcarry, self.state, [per_round],
                 verbs)
            )
            return None

        if self._snap_buffer is None:
            self._snap_buffer = SnapshotBuffer()
        buffer = self._snap_buffer
        buffer.clear()

        every = max(1, int(snapshot_every))
        chunks: list[tuple[int, int]] = []  # (offset, length)
        off = 0
        while off < rounds:
            ln = min(every, rounds - off)
            chunks.append((off, ln))
            off += ln

        def pipeline() -> None:
            try:
                st = self.state
                mcarry = None
                prev: SimState | None = None
                per_rounds = []  # folded lazily in _resolve_pending_bulk
                for off, ln in chunks:
                    ev = RoundEvents(
                        crash=events.crash[off:off + ln],
                        leave=events.leave[off:off + ln],
                        join=events.join[off:off + ln],
                    )
                    st, mcarry, per_round = run_rounds(
                        st, self.config, ln, self._key, events=ev,
                        mcarry0=mcarry, scenario=self._scn_tensor,
                    )
                    per_rounds.append(per_round)
                    if prev is not None:
                        # blocks until the previous chunk lands — the current
                        # chunk is already queued behind it, so the device
                        # never idles; bounding the pipeline here also bounds
                        # how many chunk states can be live in HBM (<= 2)
                        self._publish(prev)
                    prev = st
                self._publish(prev)
                self._pending_bulk.append(
                    (start_round, rounds, mcarry, st, per_rounds, verbs)
                )
            except BaseException as e:  # re-raised by the next _join_bulk
                self._bulk_error = e

        t = threading.Thread(target=pipeline, daemon=True, name="gossipfs-bulk")
        self._bulk_thread = t
        t.start()
        return buffer

    def _publish(self, st: SimState) -> None:
        alive = np.asarray(st.alive)  # waits for the chunk to complete
        self.state = st
        self._snap_buffer.push(
            Snapshot(round=int(st.round), alive=alive, state=st)
        )

    def _resolve_pending_bulk(self) -> None:
        """Synthesize detection events from finished bulk scans.

        Inside a compiled scan the full fail matrix never reaches the host;
        the metrics carry records, per subject, the first detection round
        and the (lowest-index) observer that fired — so bulk advancement
        reports the same first event per subject as the per-round path.
        """
        pending, self._pending_bulk = self._pending_bulk, []
        for start, rounds, mcarry, state, per_rounds, verbs in pending:
            if self.config.suspicion is not None:
                for pr in per_rounds:
                    self._accumulate_suspicion_bulk(pr)
            if self._recorder is not None:
                # bulk backend: expand the scan's existing outputs into
                # schema events — runs only when results are read anyway.
                # The verbs the scan applied on its first round become the
                # ground-truth rows (leave/join don't ride decode_scan's
                # crash_rounds, so emit them here at the start round).
                from gossipfs_tpu.core.rounds import RoundMetrics
                from gossipfs_tpu.obs.recorder import decode_scan

                crashed, left, joined = verbs
                for s in sorted(left):
                    self._rec_emit(start, "leave", s)
                for s in sorted(joined):
                    self._rec_emit(start, "join", s)
                flat = RoundMetrics(*(
                    np.concatenate([np.asarray(getattr(p, f))
                                    for p in per_rounds])
                    for f in RoundMetrics._fields
                ))
                self._recorder.extend(decode_scan(
                    flat, mcarry, n=self.config.n, start_round=start,
                    crash_rounds={s: start for s in sorted(crashed)},
                    alive=state.alive,
                    suspicion=self.config.suspicion is not None,
                ))
            first = np.asarray(mcarry.first_detect)
            observer = np.asarray(mcarry.first_observer)
            alive = np.asarray(state.alive)
            in_window = (first >= start) & (first < start + rounds)
            for subj in np.nonzero(in_window)[0]:
                self._events.append(
                    DetectionEvent(
                        round=int(first[subj]),
                        observer=int(observer[subj]),
                        subject=int(subj),
                        false_positive=bool(alive[subj]),
                    )
                )

    # -- views -------------------------------------------------------------
    def membership(self, observer: int) -> list[int]:
        # a SUSPECT entry is still in the list (pending refute/confirm)
        # — the UDP engine's members dict naturally agrees, since its
        # suspects are only removed at confirmation
        row = np.asarray(self.state.status[observer])
        return [
            int(j)
            for j in np.nonzero((row == int(MEMBER)) | (row == int(SUSPECT)))[0]
        ]

    def suspects(self, observer: int) -> list[int]:
        """Entries the observer currently holds SUSPECT (suspicion runs;
        empty in the reference mode — the lane value is unreachable)."""
        row = np.asarray(self.state.status[observer])
        return [int(j) for j in np.nonzero(row == int(SUSPECT))[0]]

    def suspicion_status(self) -> dict | None:
        """THE suspicion vitals document (CLI ``suspicion status``): per-
        node live suspect counts plus the cumulative lifecycle totals.
        None when suspicion is not armed."""
        sus = self.config.suspicion
        if sus is None:
            return None
        self._join_bulk()
        self._resolve_pending_bulk()  # fold any finished scans' totals in
        st = np.asarray(self.state.status)
        alive = np.asarray(self.state.alive)
        counts = ((st == int(SUSPECT)).sum(axis=1) * alive).astype(int)
        return {
            "enabled": True,
            "t_suspect": sus.t_suspect,
            "lh_multiplier": sus.lh_multiplier,
            "suspect_counts": {
                int(i): int(c) for i, c in enumerate(counts) if c
            },
            "suspects_now": int(counts.sum()),
            **self._sus_totals,
        }

    def alive_nodes(self) -> list[int]:
        return [int(j) for j in np.nonzero(np.asarray(self.state.alive))[0]]

    def drain_events(self) -> list[DetectionEvent]:
        self._join_bulk()
        self._resolve_pending_bulk()
        out, self._events = self._events, []
        return out


class PackedDetector:
    """Interactive FailureDetector over the rr kernel's packed state.

    The capacity-frontier interactive path: the state lives as the
    resident-round kernel's stripe-major packed lanes (2 B/entry,
    core/rounds._scan_rounds_rr_packed) and every ``advance`` runs ONE
    donated 1-round scan — which is what fits N=49,152+ interactively
    (the 2-D ``gossip_round`` path's doubled lanes measured 20.3 GB at
    that size, past the chip).  Same FailureDetector seam as SimDetector:
    ``crash`` and ``leave`` (silent death — no LEAVE broadcast on this
    path), and since round 5 ``join``/rejoin — applied as an O(N)
    column/row rewrite on the packed lanes between donated scans, with
    the introducer-push, fail-list-suppression, and fresh-incarnation
    rebase semantics of the matrix path (zombie suppression intact).
    Detection events are synthesized by diffing the carried
    first-detection vector, so they match the scan path's first-observer
    semantics exactly.
    """

    def __init__(self, config: SimConfig, seed: int = 0):
        from gossipfs_tpu.core import rounds as R

        if not R._use_rr(config, config.n, config.n):
            raise ValueError(
                "PackedDetector requires a resident-round config "
                "(merge_kernel='pallas_rr', all-int8, random/random_arc)"
            )
        self.config = config
        self._carry = R.rr_packed_init(config)
        self._mcarry = R.MetricsCarry.init(config.n)
        self._key = jax.random.PRNGKey(seed)
        self._pending_crash: set[int] = set()
        self._pending_join: list[int] = []
        self._events: list[DetectionEvent] = []
        # local-health lane (round 14): lh-armed rr configs carry the
        # per-receiver suspect counts between donated scans, exactly like
        # the member counts (a fresh fully-joined cluster holds zero)
        self._lh = (config.suspicion is not None
                    and config.suspicion.lh_multiplier > 0)
        self._sus_counts = (jnp.zeros((config.n,), jnp.int32)
                            if self._lh else None)

        def one_round(hb4, as4, alive, hb_base, rnd, counts, sus_counts,
                      mc, ev):
            return R._scan_rounds_rr_packed(
                hb4, as4, alive, hb_base, rnd, config,
                # fold the round into the session key inside the core
                self._key, ev, 0.0, None, mcarry0=mc, counts0=counts,
                sus_counts0=sus_counts,
            )

        self._step = jax.jit(one_round, donate_argnums=(0, 1))

        def join_one(hb4, as4, alive, hb_base, counts, mc, j, crash_mask):
            """One join on the packed lanes — O(N): a column rebase+add
            pass, the joiner row copied from the introducer, an alive
            flip, count deltas, and carry resets.  Mirrors the matrix
            path's _apply_events join block (core/rounds.py:278-339,
            itself addNewMember + the full-list push,
            reference slave/slave.go:250-274, 430-439) op for op, so a
            single join per advance is bit-identical to the matrix scan.
            """
            from gossipfs_tpu.core.state import UNKNOWN

            nc, n, cs, lane = hb4.shape
            c_blk = cs * lane
            sj, scs, sl = j // c_blk, (j % c_blk) // lane, j % lane
            intro = config.introducer
            # matrix ordering: crashes land before joins in the same round
            alive_eff = alive & ~crash_mask
            ok = ~alive_eff[j] & alive_eff[intro]

            # -- column j: rebase to base 0 (fresh incarnation's true hb 0
            # must encode exactly; old lanes renormalize, clipping at the
            # ceiling — ordinary zombies; sentinels stay sentinels)
            col_hb = hb4[sj, :, scs, sl]
            col_as = as4[sj, :, scs, sl]
            base_j = hb_base[j]
            sent = col_hb == jnp.int8(-128)
            true32 = col_hb.astype(jnp.int32) + base_j
            col_hb2 = jnp.where(
                (base_j != 0) & ~sent,
                jnp.clip(true32, -128, 127).astype(jnp.int8), col_hb,
            )
            # receivers add the joiner unless it sits on their fail list
            # (FAILED = cooldown suppression); the introducer appends
            # unconditionally
            st_col = col_as.astype(jnp.int32) & 3
            upd = (alive_eff & (st_col == int(UNKNOWN))) \
                | (jnp.arange(n) == intro)
            col_hb3 = jnp.where(upd, jnp.int8(0), col_hb2)
            col_as3 = jnp.where(upd, jnp.int8(int(MEMBER) - 128), col_as)
            okc = ok  # scalar gate
            hb4 = hb4.at[sj, :, scs, sl].set(
                jnp.where(okc, col_hb3, col_hb))
            as4 = as4.at[sj, :, scs, sl].set(
                jnp.where(okc, col_as3, col_as))
            hb_base = hb_base.at[j].set(jnp.where(okc, 0, base_j))
            counts = counts + (
                okc & upd & (st_col != int(MEMBER))
            ).astype(jnp.int32)

            # -- joiner row := introducer's post-append row (the same
            # full-list push the real joiner receives); fresh fail list
            intro_hb = hb4[:, intro]
            intro_as = as4[:, intro]
            intro_mem = (intro_as.astype(jnp.int32) & 3) == int(MEMBER)
            hz_c = jnp.clip(-hb_base, -128, 0).astype(jnp.int8).reshape(
                nc, cs, lane)
            row_hb = jnp.where(intro_mem, intro_hb, hz_c)
            row_as = jnp.where(intro_mem, jnp.int8(int(MEMBER) - 128),
                               jnp.int8(int(UNKNOWN) - 128))
            # self entry always present, at the fresh base's encoded 0
            row_hb = row_hb.at[sj, scs, sl].set(jnp.int8(0))
            row_as = row_as.at[sj, scs, sl].set(jnp.int8(int(MEMBER) - 128))
            hb4 = hb4.at[:, j].set(jnp.where(okc, row_hb, hb4[:, j]))
            as4 = as4.at[:, j].set(jnp.where(okc, row_as, as4[:, j]))
            alive = alive.at[j].set(alive[j] | okc)
            cnt_row = jnp.sum(
                ((row_as.astype(jnp.int32) & 3) == int(MEMBER))
                .astype(jnp.int32))
            counts = counts.at[j].set(jnp.where(okc, cnt_row, counts[j]))
            # a rejoin resets the subject's detection/convergence clocks
            # (core/rounds._update_carry's `rejoined` semantics)
            mc = R.MetricsCarry(
                first_detect=mc.first_detect.at[j].set(
                    jnp.where(okc, -1, mc.first_detect[j])),
                first_observer=mc.first_observer.at[j].set(
                    jnp.where(okc, -1, mc.first_observer[j])),
                converged=mc.converged.at[j].set(
                    jnp.where(okc, -1, mc.converged[j])),
                first_suspect=mc.first_suspect.at[j].set(
                    jnp.where(okc, -1, mc.first_suspect[j])),
            )
            return hb4, as4, alive, hb_base, counts, mc, ok

        self._join_one = jax.jit(join_one, donate_argnums=(0, 1))

    @property
    def round(self) -> int:
        return int(self._carry[4])

    # -- verbs -------------------------------------------------------------
    def _check(self, node: int) -> int:
        # an unvalidated id would poison the pending set and raise on
        # every subsequent advance — fatal for a multi-GB frontier session
        if not 0 <= node < self.config.n:
            raise ValueError(
                f"node id {node} out of range [0, {self.config.n})"
            )
        return node

    def crash(self, node: int) -> None:
        self._pending_crash.add(self._check(node))

    def leave(self, node: int) -> None:
        # lean fault model: leave == silent death (the scan path's
        # crash_only_events contract; detection still happens by timeout)
        self._pending_crash.add(self._check(node))

    def join(self, node: int) -> None:
        """Queue a (re)join, applied before the next round's scan.

        Applied as an O(N) column/row rewrite on the packed lanes between
        donated scans (see ``join_one`` in ``__init__``) — the round-4
        frontier refused joins outright.  Joins within one round apply in
        call order, each seeing the previous (the matrix path's batched
        form lets simultaneous joiners see each other; one join per round
        is bit-identical to it, which is the CLI's usage).
        """
        n = self._check(node)
        if n not in self._pending_join:
            self._pending_join.append(n)

    def advance(self, rounds: int = 1) -> None:
        n = self.config.n
        for _ in range(rounds):
            mask = np.zeros((n,), dtype=bool)
            if self._pending_crash:
                mask[list(self._pending_crash)] = True
                self._pending_crash.clear()
            if self._pending_join:
                hb4, as4, alive, hb_base, rnd, counts = self._carry
                mc = self._mcarry
                # an effective join clears the node's same-round crash
                # bit — the matrix path applies crashes BEFORE joins, so
                # a crash(j)+join(j) round must end with j alive.  The
                # device's own `ok` is the single source of truth (one
                # scalar transfer per join — a rare verb)
                for j in self._pending_join:
                    cm = jnp.asarray(mask)
                    (hb4, as4, alive, hb_base, counts, mc,
                     ok) = self._join_one(
                        hb4, as4, alive, hb_base, counts, mc,
                        jnp.int32(j), cm,
                    )
                    if bool(ok):
                        mask[j] = False
                        if self._lh:
                            # the joiner's fresh row holds no SUSPECT
                            # entries; other receivers' suspect counts
                            # are untouched (the join add writes only
                            # UNKNOWN entries)
                            self._sus_counts = self._sus_counts.at[j].set(0)
                self._pending_join.clear()
                self._carry = (hb4, as4, alive, hb_base, rnd, counts)
                self._mcarry = mc
            m = jnp.asarray(mask)
            z = jnp.zeros((1, n), dtype=bool)
            ev = RoundEvents(crash=m[None], leave=z, join=z)
            hb4, as4, alive, hb_base, rnd, counts = self._carry
            round_idx = int(rnd)
            prev_first = self._mcarry.first_detect
            # 9-value unpack mirrors one_round's return; its width (and
            # the MetricsCarry/RoundMetrics constructor arities above)
            # are pinned to core/rounds by the scan-carry-arity rule
            (hb4, as4, alive, hb_base, rnd, counts, sus_counts, mc,
             per_round) = (
                self._step(hb4, as4, alive, hb_base, rnd, counts,
                           self._sus_counts, self._mcarry, ev)
            )
            self._carry = (hb4, as4, alive, hb_base, rnd, counts)
            self._sus_counts = sus_counts
            self._mcarry = mc
            if int(per_round.true_detections[0]) + int(
                per_round.false_positives[0]
            ) == 0:
                continue  # quiet round: two scalar transfers
            fresh = np.asarray(
                (mc.first_detect == round_idx) & (prev_first < 0)
            )
            obs = np.asarray(mc.first_observer)
            alive_h = np.asarray(alive)
            for subj in np.nonzero(fresh)[0]:
                self._events.append(
                    DetectionEvent(
                        round=round_idx,
                        observer=int(obs[subj]),
                        subject=int(subj),
                        false_positive=bool(alive_h[subj]),
                    )
                )

    # -- views -------------------------------------------------------------
    def membership(self, observer: int) -> list[int]:
        from gossipfs_tpu.ops import merge_pallas

        as_row = self._carry[1][:, observer]  # [nc, cs, LANE]
        st = merge_pallas.unpack_age_status(as_row)[1].reshape(-1)
        return [int(j) for j in np.nonzero(np.asarray(st) == int(MEMBER))[0]]

    def alive_nodes(self) -> list[int]:
        return [int(j) for j in np.nonzero(np.asarray(self._carry[2]))[0]]

    def drain_events(self) -> list[DetectionEvent]:
        out, self._events = self._events, []
        return out
