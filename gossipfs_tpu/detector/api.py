"""The FailureDetector interface — the seam BASELINE.json names.

The reference entangles failure detection with its node runtime (heartbeat
goroutine + UDP receive loop, slave/slave.go:169-544).  Here the detector is
an interface: feed membership events in, advance time, read each node's
membership view and the detection event stream out.  Consumers (the SDFS
master's placement logic, the CLI, the gRPC shim) do not care whether the
implementation is the batched TPU sim (detector/sim.py) or real UDP sockets
(detector/udp.py, the 10-node parity path).
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, runtime_checkable


@dataclasses.dataclass(frozen=True)
class DetectionEvent:
    """One detector firing: ``observer`` declared ``subject`` failed at ``round``."""

    round: int
    observer: int
    subject: int
    false_positive: bool  # subject was actually alive (ground truth known in sim)


@runtime_checkable
class FailureDetector(Protocol):
    """Protocol every detector implementation satisfies."""

    def join(self, node: int) -> None:
        """Node (re)joins through the introducer (CLI ``join``, README.md:10)."""

    def leave(self, node: int) -> None:
        """Voluntary departure with LEAVE broadcast (CLI ``leave``)."""

    def crash(self, node: int) -> None:
        """Crash-stop fault injection (CTRL+C, README.md:30)."""

    def advance(self, rounds: int = 1) -> None:
        """Advance simulated/real time by whole heartbeat periods."""

    def membership(self, observer: int) -> list[int]:
        """Observer's current member list (CLI ``lsm``, README.md:12)."""

    def alive_nodes(self) -> list[int]:
        """Ground-truth live set (what the SDFS master consumes)."""

    def drain_events(self) -> list[DetectionEvent]:
        """Detection events since the last drain.

        The sim reports one event per newly-detected subject, attributed
        to the lowest-index observer that fired that round (bulk and
        interactive paths agree; effectively the reference's semantics,
        where the first detector's REMOVE broadcast preempts the rest).
        The socket engines report whichever of their detectors actually
        fired first in real time.
        """
