"""One-process-per-node deployment: the reference's REAL topology.

The reference runs every cluster member as its own OS process carrying all
layers — UDP gossip membership, the SDFS replica store, and a per-node RPC
server (reference: main.go:14-35, server/server.go:179-199).  The embedded
shim (shim/service.py) keeps that RPC surface but hosts the whole cluster
in one process; THIS module is the deployment where each node is its own
``python -m gossipfs_tpu.deploy.node`` process and every repair, election,
and confirmation crosses a real process boundary:

  * membership: the real-socket gossip node (detector/udp.py ``UdpNode``
    — reference wire constants, ring push, timeout detection, REMOVE
    broadcast) auto-ticking on its own asyncio loop.  kill -9 the process
    and the others detect it the protocol way.
  * files: a private ``sdfs/store.LocalStore`` rooted in the node's own
    directory; replica bytes move between processes as ``PutFileData``
    gRPC messages (the reference moves them via scp, slave.go:680-698 —
    same sanctioned substitution the embedded shim documents).
  * control plane: each node serves the gossipfs.proto surface on its own
    port.  The master role (initially node 0, reference master/master.go)
    plans placement and drives re-replication ``RECOVERY_DELAY`` periods
    after a holder leaves its own membership view; when the master dies,
    the lowest live node campaigns with per-node ``Vote`` RPCs and
    ``AssignNewMaster`` returns each node's store listing for the metadata
    rebuild (reference: slave.go:930-1051).
  * logs: every node appends to ``<dir>/node<i>.log``; the ``Grep`` RPC
    serves the node's own log — the reference's distributed grep, with the
    querier fanning out to live nodes.

No jax anywhere on this path: a node process starts in milliseconds and
never touches the TPU tunnel.

    python -m gossipfs_tpu.deploy.node --idx 3 --n 5 \
        --udp-base 19000 --rpc-base 19100 --dir /tmp/cluster

``deploy/launcher.py`` spawns a whole cluster and runs the kill -9
detection/repair/election scenario end to end.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import pathlib
import threading
import time

import grpc

from gossipfs_tpu.detector.udp import CMD_SEP, UdpNode
from gossipfs_tpu.obs import schema as obs_schema
from gossipfs_tpu.sdfs.store import LocalStore
from gossipfs_tpu.sdfs.types import (
    RECOVERY_DELAY,          # periods after detection before re-replication
    REPLICATION_FACTOR,
    WRITE_CONFLICT_WINDOW,   # seconds (1 reference round == 1 s)
)
from gossipfs_tpu.shim import wire
from gossipfs_tpu.shim.client import ShimClient
from gossipfs_tpu.shim.wire import SERVICE


class _Env:
    """The small interface UdpNode needs from its host (duck-typed for the
    in-process UdpCluster in detector/udp.py)."""

    def __init__(self, daemon: "NodeDaemon", period: float, t_fail: int,
                 t_cooldown: int, min_group: int):
        self.period = period
        self.t_fail = t_fail
        self.t_cooldown = t_cooldown
        self.min_group = min_group
        self.fresh_cooldown = True
        # protocol-mode knobs (round 14, detector/udp.py): the deploy
        # daemons keep the Go-parity wire behavior — ring pushes + the
        # REMOVE broadcast (the reference's per-machine topology)
        self.push = "ring"
        self.fanout = 3
        self.remove_broadcast = True
        # delta dissemination (round 20, protocol_spec DELTA_GOSSIP)
        # stays OFF in the deployment: the daemons keep the committed
        # full-list wire format; the knobs exist because UdpNode reads
        # them from its host every tick
        self.delta = False
        self.delta_entries = 16
        self.anti_entropy_every = 4
        # suspicion subsystem (suspicion/): SuspicionParams pushed over
        # the control plane (SuspicionLoad RPC); the UdpNode reads this
        # every tick, exactly like the in-process UdpCluster's attribute
        self.suspicion = None
        self._daemon = daemon

    def record_detection(self, observer: int, subject_addr: str) -> None:
        self._daemon.on_detection(subject_addr)

    def record_obs(self, kind: str, observer: int, subject_addr: str,
                   **detail) -> None:
        """UdpNode's flight-recorder seam (obs/): in the deployment the
        recorder IS the node's structured log — suspect/refute/remove
        events land in node<i>.log as schema rows, so merging the
        per-node logs (tools/timeline.py) reconstructs the lifecycle."""
        self._daemon.log(kind, f"{kind} {subject_addr}",
                         subject=self._daemon.addr_to_idx(subject_addr))

    def message_allowed(self, src: int, peer_addr: str) -> bool:
        """UdpNode._send scenario hook: the daemon evaluates the rule
        table pushed over the control plane (ScenarioLoad)."""
        return not self._daemon.scenario_drops(src, peer_addr)


class NodeDaemon:
    """One cluster member: gossip + store + RPC server, all in-process."""

    def __init__(self, idx: int, n: int, udp_base: int, rpc_base: int,
                 root: str, period: float = 0.1, t_fail: int = 5,
                 t_cooldown: int = 5, min_group: int = 4,
                 auto_confirm: bool = True, introducer: int = 0):
        self.idx = idx
        self.n = n
        self.udp_base = udp_base
        self.rpc_base = rpc_base
        self.period = period
        self.auto_confirm = auto_confirm
        self.introducer = introducer
        self.master_id = 0  # initial master, reference main.go
        root_p = pathlib.Path(root)
        self.store = LocalStore(root_p / f"node{idx}")
        self.log_path = root_p / f"node{idx}.log"
        self._env = _Env(self, period, t_fail, t_cooldown, min_group)
        self.udp = UdpNode(self._env, idx, udp_base + idx)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._lock = threading.RLock()
        # master state (meaningful only while self.idx == self.master_id)
        self.meta: dict[str, tuple[int, list[int]]] = {}  # file -> (version, holders)
        # placements handed out by GetPutInfo but not yet committed by the
        # writer's UpdateFileVersion — a writer that dies mid-push leaves
        # only a stale pending entry, never unreadable metadata.  Keyed by
        # (file, version) with versions allocated past any pending one, so
        # concurrent writers to the same file each commit THEIR OWN plan
        # (a single slot would let writer A publish writer B's replica set)
        # (file, version) -> (planned replicas, plan time).  The timestamp
        # lets GetPutInfo expire plans whose writer died before committing
        # (round-5 advisor: an abandoned plan used to hold the write-
        # conflict window open forever and leak the pending entry)
        self.pending: dict[tuple[str, int], tuple[list[int], float]] = {}
        self.last_put: dict[str, tuple[float, str]] = {}  # file -> (time, callback)
        self._lost_at: dict[int, float] = {}              # node -> detect time
        self._repair_tick = 0
        self._clients: dict[int, ShimClient] = {}
        self._server: grpc.Server | None = None
        self._stop = threading.Event()
        # scenario engine: rule table pushed via ScenarioLoad.  Each node
        # anchors round 0 at its own PROTOCOL-round counter at receipt
        # (UdpNode.rounds — the same clock the logs stamp): under host
        # load the node's ticks stall and the fault windows stall with
        # them, exactly like the sim and in-process UDP engines.  A
        # wall-clock anchor would instead let a partition "heal" while
        # the starved node executed almost no protocol rounds.  Receipt
        # skew across the fan-out is ~one tick against multi-round rule
        # windows.
        self._scn_runtime = None
        self._scn_round0 = 0
        # vitals counter: detections this daemon's own detector fired
        # (drain-free — the Vitals RPC reports cumulative counts)
        self._det_total = 0
        # the per-node log IS a schema event stream (obs/schema.py): a
        # self-describing header row opens it, and every log site's kind
        # rewrites through LOG_KIND_MAP on write
        if not self.log_path.exists() or self.log_path.stat().st_size == 0:
            with open(self.log_path, "a") as f:
                f.write(json.dumps(obs_schema.header(
                    "deploy-node", n=n, node=idx)) + "\n")

    # -- scenario engine ---------------------------------------------------

    def _scn_round(self) -> int:
        return self.udp.rounds - self._scn_round0

    def scenario_drops(self, src: int, peer_addr: str) -> bool:
        """Whether the armed scenario drops this outgoing gossip datagram."""
        rt = self._scn_runtime
        if rt is None:
            return False
        try:
            dst = int(peer_addr.rsplit(":", 1)[1]) - self.udp_base
        except ValueError:
            return False
        if not 0 <= dst < self.n:
            return False
        return rt.drops(src, dst, self._scn_round())

    # -- plumbing ----------------------------------------------------------

    def addr_to_idx(self, addr: str) -> int:
        try:
            return int(addr.rsplit(":", 1)[1]) - self.udp_base
        except (ValueError, IndexError):
            return -1

    def log(self, kind: str, message: str, **fields) -> None:
        # ``round`` is the node's OWN protocol-round clock (heartbeat
        # ticks, detector/udp.py UdpNode.rounds): latency read off the
        # log is then in protocol rounds — it stalls with the process
        # under host load instead of widening like wall-clock windows.
        # ``kind`` rewrites through the schema map (obs/schema.py), so
        # node<i>.log is a flight-recorder stream the timeline analyzer
        # merges directly; unmapped operational kinds pass through and
        # must be listed in UNEXPORTED_LOG_KINDS (the lint test).  The
        # original site name survives as ``site`` — the distributed-grep
        # surface keeps matching the historical kind strings.
        skind = obs_schema.LOG_KIND_MAP.get(kind, kind)
        entry = {"ts": round(time.time(), 3), "node": self.idx,
                 "round": self.udp.rounds,
                 "kind": skind, "message": message, **fields}
        if skind != kind:
            entry["site"] = kind
        with open(self.log_path, "a") as f:
            f.write(json.dumps(entry) + "\n")

    # deadline for data-plane RPCs (multi-MB payloads — the reference's
    # file5/file10 workload takes seconds per transfer on a loaded host);
    # control-plane RPCs keep the snappy 3 s default so an election or
    # repair scan over a stalled-but-connected peer cannot park the
    # control loop for tens of seconds
    DATA_RPC_TIMEOUT = 30.0

    def client(self, idx: int) -> ShimClient:
        # called from gRPC worker threads, the control loop, and announce
        # threads; grpc channels are thread-safe but the cache isn't
        with self._lock:
            c = self._clients.get(idx)
            if c is None:
                c = self._clients[idx] = ShimClient(
                    f"127.0.0.1:{self.rpc_base + idx}", timeout=3.0
                )
            return c

    def view(self) -> list[int]:
        """Node indices in this node's own membership table."""
        out = []
        for addr in list(self.udp.members):
            port = int(addr.rsplit(":", 1)[1])
            out.append(port - self.udp_base)
        return sorted(out)

    def on_detection(self, subject_addr: str) -> None:
        port = int(subject_addr.rsplit(":", 1)[1])
        subject = port - self.udp_base
        self._lost_at.setdefault(subject, time.monotonic())
        self._det_total += 1
        self.log("detect", f"detected failure of node {subject}",
                 subject=subject)

    # -- master duties -----------------------------------------------------

    def _place(self, file: str, live: list[int]) -> list[int]:
        """Hash-ringed placement over the master's live view (reference
        master/master.go:104-131 hashes onto the member ring).  crc32, not
        ``hash()``: Python string hashing is salted per process, and the
        master role migrates between processes on election."""
        import zlib

        if not live:
            return []
        start = zlib.crc32(file.encode()) % len(live)
        return [live[(start + k) % len(live)] for k in
                range(min(REPLICATION_FACTOR, len(live)))]

    def _master_repair(self) -> None:
        now = time.monotonic()
        self._repair_tick += 1
        live = set(self.view())
        # a holder can leave the master's view through a peer's REMOVE
        # broadcast, which never passes through this node's own detector —
        # the view, not record_detection, is the authority on loss
        with self._lock:
            holding = {h for _, hs in self.meta.values() for h in hs}
        for h in holding - live:
            self._lost_at.setdefault(h, now)
        due = {s for s, t0 in self._lost_at.items()
               if now - t0 >= RECOVERY_DELAY * self.period and s not in live}
        if not due:
            return
        retry = False
        with self._lock:
            for file, (version, holders) in list(self.meta.items()):
                dead = [h for h in holders if h in due]
                if not dead:
                    continue
                survivors = [h for h in holders if h in live]
                if not survivors:
                    self.log("lost", f"no live replica of {file}", file=file)
                    continue
                candidates = [x for x in sorted(live)
                              if x not in holders]
                placed, failed = [], False
                for k, tgt in enumerate(candidates[:len(dead)]):
                    # rotate sources ACROSS ticks too, so a copy-less
                    # survivor (refused RemoteReput) doesn't livelock the
                    # retry on the same source forever
                    src = survivors[(k + self._repair_tick) % len(survivors)]
                    try:
                        ok = bool(self.client(src).call(
                            "RemoteReput", source=src, target=tgt,
                            file=file, version=version,
                            timeout=self.DATA_RPC_TIMEOUT,
                        ).get("ok"))
                    except grpc.RpcError as e:
                        ok = False
                        self.log("repair_error", str(e.code()), file=file)
                    if ok:
                        placed.append(tgt)
                        self.log("re_replicate",
                                 f"Re-replicated {file} v{version} from "
                                 f"{src} to [{tgt}]", file=file, source=src,
                                 target=tgt)
                    else:
                        failed = True
                if failed:
                    # keep the dead holders listed so the next control
                    # tick re-detects the deficit and retries; only the
                    # successfully-pushed targets become holders
                    retry = True
                    self.meta[file] = (version, holders + placed)
                else:
                    self.meta[file] = (
                        version, [h for h in holders if h not in due] + placed
                    )
        if not retry:
            for s in due:
                self._lost_at.pop(s, None)

    def _maybe_campaign(self) -> None:
        """Lowest live node runs the distributed revote when the master is
        gone from its own view (reference slave.go:930-1051)."""
        live = self.view()
        if self.master_id in live or not live or live[0] != self.idx:
            return
        votes = 1  # self
        for peer in live:
            if peer == self.idx:
                continue
            try:
                r = self.client(peer).call(
                    "Vote", candidate=self.idx, voter=peer
                )
                votes += 1 if r.get("elected") else 0
            except grpc.RpcError:
                pass
        if votes <= len(live) // 2:
            self.log("election_stall", f"{votes}/{len(live)} votes")
            return
        # won.  Rebuild the metadata from per-node store listings BEFORE
        # announcing: each AssignNewMaster flips that peer's master pointer
        # immediately, so a put raced between announcement and rebuild
        # would land in a meta dict the rebuild then replaces (observed as
        # a lost file).  Gather -> install atomically -> announce.
        per_holder: dict[str, list[tuple[int, int]]] = {}  # file -> [(peer, v)]
        for peer in live:
            listing: dict[str, int] = {}
            if peer == self.idx:
                listing = self.store.listing()
            else:
                try:
                    r = self.client(peer).call("Store", node=peer)
                    listing = dict(r.get("listing") or {})
                except grpc.RpcError:
                    continue
            for file, version in listing.items():
                per_holder.setdefault(file, []).append((peer, int(version)))
        with self._lock:
            self.master_id = self.idx
            # keep only the max-version holders per file (stale replicas
            # are repaired by read-repair / the next put, not trusted here)
            self.meta = {}
            for file, pairs in per_holder.items():
                v = max(ver for _, ver in pairs)
                self.meta[file] = (v, [p for p, ver in pairs if ver == v])
        for peer in live:
            if peer == self.idx:
                continue
            try:
                # the reply's listing (reference slave.go:1010-1051 shape)
                # is redundant here — the rebuild already ran
                self.client(peer).call(
                    "AssignNewMaster", node=peer, master=self.idx
                )
            except grpc.RpcError:
                pass
        self.log("elected", f"node {self.idx} became master with "
                 f"{votes}/{len(live)} votes", votes=votes)

    def _announce(self, peers: list[int]) -> None:
        for peer in peers:
            try:
                self.client(peer).call(
                    "AssignNewMaster", node=peer, master=self.idx
                )
            except grpc.RpcError:
                pass

    def _control_loop(self) -> None:
        tick = 0
        while not self._stop.wait(self.period):
            tick += 1
            try:
                if self.master_id == self.idx:
                    self._master_repair()
                    if tick % 20 == 0:
                        # idempotent re-announce: a peer whose server was
                        # slow during the election's single AssignNewMaster
                        # fan-out would otherwise point at the dead master
                        # forever (it never campaigns unless it is lowest).
                        # Fire-and-forget thread: a hung peer's RPC timeout
                        # must not stall the repair loop it shares a thread
                        # with
                        peers = [p for p in self.view() if p != self.idx]
                        threading.Thread(
                            target=self._announce, args=(peers,), daemon=True
                        ).start()
                else:
                    self._maybe_campaign()
            except Exception as e:  # keep the daemon alive; log the fault
                self.log("control_error", repr(e))

    # -- RPC handlers ------------------------------------------------------

    def Put(self, req, ctx):
        file = req["file"]
        data = base64.b64decode(req.get("data_b64", ""))
        info = self.client(self.master_id).call(
            "GetPutInfo", file=file, confirm=bool(req.get("confirm")),
            callback=f"127.0.0.1:{self.rpc_base + self.idx}",
        )
        if not info.get("ok"):
            return {"ok": False}
        version = int(info.get("version", 1))
        payload = base64.b64encode(data).decode()
        for replica in info.get("replicas") or []:
            self.client(int(replica)).call(
                "PutFileData", node=int(replica), file=file,
                version=version, data_b64=payload,
                timeout=self.DATA_RPC_TIMEOUT,
            )
        # commit: the master publishes the new version only now that every
        # replica holds the bytes (reference Update_file_version).  A
        # refused commit means the plan expired under us (we stalled past
        # the conflict window) — report failure so the caller retries the
        # whole put instead of believing unpublished bytes are durable
        r = self.client(self.master_id).call(
            "UpdateFileVersion", node=self.idx, file=file, version=version
        )
        if not r.get("ok"):
            return {"ok": False, "expired": True}
        self.log("put", f"put {file} v{version}", file=file)
        return {"ok": True}

    def GetPutInfo(self, req, ctx):
        file = req["file"]
        now = time.time()
        with self._lock:
            # expire abandoned plans: a writer that took a plan and died
            # without committing must not keep prompting later writers,
            # and its pending entry must not leak.  A last_put stamp no
            # newer than the expired plan belonged to that aborted write
            stale = [(k, t) for k, (_r, t) in self.pending.items()
                     if now - t >= WRITE_CONFLICT_WINDOW]
            for k, t in stale:
                del self.pending[k]
                lp = self.last_put.get(k[0])
                if lp and lp[0] <= t:
                    del self.last_put[k[0]]
            prev = self.last_put.get(file)
        conflict = prev is not None and now - prev[0] < WRITE_CONFLICT_WINDOW
        if conflict and not req.get("confirm"):
            # ask the REQUESTER to confirm the overwrite
            # (server.go:155-177); its own policy answers.  The RPC runs
            # with no lock held — a dead/hung requester must not stall
            # the master's repair loop or other writers for its timeout
            cb = req.get("callback") or ""
            ok = False
            if cb:
                try:
                    c = ShimClient(cb, timeout=5.0)
                    ok = bool(c.call("AskForConfirmation",
                                     file=file).get("confirm"))
                    c.close()
                except grpc.RpcError:
                    ok = False
            if not ok:
                return {"ok": False, "conflict": True}
        with self._lock:
            version, holders = self.meta.get(file, (0, []))
            live = self.view()
            replicas = holders if holders else self._place(file, live)
            replicas = [r for r in replicas if r in live] or \
                self._place(file, live)
            # two-phase, the reference's own flow (Get_put_info hands out
            # the plan, Update_file_version commits after the transfer):
            # committing here would strand the readable version if the
            # writer dies between this reply and its pushes.  The new
            # version goes past every in-flight one so concurrent writers
            # never share a pending slot
            new_v = max([version] + [v for (f, v) in self.pending
                                     if f == file]) + 1
            self.pending[(file, new_v)] = (list(replicas), now)
            self.last_put[file] = (now, req.get("callback") or "")
        return {"ok": True, "conflict": conflict,
                "replicas": list(replicas), "version": new_v}

    def PutFileData(self, req, ctx):
        data = base64.b64decode(req.get("data_b64", ""))
        self.store.put(req["file"], data, int(req.get("version", 1)))
        return {"ok": True}

    def GetFileData(self, req, ctx):
        data = self.store.get(req["file"])
        if data is None:
            return {"local_version": -1}
        return {"local_version": self.store.version(req["file"]),
                "data_b64": base64.b64encode(data).decode()}

    def GetFileInfo(self, req, ctx):
        with self._lock:
            version, holders = self.meta.get(req["file"], (-1, []))
        return {"replicas": list(holders), "version": version}

    def Get(self, req, ctx):
        info = self.client(self.master_id).call("GetFileInfo",
                                                file=req["file"])
        want = int(info.get("version", -1))
        live = set(self.view())
        for holder in info.get("replicas") or []:
            if int(holder) not in live:
                continue
            try:
                r = self.client(int(holder)).call(
                    "GetFileData", node=int(holder), file=req["file"],
                    timeout=self.DATA_RPC_TIMEOUT,
                )
            except grpc.RpcError:
                continue
            # exact-version gate: a stale replica (failed push, repair
            # from a stale holder) must not serve old bytes as current,
            # and a NEWER-than-committed local version means a writer
            # pushed and died before UpdateFileVersion — serving those
            # bytes would be a dirty read of an aborted two-phase put
            # (round-5 advisor)
            if want >= 0 and int(r.get("local_version", -1)) == want:
                return {"found": True, "data_b64": r.get("data_b64", "")}
        return {"found": False}

    def GetDeleteInfo(self, req, ctx):
        with self._lock:
            _, holders = self.meta.get(req["file"], (0, []))
            self.meta.pop(req["file"], None)
            self.last_put.pop(req["file"], None)
            for k in [k for k in self.pending if k[0] == req["file"]]:
                del self.pending[k]
        return {"old_replicas": list(holders)}

    def DeleteFileData(self, req, ctx):
        self.store.delete(req["file"])
        return {"ok": True}

    def Delete(self, req, ctx):
        info = self.client(self.master_id).call("GetDeleteInfo",
                                                file=req["file"])
        for holder in info.get("old_replicas") or []:
            try:
                self.client(int(holder)).call(
                    "DeleteFileData", node=int(holder), file=req["file"]
                )
            except grpc.RpcError:
                pass
        return {"ok": True}

    def Ls(self, req, ctx):
        info = self.client(self.master_id).call("GetFileInfo",
                                                file=req["file"])
        return {"replicas": info.get("replicas") or []}

    def Store(self, req, ctx):
        return {"listing": self.store.listing()}

    def RemoteReput(self, req, ctx):
        """Master -> surviving holder: push the file to the new target."""
        file, target = req["file"], int(req["target"])
        data = self.store.get(file)
        if data is None:
            # OkReply carries only `ok` — a free-text field here would
            # fail response serialization and surface as an opaque
            # RpcError at the master instead of a clean refusal
            self.log("reput_miss", f"no local copy of {file}", file=file)
            return {"ok": False}
        self.client(target).call(
            "PutFileData", node=target, file=file,
            version=int(req.get("version", 1)),
            data_b64=base64.b64encode(data).decode(),
            timeout=self.DATA_RPC_TIMEOUT,
        )
        self.log("reput", f"pushed {file} to {target}", file=file,
                 target=target)
        return {"ok": True}

    def Vote(self, req, ctx):
        """Grant iff the candidate is the lowest node in MY live view."""
        live = self.view()
        grant = bool(live) and int(req["candidate"]) == live[0]
        return {"elected": grant, "votes": 1 if grant else 0}

    def AssignNewMaster(self, req, ctx):
        with self._lock:
            changed = self.master_id != int(req["master"])
            self.master_id = int(req["master"])
        if changed:  # re-announces are periodic; log transitions only
            self.log("new_master", f"master is now {self.master_id}",
                     master=self.master_id)
        return {"listing": self.store.listing()}

    def AskForConfirmation(self, req, ctx):
        return {"confirm": self.auto_confirm}

    def ScenarioLoad(self, req, ctx):
        """Arm a fault scenario on THIS node (scenarios/schedule.py JSON in
        ``data_b64``).  The launcher fans the same table out to every
        node — the deploy backend of the scenario engine; windows count
        from each node's receipt.  An empty payload disarms."""
        from gossipfs_tpu.scenarios.runtime import ScenarioRuntime
        from gossipfs_tpu.scenarios.schedule import FaultScenario

        payload = base64.b64decode(req.get("data_b64", "") or "")
        if not payload:
            self._scn_runtime = None
            self.log("scenario_clear", "scenario cleared")
            return {"ok": True}
        try:
            sc = FaultScenario.from_json(payload.decode())
        except (ValueError, KeyError) as e:
            self.log("scenario_error", repr(e))
            return {"ok": False}
        if sc.n != self.n:
            self.log("scenario_error",
                     f"scenario n={sc.n} != cluster n={self.n}")
            return {"ok": False}
        self._scn_round0 = self.udp.rounds
        self._scn_runtime = ScenarioRuntime(sc)
        self.log("scenario", f"armed scenario {sc.name}",
                 scenario=sc.name, horizon=sc.horizon)
        return {"ok": True}

    def ScenarioStatus(self, req, ctx):
        """This node's view of the armed scenario (GrepReply lines).

        Also carries the node's protocol-round tick counter, its members'
        heartbeat counters, and — when suspicion is armed — the node's
        suspicion vitals (live suspects, refutation/confirm totals): the
        per-node state an operator (or a test) wants next to the fault
        state, all riding the one status RPC."""
        rt = self._scn_runtime
        doc = {"node": self.idx, "armed": rt is not None,
               "rounds": self.udp.rounds,
               "tick_error": repr(self.udp.last_tick_error)
               if self.udp.last_tick_error else "",
               "hb": {a: m.hb for a, m in self.udp.members.items()}}
        doc["suspicion_armed"] = self._env.suspicion is not None
        if self.udp._sus is not None:
            srt = self.udp._sus[1]
            # the ONE vitals producer (SuspicionRuntime.status) so the
            # fields cannot drift between engines; only `suspects` is
            # remapped from addresses to node indices
            sdoc = srt.status()
            sdoc["suspects"] = sorted(
                int(a.rsplit(":", 1)[1]) - self.udp_base
                for a in srt.suspects
            )
            doc.update(sdoc)
        if rt is not None:
            doc.update(rt.status(self._scn_round()))
        return {"lines": [doc]}

    def SuspicionLoad(self, req, ctx):
        """Arm the suspicion lifecycle on THIS node (suspicion/params.py
        JSON in ``data_b64``; empty payload disarms).  The launcher fans
        the same params out to every node — the deploy backend of the
        suspicion subsystem, riding the control plane like ScenarioLoad."""
        from gossipfs_tpu.suspicion.params import SuspicionParams

        payload = base64.b64decode(req.get("data_b64", "") or "")
        if not payload:
            self._env.suspicion = None
            self.log("suspicion_clear", "suspicion cleared")
            return {"ok": True}
        try:
            params = SuspicionParams.from_json(payload.decode())
        except (ValueError, KeyError) as e:
            self.log("suspicion_error", repr(e))
            return {"ok": False}
        self._env.suspicion = params
        self.log("suspicion", f"armed suspicion t_suspect={params.t_suspect}",
                 t_suspect=params.t_suspect)
        return {"ok": True}

    def Vitals(self, req, ctx):
        """THIS node's uniform vitals row (obs.schema.VITALS_FIELDS),
        riding GrepReply Struct lines like ScenarioStatus.  Ground-truth
        fields the per-process deployment cannot know (n_alive,
        false_positives, fp_suppressed — other processes' liveness) are
        ABSENT, rendered ``n/a`` by consumers, never 0 (the round-8
        status-shape convention)."""
        doc = {
            "engine": "deploy",
            "node": self.idx,
            "round": self.udp.rounds,
            "members": len(self.udp.members),
            "detections": self._det_total,
        }
        if self.udp._sus is not None:
            srt = self.udp._sus[1]
            doc.update(suspects_now=len(srt.suspects),
                       suspects_entered=srt.entered,
                       refutations=srt.refutations,
                       confirms=srt.confirms)
        return {"lines": [doc]}

    def UpdateFileVersion(self, req, ctx):
        """The writer's commit: the pushes landed, publish the placement."""
        file, version = req["file"], int(req["version"])
        with self._lock:
            entry = self.pending.pop((file, version), None)
            cur_v, _holders = self.meta.get(file, (0, []))
            if entry is None or version < cur_v:
                # the plan expired (writer stalled past the conflict
                # window and the GetPutInfo sweep reclaimed it) or this
                # is a stale duplicate: publishing would pin the version
                # to holders that never took these bytes.  The writer
                # must retry the whole put
                return {"ok": False, "expired": True}
            self.meta[file] = (version, entry[0])
            # refresh the conflict stamp at commit: the window measures
            # from the write that actually published
            lp = self.last_put.get(file)
            self.last_put[file] = (time.time(), lp[1] if lp else "")
        return {"ok": True}

    def Lsm(self, req, ctx):
        return {"members": self.view()}

    def AliveNodes(self, req, ctx):
        return {"nodes": self.view()}

    def Grep(self, req, ctx):
        """Serve THIS node's own log (reference: each machine greps its own
        Machine.log, logger/logger.go:28-44); the querier fans out."""
        import re
        pat = re.compile(req.get("pattern", ""))
        lines = []
        if self.log_path.exists():
            for line in self.log_path.read_text().splitlines():
                if pat.search(line):
                    lines.append(json.loads(line))
        return {"lines": lines}

    def ShowMetadata(self, req, ctx):
        with self._lock:
            return {"files": {
                f: {"version": v, "node_list": hs}
                for f, (v, hs) in self.meta.items()
            }}

    METHODS = (
        "Put", "GetPutInfo", "PutFileData", "GetFileData", "GetFileInfo",
        "Get", "GetDeleteInfo", "DeleteFileData", "Delete", "Ls", "Store",
        "RemoteReput", "Vote", "AssignNewMaster", "AskForConfirmation",
        "UpdateFileVersion", "Lsm", "AliveNodes", "Grep", "ShowMetadata",
        "ScenarioLoad", "ScenarioStatus", "SuspicionLoad", "Vitals",
    )

    # -- lifecycle ---------------------------------------------------------

    def _generic_handler(self) -> grpc.GenericRpcHandler:
        def make(method):
            fn = getattr(self, method)

            def unary(request, context):
                return fn(request, context)

            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=wire.request_deserializer(method),
                response_serializer=wire.response_serializer(method),
            )

        return grpc.method_handlers_generic_handler(
            SERVICE, {m: make(m) for m in self.METHODS}
        )

    def serve_forever(self) -> None:
        from concurrent import futures

        # membership loop on a background thread
        loop = asyncio.new_event_loop()
        self._loop = loop
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        asyncio.run_coroutine_threadsafe(self.udp.start(), loop).result(10)
        if self.idx != self.introducer:
            intro_addr = f"127.0.0.1:{self.udp_base + self.introducer}"
            loop.call_soon_threadsafe(
                self.udp._send, intro_addr,
                f"{self.udp.addr}{CMD_SEP}JOIN",
            )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=wire.message_size_options(),
        )
        self._server.add_generic_rpc_handlers((self._generic_handler(),))
        self._server.add_insecure_port(f"127.0.0.1:{self.rpc_base + self.idx}")
        self._server.start()
        ctrl = threading.Thread(target=self._control_loop, daemon=True)
        ctrl.start()
        self.log("start", f"node {self.idx} up "
                 f"(udp {self.udp.port}, rpc {self.rpc_base + self.idx})")
        try:
            self._server.wait_for_termination()
        finally:
            self._stop.set()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--idx", type=int, required=True)
    p.add_argument("--n", type=int, required=True)
    p.add_argument("--udp-base", type=int, default=19000)
    p.add_argument("--rpc-base", type=int, default=19100)
    p.add_argument("--dir", type=str, required=True)
    p.add_argument("--period", type=float, default=0.1)
    p.add_argument("--t-fail", type=int, default=5)
    p.add_argument("--no-auto-confirm", action="store_true")
    p.add_argument("--introducer", type=int, default=0)
    args = p.parse_args(argv)
    NodeDaemon(
        args.idx, args.n, args.udp_base, args.rpc_base, args.dir,
        period=args.period, t_fail=args.t_fail,
        auto_confirm=not args.no_auto_confirm, introducer=args.introducer,
    ).serve_forever()


if __name__ == "__main__":
    main()
