"""Cluster launcher for the one-process-per-node deployment.

Spawns ``n`` ``gossipfs_tpu.deploy.node`` OS processes (each with its own
UDP gossip endpoint, replica store, log file, and gRPC server — the
reference's per-machine topology, main.go:14-35), then exposes client
helpers and the kill -9 scenario the deployment exists to demonstrate:

    python -m gossipfs_tpu.deploy.launcher --n 5

prints one JSON document with measured wall-clock times for failure
detection (the gossip way: the victim vanishes from a SURVIVOR's view),
re-replication (the replica set heals to full strength on live nodes),
byte-identical recovery of the file, and a master-kill election.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

from gossipfs_tpu.shim import retry
from gossipfs_tpu.shim.client import ShimClient


def _free_port_base(span: int, *, tcp: bool = True, udp: bool = True) -> int:
    """A base port with ``span`` free ports above it.

    Probes EVERY port in the window — TCP and/or UDP per the flags; the
    deploy cluster needs both (gossip sockets on UDP, RPC servers on
    TCP), the in-process udp campaign runner (campaigns/engines.py)
    UDP only — by bind-and-hold before releasing the lot (round-5
    advisor: the old single-ephemeral probe let two concurrent clusters
    land overlapping windows and cross-talk; round 14 re-observed the
    same failure between a tier-1 udp smoke and a concurrent campaign
    run on a fixed base port).  A race remains between release and the
    cluster's own binds, but it is milliseconds wide instead of
    window-sized.
    """
    for _ in range(64):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        if base + span >= 65000:
            continue
        held: list[socket.socket] = []
        try:
            for p in range(base, base + span):
                if tcp:
                    t = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                    t.bind(("127.0.0.1", p))
                    held.append(t)
                if udp:
                    u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                    u.bind(("127.0.0.1", p))
                    held.append(u)
        except OSError:
            continue
        finally:
            for h in held:
                h.close()
        return base
    raise RuntimeError("no free port window")


class Cluster:
    """n node processes + per-node ShimClients."""

    def __init__(self, n: int, period: float = 0.1, root: str | None = None,
                 rpc_timeout: float = 5.0, t_fail: int = 5,
                 ctrl_timeout: float = 2.0):
        self.n = n
        self.period = period
        self.t_fail = t_fail  # detection timeout in rounds (slave.go:24);
                              # partition scenarios on small rings raise it
                              # — mid-split freshness paths stretch across
                              # the dropped boundary, and the default 5 sits
                              # at the cascade threshold (BASELINE's ring-
                              # fragility finding, now reproducible on demand)
        self.root = root or tempfile.mkdtemp(prefix="gossipfs_deploy_")
        # multi-MB puts fan out 4 replica pushes through the writer's RPC:
        # on a loaded 1-core host the reference-size workload (5-10 MB,
        # bench/ref_workflow.py) needs deadlines past the 5 s default
        self.rpc_timeout = rpc_timeout
        # per-RPC deadline for the small idempotent CONTROL-PLANE verbs
        # (scenario/suspicion pushes, vitals, status): far shorter than
        # the data-plane timeout — a dead node should cost a campaign
        # runner ~2 s, not 5+ s per probe — with transient failures
        # retried under the shared bounded-backoff discipline
        # (shim/retry.py; round 14)
        self.ctrl_timeout = ctrl_timeout
        base = _free_port_base(2 * n + 16)
        self.udp_base = base
        self.rpc_base = base + n + 8
        self.procs: dict[int, subprocess.Popen] = {}
        self._clients: dict[int, ShimClient] = {}

    def client(self, idx: int) -> ShimClient:
        c = self._clients.get(idx)
        if c is None:
            c = self._clients[idx] = ShimClient(
                f"127.0.0.1:{self.rpc_base + idx}", timeout=self.rpc_timeout
            )
        return c

    def spawn(self, idx: int) -> None:
        env = dict(os.environ)
        # the node imports no jax; scrub the TPU tunnel vars anyway so a
        # transitive import can never dial the chip from N processes
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        self.procs[idx] = subprocess.Popen(
            [sys.executable, "-m", "gossipfs_tpu.deploy.node",
             "--idx", str(idx), "--n", str(self.n),
             "--udp-base", str(self.udp_base),
             "--rpc-base", str(self.rpc_base),
             "--dir", self.root, "--period", str(self.period),
             "--t-fail", str(self.t_fail)],
            env=env,
        )

    def _probe_lsm(self, idx: int) -> list[int] | None:
        """One liveness probe on a FRESH throwaway channel.

        Boot-time probing must NOT reuse the cached ``client(idx)``
        channel: a channel whose first connect hit the not-yet-bound
        port enters grpc's transient-failure backoff, and rapid retries
        on it never reconnect — observed on this host as a LIVE server
        staying "unavailable" for 40+ s (the whole deploy lane failed to
        boot).  A fresh channel connects the moment the server is up;
        the cached clients are only created after start() returns, when
        every server answers.
        """
        c = ShimClient(f"127.0.0.1:{self.rpc_base + idx}", timeout=2.0)
        try:
            return c.lsm(idx)
        except Exception:
            return None
        finally:
            c.close()

    def _probe_ready(self, idx: int) -> bool:
        """Full view AND every heartbeat counter past the detection grace.

        View convergence alone is NOT "the cluster is up": members whose
        counters still sit at hb <= 1 are inside the reference's
        detection grace (slave.go:468-469) — kill one then and NO
        survivor can ever declare it failed (its entry is frozen at
        hb=1, permanently grace-protected).  Scenarios that start with a
        kill therefore need counters > 1 everywhere, which also proves
        gossip (not just the introducer's JOIN push) actually flows.
        """
        c = ShimClient(f"127.0.0.1:{self.rpc_base + idx}", timeout=2.0)
        try:
            line = c.call("ScenarioStatus")["lines"][0]
            hb = line.get("hb") or {}
            if len(hb) != self.n:
                return False
            # below min_group (NodeDaemon's default 4) nodes stay in the
            # refresh-only branch and never bump — counters sit at 0
            # forever, AND detection is disabled anyway, so the grace
            # concern the hb check exists for cannot arise
            return self.n < 4 or min(hb.values()) > 1
        except Exception:
            return False
        finally:
            c.close()

    def start(self, timeout: float = 30.0) -> None:
        self.spawn(0)  # introducer first (reference SPOF, slave.go:22)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._probe_lsm(0) is not None:
                break
            time.sleep(0.25)
        else:
            raise RuntimeError("introducer did not come up")
        for i in range(1, self.n):
            self.spawn(i)
        # wait until every node's own view holds the full cohort with
        # every counter past the hb-grace (see _probe_ready)
        while time.monotonic() < deadline:
            if all(self._probe_ready(i) for i in range(self.n)):
                return
            time.sleep(0.25)
        raise RuntimeError("cluster did not converge")

    def kill9(self, idx: int) -> None:
        self.procs[idx].send_signal(signal.SIGKILL)
        self.procs[idx].wait()

    def _ctrl_call(self, idx: int, method: str, **request):
        """One idempotent control-plane RPC to node ``idx`` under the
        shared bounded-backoff discipline (shim/retry.py): a short
        per-RPC deadline (``ctrl_timeout``) so a dead node fails fast,
        transient codes (UNAVAILABLE mid-restart, DEADLINE_EXCEEDED on
        a starved host, backpressure) retried with exponential backoff,
        total retry time hard-bounded — replacing the round-7 one-shot
        try/except fan-outs that silently dropped a push whenever a
        node hiccuped for one scheduling quantum.  ``retries=False``
        disables the ShimClient's own backpressure loop: THIS is the
        one retry layer (nesting the two would multiply the bound —
        ~4 x the inner 10 s ceiling instead of the ~3 s promised here).
        """
        return retry.call_with_backoff(
            lambda: self.client(idx).call(
                method, timeout=self.ctrl_timeout, retries=False,
                **request),
            retryable=retry.grpc_transient,
            attempts=4, base_delay=0.1, max_delay=0.8,
            total_deadline=3.0,
        )

    def load_scenario(self, scenario) -> list[int]:
        """Push one scenarios.FaultScenario rule table to every live node
        (the deploy backend of the scenario engine).  Each node anchors
        the rule windows at its own receipt; the fan-out completes in
        milliseconds against multi-period windows.  Returns the node ids
        that acked."""
        payload = base64.b64encode(scenario.to_json().encode()).decode()
        acked = []
        for idx, proc in self.procs.items():
            if proc.poll() is not None:
                continue
            try:
                ok = self._ctrl_call(
                    idx, "ScenarioLoad", file=scenario.name,
                    data_b64=payload,
                ).get("ok")
            except Exception:
                ok = False
            if ok:
                acked.append(idx)
        return acked

    def load_suspicion(self, params) -> list[int]:
        """Push one suspicion.SuspicionParams to every live node (the
        deploy backend of the suspicion subsystem; None disarms).
        Returns the node ids that acked."""
        payload = ("" if params is None
                   else base64.b64encode(params.to_json().encode()).decode())
        acked = []
        for idx, proc in self.procs.items():
            if proc.poll() is not None:
                continue
            try:
                ok = self._ctrl_call(
                    idx, "SuspicionLoad", file="suspicion",
                    data_b64=payload,
                ).get("ok")
            except Exception:
                ok = False
            if ok:
                acked.append(idx)
        return acked

    def vitals(self) -> list[dict]:
        """Collect every live node's uniform vitals row (the `Vitals`
        RPC; obs.schema.VITALS_FIELDS).  Dead nodes are skipped — their
        absence, not a zeroed row, is the signal."""
        lines: list[dict] = []
        for idx, proc in self.procs.items():
            if proc.poll() is not None:
                continue
            try:
                lines += self._ctrl_call(idx, "Vitals").get("lines") or []
            except Exception:
                pass
        return lines

    def scenario_status(self) -> list[dict]:
        """Collect every node's ScenarioStatus line (skipping dead nodes)."""
        lines: list[dict] = []
        for idx, proc in self.procs.items():
            if proc.poll() is not None:
                continue
            try:
                lines += self._ctrl_call(idx, "ScenarioStatus").get(
                    "lines") or []
            except Exception:
                pass
        return lines

    def wait_detected(self, victim: int, observer: int,
                      timeout: float = 30.0) -> float:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if victim not in self.client(observer).lsm(observer):
                return time.monotonic() - t0
            time.sleep(self.period / 4)
        raise TimeoutError(f"{observer} never dropped {victim}")

    def wait_repaired(self, file: str, via: int, expect: int,
                      timeout: float = 60.0) -> float:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            live = set(self.client(via).lsm(via))
            reps = set(self.client(via).ls(file))
            if len(reps) >= expect and reps <= live:
                return time.monotonic() - t0
            time.sleep(self.period / 2)
        raise TimeoutError(f"{file} never healed")

    def wait_new_master(self, via: int, old: int, timeout: float = 60.0) -> float:
        """Wait until a put through ``via`` succeeds under a new master."""
        t0 = time.monotonic()
        probe = b"election-probe"
        while time.monotonic() - t0 < timeout:
            try:
                if self.client(via).put("___probe.txt", probe, confirm=True):
                    return time.monotonic() - t0
            except Exception:
                pass
            time.sleep(self.period)
        raise TimeoutError("no new master answered a put")

    def stop(self) -> None:
        for c in self._clients.values():
            c.close()
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def scenario(n: int = 5, period: float = 0.1) -> dict:
    """The deployment's reason to exist, measured end to end."""
    cluster = Cluster(n, period=period)
    out: dict = {"n": n, "period_s": period}
    try:
        t0 = time.monotonic()
        cluster.start()
        out["startup_convergence_s"] = round(time.monotonic() - t0, 3)

        data = os.urandom(256 * 1024)  # 256 KB payload
        assert cluster.client(1).put("wiki.txt", data)
        holders = cluster.client(1).ls("wiki.txt")
        out["put_replicas"] = holders

        # kill -9 a NON-master replica holder; watch from a survivor
        victim = next(h for h in holders if h != 0)
        observer = next(i for i in range(n) if i != victim and i != 0)
        cluster.kill9(victim)
        out["victim"] = victim
        out["detect_s"] = round(
            cluster.wait_detected(victim, observer), 3
        )
        out["repair_s"] = round(
            cluster.wait_repaired("wiki.txt", observer, min(4, n - 1)), 3
        )
        got = cluster.client(observer).get("wiki.txt")
        out["bytes_identical_after_repair"] = got == data

        # kill -9 the master; the lowest live node must take over
        cluster.kill9(0)
        out["election_s"] = round(cluster.wait_new_master(observer, 0), 3)
        # distributed grep: each node serves only its own log; fan out
        hits = []
        for i in range(n):
            if i in (0, victim):
                continue
            hits += cluster.client(i).call(
                "Grep", pattern="became master"
            ).get("lines") or []
        out["election_logged"] = bool(hits)
        return out
    finally:
        cluster.stop()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=5)
    p.add_argument("--period", type=float, default=0.1)
    args = p.parse_args(argv)
    print(json.dumps(scenario(args.n, args.period)))


if __name__ == "__main__":
    main()
