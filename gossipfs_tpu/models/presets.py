"""Named protocol models: the five BASELINE.json benchmark configurations.

"Models" in this framework are protocol configurations of the gossip
simulator (the sim *is* the model of the distributed system), the way the
reference's "model" is its hardcoded constant block (slave/slave.go:21-29).
"""

from __future__ import annotations

import dataclasses

from gossipfs_tpu.config import SimConfig


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A benchmark scenario: protocol config + fault schedule + horizon."""

    name: str
    config: SimConfig
    rounds: int
    crash_rate: float = 0.0
    rejoin_rate: float = 0.0
    sdfs_cosim: bool = False
    n_files: int = 0


def reference_parity_10() -> Scenario:
    """Config 1: 10 nodes, ring fanout 3 — the reference's real deployment
    shape (use detector/udp.py for actual sockets, this for the sim twin)."""
    return Scenario(name="parity-10", config=SimConfig(n=10), rounds=120)


def sim_1k() -> Scenario:
    """Config 2: 1k nodes, ring fanout 3, no churn (CPU-feasible)."""
    return Scenario(name="sim-1k", config=SimConfig(n=1024), rounds=120)


def sim_10k_crash() -> Scenario:
    """Config 3: 10k nodes, 1% crash-stop churn.

    Random log-N fanout with gossip-only dissemination and a real cooldown:
    at 10k the ring's freshness diameter dwarfs t_fail, so ring mode would be
    one continuous false-positive storm (see
    tests/test_rounds.py::test_emergent_false_positives_beyond_reference_scale).

    N is 10,240 ("10k-class"): lane-aligned (N % 128 == 0) so the pallas
    merge kernel runs instead of silently falling back to the XLA gather
    path at a fraction of the bandwidth.
    """
    n = 10_240
    return Scenario(
        name="sim-10k-crash",
        config=SimConfig(
            n=n,
            topology="random",
            fanout=SimConfig.log_fanout(n),
            remove_broadcast=False,
            fresh_cooldown=True,
            t_cooldown=12,
            # the TPU fast path (falls back to XLA off-TPU): fused pallas
            # merge, int8 gossip view, int16 relative heartbeat storage
            merge_kernel="pallas",
            view_dtype="int8",
            hb_dtype="int16",
            merge_block_c=16_384,
        ),
        rounds=120,
        crash_rate=0.01,
    )


def sim_100k() -> Scenario:
    """Config 4: 100k nodes, fanout log N, 5% churn + preemption (v5e-8).

    N is 131,072 (2^17, "100k-class"): lane-aligned for the pallas merge
    kernel at full block sizes, and it divides an 8-chip v5e mesh into
    16,384-column shards — each chip then runs exactly the single-chip
    headline shape under parallel.mesh.run_rounds_sharded.
    """
    n = 131_072
    return Scenario(
        name="sim-100k",
        config=SimConfig(
            n=n,
            topology="random",
            fanout=SimConfig.log_fanout(n),
            remove_broadcast=False,
            fresh_cooldown=True,
            t_cooldown=12,
            merge_kernel="pallas",
            view_dtype="int8",
            hb_dtype="int16",
            merge_block_c=16_384,
        ),
        rounds=60,
        crash_rate=0.05,
        rejoin_rate=0.05,
    )


def sim_100k_sdfs() -> Scenario:
    """Config 5: config 4 plus SDFS replica re-placement consuming the sim
    membership view (gossipfs_tpu.cosim)."""
    sc = sim_100k()
    return dataclasses.replace(sc, name="sim-100k-sdfs", sdfs_cosim=True, n_files=1000)


ALL = {
    s.name: s
    for s in (
        reference_parity_10(),
        sim_1k(),
        sim_10k_crash(),
        sim_100k(),
        sim_100k_sdfs(),
    )
}
