"""(k, m) systematic Reed-Solomon over GF(256) as pure tensor ops.

The field is GF(2^8) under the AES-adjacent primitive polynomial
``x^8 + x^4 + x^3 + x^2 + 1`` (0x11d) with generator 2 — the same field
every production RS deployment uses, so the exp/log tables are 256
bytes each and every field multiply is two gathers and an add.  That
makes GF matrix multiplication a *batched tensor program*: gather logs,
add, gather exps, mask zeros, XOR-reduce the shared axis — exactly a
matmul with (+, x) swapped for (xor, table-mul), which is why the
tensor path (``gf_matmul`` / ``encode``) runs under jit on the same
device as the detector's scan.

The code is SYSTEMATIC: generator ``G = [I | P]`` with ``P`` a k x m
Cauchy block, ``P[i][j] = inv(x_i ^ y_j)`` over the disjoint evaluation
points ``x_i = i`` and ``y_j = k + j`` (so k + m <= 256).  Every square
submatrix of a Cauchy matrix is nonsingular, hence every k x k
submatrix of ``G`` is invertible and the code is MDS: ANY k of the
k + m fragments reconstruct the payload (the classic Cauchy-RS
construction, cf. Jerasure).  Data fragments are the payload rows
verbatim — reads with zero fragment loss never touch the field at all.

Decode inverts the k x k survivor submatrix ON HOST (GF Gauss-Jordan
over a tiny k x k, ``gf_matinv``) and applies the inverse as one more
batched matmul — tensor or numpy; the two paths are pinned bit-exact
by tests/test_erasure.py.

The numpy twin (``*_np``) is the CoSim byte path: the co-sim's
fragments are host ``bytes``, and shipping every 4 KiB payload through
a device round-trip would be dishonest benchmarking (BASELINE.md's
CPU-pinned boundary).  On-TPU encode beside the detector scan is the
named ROADMAP follow-up.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# GF(256) tables — poly 0x11d, generator 2
# ---------------------------------------------------------------------------

_POLY = 0x11D


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.int32)   # doubled so log(a)+log(b) <= 508 indexes directly
    log = np.zeros(256, dtype=np.int32)   # log[0] unused — callers mask zeros
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


EXP, LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Scalar field multiply (host reference path)."""
    if a == 0 or b == 0:
        return 0
    return int(EXP[LOG[a] + LOG[b]])


def gf_inv(a: int) -> int:
    """Multiplicative inverse; 0 has none."""
    if a == 0:
        raise ZeroDivisionError("0 has no inverse in GF(256)")
    return int(EXP[255 - LOG[a]])


def gf_div(a: int, b: int) -> int:
    """a / b in the field."""
    if b == 0:
        raise ZeroDivisionError("division by 0 in GF(256)")
    if a == 0:
        return 0
    return int(EXP[(LOG[a] - LOG[b]) % 255])


# ---------------------------------------------------------------------------
# GF matrix multiply — numpy twin and jit tensor path, pinned bit-exact
# ---------------------------------------------------------------------------


def gf_matmul_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """uint8 [r, c] x [c, L] -> [r, L] over GF(256), host side."""
    a = np.asarray(a, dtype=np.int32)
    b = np.asarray(b, dtype=np.int32)
    prod = EXP[LOG[a][:, :, None] + LOG[b][None, :, :]]
    nz = (a[:, :, None] != 0) & (b[None, :, :] != 0)
    return np.bitwise_xor.reduce(
        np.where(nz, prod, 0), axis=1
    ).astype(np.uint8)


@jax.jit
def gf_matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """The tensor twin of :func:`gf_matmul_np`: log gathers + add + exp
    gather + zero mask + XOR reduction of the shared axis — a batched
    GF "matmul" under jit."""
    a = a.astype(jnp.int32)
    b = b.astype(jnp.int32)
    exp_t = jnp.asarray(EXP)
    log_t = jnp.asarray(LOG)
    prod = exp_t[log_t[a][:, :, None] + log_t[b][None, :, :]]
    nz = (a[:, :, None] != 0) & (b[None, :, :] != 0)
    out = jax.lax.reduce(
        jnp.where(nz, prod, 0), jnp.int32(0), jax.lax.bitwise_xor, (1,)
    )
    return out.astype(jnp.uint8)


# ---------------------------------------------------------------------------
# The systematic generator and its survivor inverses
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def parity_matrix(k: int, m: int) -> np.ndarray:
    """uint8 [k, m] Cauchy parity block P; G = [I | P]."""
    if k < 1 or m < 1 or k + m > 256:
        raise ValueError(f"stripe shape ({k}, {m}) not representable in GF(256)")
    p = np.zeros((k, m), dtype=np.uint8)
    for i in range(k):
        for j in range(m):
            p[i, j] = gf_inv(i ^ (k + j))
    return p


@functools.lru_cache(maxsize=None)
def generator_rows(k: int, m: int) -> np.ndarray:
    """uint8 [k+m, k]: row s maps the k data rows to fragment slot s
    (identity rows for s < k, P columns for the parity slots)."""
    return np.concatenate(
        [np.eye(k, dtype=np.uint8), parity_matrix(k, m).T], axis=0
    )


def gf_matinv(a: np.ndarray) -> np.ndarray:
    """GF(256) Gauss-Jordan inverse of a small k x k matrix (host)."""
    k = a.shape[0]
    aug = np.concatenate(
        [np.array(a, dtype=np.uint8), np.eye(k, dtype=np.uint8)], axis=1
    )

    def scale(row: np.ndarray, s: int) -> np.ndarray:
        out = EXP[LOG[row.astype(np.int32)] + LOG[s]]
        return np.where(row != 0, out, 0).astype(np.uint8)

    for col in range(k):
        nz = np.nonzero(aug[col:, col])[0]
        if len(nz) == 0:
            raise np.linalg.LinAlgError("singular matrix over GF(256)")
        piv = col + int(nz[0])
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        aug[col] = scale(aug[col], gf_inv(int(aug[col, col])))
        for r in range(k):
            if r != col and aug[r, col]:
                aug[r] ^= scale(aug[col], int(aug[r, col]))
    return aug[:, k:]


@functools.lru_cache(maxsize=None)
def decode_matrix(k: int, m: int, slots: tuple[int, ...]) -> np.ndarray:
    """uint8 [k, k]: left-inverse of G restricted to the k surviving
    fragment ``slots`` — ``data = decode_matrix @ fragments[slots]``.
    Cached per erasure pattern (there are only C(k+m, k) of them)."""
    if len(slots) != k:
        raise ValueError(f"need exactly k={k} slots, got {len(slots)}")
    return gf_matinv(generator_rows(k, m)[list(slots)])


# ---------------------------------------------------------------------------
# Encode / decode — fragment matrices
# ---------------------------------------------------------------------------


def encode_np(data: np.ndarray, m: int) -> np.ndarray:
    """uint8 [k, L] data rows -> [k+m, L] fragment rows (systematic)."""
    k = data.shape[0]
    parity = gf_matmul_np(parity_matrix(k, m).T, data)
    return np.concatenate([np.asarray(data, dtype=np.uint8), parity], axis=0)


def encode(data: jax.Array, m: int) -> jax.Array:
    """Tensor twin of :func:`encode_np` (jit via :func:`gf_matmul`)."""
    k = data.shape[0]
    parity = gf_matmul(jnp.asarray(parity_matrix(k, m).T), data)
    return jnp.concatenate([data.astype(jnp.uint8), parity], axis=0)


def decode_np(fragments: np.ndarray, slots: tuple[int, ...], k: int,
              m: int) -> np.ndarray:
    """[k, L] surviving fragment rows (slot order ``slots``) -> data rows."""
    return gf_matmul_np(decode_matrix(k, m, tuple(slots)), fragments)


def decode(fragments: jax.Array, slots: tuple[int, ...], k: int,
           m: int) -> jax.Array:
    """Tensor twin of :func:`decode_np`: the survivor inverse is a tiny
    host matrix; applying it stays a batched device matmul."""
    return gf_matmul(jnp.asarray(decode_matrix(k, m, tuple(slots))),
                     fragments)


# ---------------------------------------------------------------------------
# Blob helpers — the CoSim byte path
# ---------------------------------------------------------------------------


def split_blob(data: bytes, k: int) -> np.ndarray:
    """bytes -> uint8 [k, ceil(len/k)] data rows, zero padded."""
    length = len(data)
    frag_len = -(-length // k) if length else 0
    arr = np.zeros((k, frag_len), dtype=np.uint8)
    flat = np.frombuffer(data, dtype=np.uint8)
    arr.reshape(-1)[:length] = flat
    return arr


def encode_blob(data: bytes, k: int, m: int) -> list[bytes]:
    """bytes -> k+m fragment byte strings of ceil(len/k) bytes each."""
    rows = encode_np(split_blob(data, k), m)
    return [rows[s].tobytes() for s in range(k + m)]


def decode_blob(fragments: dict[int, bytes], k: int, m: int,
                length: int) -> bytes:
    """Any >= k fragments (slot -> bytes) -> the original payload."""
    slots = tuple(sorted(fragments))[:k]
    if len(slots) < k:
        raise ValueError(
            f"need >= {k} fragments to decode, got {len(fragments)}"
        )
    frag_len = -(-length // k) if length else 0
    rows = np.stack([
        np.frombuffer(fragments[s], dtype=np.uint8) for s in slots
    ]) if frag_len else np.zeros((k, 0), dtype=np.uint8)
    if all(s < k for s in slots):
        data = rows          # all-systematic survivors: no field math at all
    else:
        data = decode_np(rows, slots, k, m)
    return data.reshape(-1)[:length].tobytes()


# Fragment storage framing: each stored fragment is self-describing —
# a 4-byte big-endian payload length ahead of the row bytes — so a
# rebuilt master (election after the old one died) can recover a
# stripe's exact payload length from ANY surviving fragment.  The
# header is framing, not payload: repair-byte accounting counts row
# bytes only (BASELINE.md documents the convention).
_FRAME = 4


def frag_key(name: str, slot: int) -> str:
    """The LocalStore key a stripe fragment lives under."""
    return f"{name}#s{slot}"


def parse_frag_key(key: str) -> tuple[str, int] | None:
    """Inverse of :func:`frag_key`; None for non-fragment keys."""
    base, sep, tail = key.rpartition("#s")
    if not sep or not tail.isdigit():
        return None
    return base, int(tail)


def pack_fragment(row: bytes, length: int) -> bytes:
    return length.to_bytes(_FRAME, "big") + row


def unpack_fragment(blob: bytes) -> tuple[int, bytes]:
    """-> (payload length, row bytes)."""
    return int.from_bytes(blob[:_FRAME], "big"), blob[_FRAME:]


def repair_fragments(fragments: dict[int, bytes], lost_slots: list[int],
                     k: int, m: int, length: int) -> dict[int, bytes]:
    """Rebuild ``lost_slots`` from any k surviving fragments: decode the
    data rows, re-encode, and return just the requested slots — the
    fetch-k-re-encode step ``SDFSCluster.fail_recover`` executes."""
    payload = decode_blob(fragments, k, m, length)
    rows = encode_np(split_blob(payload, k), m)
    return {s: rows[s].tobytes() for s in lost_slots}
