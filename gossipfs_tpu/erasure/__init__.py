"""Tensorized erasure-coded SDFS plane: a (k, m) systematic Reed-Solomon
codec over GF(256) (``codec``) and the stripe-aware placement/repair
planner (``planner``) — the ``redundancy="stripe"`` mode behind
``sdfs/cluster.py`` and the traffic plane.

Threshold math (k-of-(k+m) reads, (k+m-f)-of-(k+m) writes) is owned by
``sdfs/quorum.py``; this package imports it, never re-derives it.
"""

from gossipfs_tpu.erasure import codec, planner

__all__ = ["codec", "planner"]
