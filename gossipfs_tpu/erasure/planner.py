"""Stripe-aware placement + repair planning over the live [N] masks.

Extends the round-12 tensor planner (``traffic/planner.py``) to the
erasure plane:

  * **placement** — ``place_stripes`` draws k+m distinct fragment
    holders per stripe with RACK-disjointness against a group vector:
    the same rejection-free sampled machinery as ``place_batch``'s
    sampled method, with the first-k-distinct dedup keyed on
    ``racks[node]`` instead of the node id (distinct racks imply
    distinct nodes).  A correlated rack kill then costs a stripe at
    most ONE fragment — the whole point of paying m parities.
  * **repair planning** — ``plan_stripe_repairs_tensor`` is the same
    one-shot masked-top-k diff with per-stripe fragment-deficit
    budgeting: score = (k+m) - live_fragments, masked to repairable
    stripes, so the budget drains MOST-ENDANGERED-FIRST (a stripe at
    k live fragments is one loss from data death; lost >= m fragments
    IS data loss).  Lost stripes (live < k) are unreconstructable and
    reported, never planned.

Threshold math is IMPORTED from ``sdfs/quorum.py``
(``stripe_read_quorum`` / ``stripe_write_quorum``) — never re-derived
here; gossipfs-lint's stripe-quorum-ownership rule enforces it.
"""

from __future__ import annotations

import functools
import random
from typing import NamedTuple

import jax
import jax.numpy as jnp

from gossipfs_tpu.sdfs.placement import (
    OVERSAMPLE_FACTOR,
    first_k_distinct,
    sample_members,
)
from gossipfs_tpu.sdfs.quorum import stripe_read_quorum, stripe_write_quorum
from gossipfs_tpu.sdfs.types import STRIPE_K, STRIPE_M, STRIPE_WRITE_SLACK


class StripePlan(NamedTuple):
    """One budgeted stripe-repair planning pass (device arrays).

    ``idx``/``valid`` — the up-to-``budget`` chosen stripe rows;
    ``need`` — fragments to rebuild per chosen stripe; ``picks`` —
    [budget, k+m] slot-aligned fresh holders (-1 where the slot is
    healthy); ``degraded`` — repairable stripes below full strength
    BEFORE the budget cut; ``lost`` — [F] stripes with fewer than k
    live fragments (data loss this pass).
    """

    idx: jax.Array
    valid: jax.Array
    need: jax.Array
    picks: jax.Array
    degraded: jax.Array
    lost: jax.Array


def first_k_group_distinct(nodes: jnp.ndarray, groups: jnp.ndarray,
                           k: int) -> jnp.ndarray:
    """[rows, m] draws -> [rows, k] first k draws with DISTINCT group
    ids, -1 padded — ``placement.first_k_distinct`` with the dup mask
    keyed on ``groups[node]``; the kept values are still the nodes."""
    rows, m = nodes.shape
    g = jnp.where(nodes >= 0, groups[jnp.clip(nodes, 0)], -1)
    dup = (g[:, :, None] == g[:, None, :]) & (
        jnp.arange(m)[None, :] < jnp.arange(m)[:, None]
    )[None]
    is_new = ~dup.any(axis=2) & (nodes >= 0)
    rank = jnp.cumsum(is_new, axis=1) - 1
    take = is_new & (rank < k)
    out = jnp.full((rows, k), -1, dtype=jnp.int32)
    row_idx = jnp.broadcast_to(jnp.arange(rows)[:, None], (rows, m))
    return out.at[row_idx, jnp.where(take, rank, k)].set(
        jnp.where(take, nodes.astype(jnp.int32), -1), mode="drop"
    )


def place_stripes(
    key: jax.Array,
    alive: jax.Array,
    racks: jax.Array,
    n_stripes: int,
    k: int = STRIPE_K,
    m: int = STRIPE_M,
) -> jax.Array:
    """int32 [n_stripes, k+m] — fragment holders drawn uniformly over
    live nodes, one per DISTINCT rack (``racks`` is the [N] group
    vector).  Slots beyond the sampled distinct-rack count are -1 (the
    caller's unplaced-slot retry rule, as in ``place_batch``)."""
    draws = sample_members(key, alive, n_stripes,
                           OVERSAMPLE_FACTOR * (k + m))
    return first_k_group_distinct(draws, racks, k + m)


def place_stripe(members: list[int], racks: dict[int, int] | list[int],
                 rng: random.Random, k: int = STRIPE_K,
                 m: int = STRIPE_M) -> list[int]:
    """Host twin of :func:`place_stripes` for the control-plane path
    (``sdfs/master.py``): k+m distinct holders, rack-BALANCED — each
    pass takes at most one node per rack, so with R racks no rack ever
    holds more than ceil((k+m)/R) fragments.  With R >= k+m that is
    full rack-disjointness; smaller clusters degrade gracefully (a
    correlated rack kill then costs at most ceil((k+m)/R) fragments,
    which stays <= m down to R = 4 at the default (4, 2) shape)."""
    pool = list(members)
    rng.shuffle(pool)
    chosen: list[int] = []
    while pool and len(chosen) < k + m:
        seen_racks: set[int] = set()
        next_pool: list[int] = []
        for node in pool:
            if len(chosen) < k + m and racks[node] not in seen_racks:
                seen_racks.add(racks[node])
                chosen.append(node)
            else:
                next_pool.append(node)
        pool = next_pool
    return chosen


def pick_repair_targets(candidates: list[int],
                        racks: dict[int, int] | list[int],
                        rack_load: dict[int, int], need: int,
                        rng: random.Random) -> list[int]:
    """Host-side repair placement: up to ``need`` distinct nodes from
    ``candidates``, always picking the least-loaded rack first
    (``rack_load`` counts the stripe's surviving fragments per rack) —
    so repair restores :func:`place_stripe`'s ceil((k+m)/R) per-rack
    bound instead of eroding it."""
    pool = list(candidates)
    rng.shuffle(pool)
    load = dict(rack_load)
    picks: list[int] = []
    cap = 0
    while pool and len(picks) < need:
        cap += 1  # this pass admits racks holding < cap fragments
        next_pool: list[int] = []
        for node in pool:
            if len(picks) < need and load.get(racks[node], 0) < cap:
                load[racks[node]] = load.get(racks[node], 0) + 1
                picks.append(node)
            else:
                next_pool.append(node)
        pool = next_pool
    return picks


def _live_slots(holders: jax.Array, mask: jax.Array) -> jax.Array:
    """[F, k+m] — fragment slot holds a node currently in ``mask``."""
    return (holders >= 0) & mask[jnp.clip(holders, 0)]


@functools.partial(jax.jit, static_argnames=("budget", "k", "m"))
def plan_stripe_repairs_tensor(
    key: jax.Array,
    holders: jax.Array,
    n_stripes: jax.Array,
    alive: jax.Array,
    reach: jax.Array,
    budget: int,
    k: int = STRIPE_K,
    m: int = STRIPE_M,
) -> StripePlan:
    """The masked-top-k stripe-repair planner: degraded = fewer than
    min(k+m, n_alive) live fragments but still >= k REACHABLE ones (the
    re-encode needs k sources); the ``budget`` largest-deficit stripes
    get slot-aligned fresh holders drawn uniformly from reachable
    non-holder nodes.  Deterministic under ``key``."""
    width = k + m
    cap = holders.shape[0]
    used = jnp.arange(cap) < n_stripes
    live = _live_slots(holders, alive) & used[:, None]
    w = live.sum(axis=1)
    target = jnp.minimum(width, alive.sum())
    sources = (_live_slots(holders, reach) & used[:, None]).sum(axis=1)
    placed = used & (holders >= 0).any(axis=1)
    lost = placed & (w < stripe_read_quorum(k, m))
    degraded = placed & ~lost & (w < target) & (
        sources >= stripe_read_quorum(k, m)
    )

    score = jnp.where(degraded, (width - w).astype(jnp.int32), 0)
    top, idx = jax.lax.top_k(score, min(budget, cap))
    valid = top > 0

    hole = valid[:, None] & ~_live_slots(holders[idx], alive)
    need = hole.sum(axis=1)

    draws = sample_members(key, reach, idx.shape[0],
                           OVERSAMPLE_FACTOR * width)
    forb = holders[idx]
    banned = (
        (draws[:, :, None] == forb[:, None, :]) & (forb >= 0)[:, None, :]
    ).any(axis=2)
    picks_flat = first_k_distinct(jnp.where(banned, -1, draws), width)
    # scatter the flat picks into the holed slots, in slot order
    rank = jnp.cumsum(hole, axis=1) - 1
    picks = jnp.where(
        hole,
        jnp.take_along_axis(picks_flat, jnp.clip(rank, 0, width - 1), 1),
        -1,
    )
    return StripePlan(idx=idx, valid=valid, need=need, picks=picks,
                      degraded=degraded.sum(), lost=lost)


@jax.jit
def commit_stripe_repairs(
    holders: jax.Array,
    idx: jax.Array,
    valid: jax.Array,
    picks: jax.Array,
) -> jax.Array:
    """Apply a :class:`StripePlan` in-array: landed picks fill their
    slots, healthy slots keep their holders (slot-aligned, so no
    compaction — the codec's row order IS the slot order)."""
    rows = holders[idx]
    newrow = jnp.where(valid[:, None] & (picks >= 0), picks, rows)
    return holders.at[idx].set(newrow)


@functools.partial(jax.jit, static_argnames=("k", "m", "slack"))
def stripe_stats(
    holders: jax.Array,
    n_stripes: jax.Array,
    alive: jax.Array,
    reach: jax.Array,
    k: int = STRIPE_K,
    m: int = STRIPE_M,
    slack: int = STRIPE_WRITE_SLACK,
) -> jax.Array:
    """[k+m+4] summary: histogram of live-fragment counts (0..k+m; the
    sub-k bins are data loss) + stripes meeting the write and read
    quorums over REACHABLE fragments (``sdfs/quorum.py`` owns both) +
    the degraded count."""
    width = k + m
    cap = holders.shape[0]
    used = jnp.arange(cap) < n_stripes
    placed = used & (holders >= 0).any(axis=1)
    w = (_live_slots(holders, alive) & placed[:, None]).sum(axis=1)
    hist = jnp.zeros((width + 1,), dtype=jnp.int32).at[
        jnp.where(placed, w, width + 1)
    ].add(placed.astype(jnp.int32), mode="drop")
    r = (_live_slots(holders, reach) & placed[:, None]).sum(axis=1)
    w_ok = (placed & (r >= stripe_write_quorum(k, m, slack))).sum()
    r_ok = (placed & (r >= stripe_read_quorum(k, m))).sum()
    degraded = (placed & (w >= stripe_read_quorum(k, m))
                & (w < width)).sum()
    return jnp.concatenate([hist, w_ok[None], r_ok[None], degraded[None]])
