"""Interactive REPL — the reference CLI, backed by the TPU sim.

Command surface matches README.md:8-29 plus fault/time controls the sim adds:

  join <n> / leave <n> / crash <n>   membership verbs (+ CTRL+C equivalent)
  lsm <n>                            print node n's membership list
  IP                                 print node ids (the sim's "addresses")
  put <local> <sdfs>                 write a file into SDFS (quorum write)
  get <sdfs> <local>                 read it back (quorum read + repair)
  delete <sdfs> / ls <sdfs> / store <n>
  show_metadata | check              master's file->replica map
  advance <r>                        advance simulated time by r rounds
  events                             detection events so far
  metrics                            the uniform vitals counter line
                                     (obs/schema.py VITALS_FIELDS — the
                                     same set the deploy Vitals RPC
                                     serves; unknowable fields as n/a)
  scenario load <file.json>          arm a declarative fault scenario
                                     (gossipfs_tpu/scenarios/ schema:
                                     partitions, link loss, slow nodes;
                                     needs --gossip-only — the broadcast
                                     modes aren't transport-filterable)
  scenario status | clear            armed-scenario state / disarm
  suspicion status                   SWIM suspect/refute vitals (per-node
                                     suspect counts, refutations, confirms
                                     — needs --t-suspect); lsm marks a
                                     SUSPECT entry with a trailing ?
  traffic status                     SDFS traffic-plane vitals (ops
                                     issued/acked, repairs pending/done —
                                     the obs/schema.py VITALS_FIELDS
                                     tail; engines without a data plane
                                     render every field n/a, never 0.
                                     invariant_violations appears when a
                                     streaming monitor rides the attached
                                     recorder — obs/monitor.py — and
                                     renders n/a otherwise, same rule)
  grep [--node <k>] <regex>          search the event log (MP1 legacy verb);
                                     --node scopes to one machine's log view

Run: ``python -m gossipfs_tpu.shim.cli [--n 16] [--topology ring]``
"""

from __future__ import annotations

import argparse
import pathlib
import re
import select
import sys

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.obs import schema
from gossipfs_tpu.sdfs.types import CONFIRM_TIMEOUT, STRIPE_K, STRIPE_M


def stdin_confirm(
    name: str,
    timeout: float = float(CONFIRM_TIMEOUT),
    stream=None,
    out=sys.stdout,
) -> bool:
    """Interactive write-conflict prompt (reference: server.go:144-153).

    The reference's master, on a put within the 60 s conflict window, asks
    the requester's human a yes/no question on stdin with a 30 s timeout
    defaulting to reject (server.go:172).  Reads one line from ``stream``
    (the REPL's own input) under ``select`` so a silent terminal rejects
    after the timeout instead of hanging the session.
    """
    stream = stream if stream is not None else sys.stdin
    print(
        f"{name} was updated in the last 60 rounds. Overwrite? "
        f"[y/N, {int(timeout)} s timeout rejects]",
        file=out,
        flush=True,
    )
    try:
        ready, _, _ = select.select([stream], [], [], timeout)
    except (ValueError, OSError, TypeError):
        # stream without a selectable fd (in-memory test streams): read
        # directly — the caller controls pacing there
        ready = [stream]
    if not ready:
        print("confirmation timed out: rejecting write", file=out)
        return False
    line = stream.readline()
    if isinstance(line, bytes):
        line = line.decode(errors="replace")
    return line.strip().lower() in ("y", "yes")


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="gossipfs", description=__doc__)
    p.add_argument("--n", type=int, default=16)
    p.add_argument("--topology", choices=["ring", "random"], default="ring")
    p.add_argument("--fanout", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--confirm-timeout", type=float, default=float(CONFIRM_TIMEOUT),
        help="seconds to wait for the write-conflict yes/no before "
             "rejecting (reference: server.go:172)",
    )
    p.add_argument(
        "--gossip-only", action="store_true",
        help="gossip-only dissemination (remove_broadcast off, fresh "
             "cooldown) — the north-star mode, and required before "
             "'scenario load' (the instantaneous REMOVE broadcast cannot "
             "be partition-filtered; scenarios/tensor.py)",
    )
    p.add_argument(
        "--t-suspect", type=int, default=0,
        help="arm the SWIM suspicion lifecycle (suspicion/): silent "
             "members pass through a refutable SUSPECT state for this "
             "many rounds before FAILED.  0 = off; needs --gossip-only "
             "(the REMOVE broadcast would bypass the suspect window; "
             "--packed is gossip-only already and runs the lifecycle "
             "in-kernel since round 11)",
    )
    p.add_argument(
        "--redundancy", choices=["replica", "stripe"], default="replica",
        help="SDFS redundancy mode: 'replica' = the reference's 4-copy "
             "scheme; 'stripe' = (k,m) GF(256) erasure coding "
             "(gossipfs_tpu/erasure/) — rack-disjoint fragments, "
             "k-of-(k+m) reads, budgeted most-endangered-first repair",
    )
    p.add_argument(
        "--stripe-k", type=int, default=STRIPE_K,
        help="data fragments per stripe (with --redundancy stripe)")
    p.add_argument(
        "--stripe-m", type=int, default=STRIPE_M,
        help="parity fragments per stripe (with --redundancy stripe)")
    p.add_argument(
        "--rack-size", type=int, default=None,
        help="nodes per failure domain for stripe placement "
             "(default: every node its own rack)")
    p.add_argument(
        "--arc-align", type=int, default=1,
        help="with --packed: tile-aligned windowed-arc gossip (bases are "
             "multiples of this; fanout rounds up to a multiple) — the "
             "headline kernel's fastest topology at the capacity frontier")
    p.add_argument(
        "--packed", action="store_true",
        help="capacity-frontier interactive mode: the membership state "
             "lives as the resident-round kernel's packed lanes "
             "(detector.sim.PackedDetector) — what fits N=49,152+ "
             "interactively on one chip.  Implies a random log2(N)-fanout "
             "crash-only protocol profile; 'join' is unsupported",
    )
    return p


def dispatch(
    sim: CoSim,
    line: str,
    out=sys.stdout,
    in_stream=None,
    confirm_timeout: float = float(CONFIRM_TIMEOUT),
) -> bool:
    """Execute one REPL command; returns False on quit.

    ``in_stream`` is where the write-conflict confirmation prompt reads its
    yes/no answer (the REPL's own stdin) — see :func:`stdin_confirm`.
    """
    parts = line.strip().split()
    if not parts:
        return True
    cmd, args = parts[0], parts[1:]
    try:
        if cmd in ("quit", "exit"):
            return False
        elif cmd == "join":
            sim.detector.join(int(args[0]))
        elif cmd == "leave":
            sim.detector.leave(int(args[0]))
        elif cmd == "crash":
            sim.detector.crash(int(args[0]))
        elif cmd == "lsm":
            obs = int(args[0])
            members = sim.detector.membership(obs)
            suspects: set[int] = set()
            if getattr(sim.config, "suspicion", None) is not None and \
                    hasattr(sim.detector, "suspects"):
                suspects = set(sim.detector.suspects(obs))
            if suspects:
                # SUSPECT entries render distinctly: still members, but
                # pending refute/confirm (suspicion/)
                print("[" + ", ".join(
                    f"{j}?" if j in suspects else str(j) for j in members
                ) + "]", file=out)
            else:
                print(members, file=out)
        elif cmd == "IP":
            print(sim.detector.alive_nodes(), file=out)
        elif cmd == "advance":
            sim.tick(int(args[0]) if args else 1)
            print(f"round={sim.round}", file=out)
        elif cmd == "put":
            data = pathlib.Path(args[0]).read_bytes()
            name = args[1]
            ok = sim.put(
                name,
                data,
                confirm=lambda: stdin_confirm(
                    name, timeout=confirm_timeout, stream=in_stream, out=out
                ),
            )
            print("ok" if ok else "Write-Write conflicts!", file=out)
        elif cmd == "get":
            blob = sim.get(args[0])
            if blob is None:
                print("No File Found", file=out)
            else:
                pathlib.Path(args[1]).write_bytes(blob)
                print(f"wrote {len(blob)} bytes", file=out)
        elif cmd == "delete":
            print("ok" if sim.delete(args[0]) else "No File Found", file=out)
        elif cmd == "ls":
            print(sim.cluster.ls(args[0]), file=out)
        elif cmd == "store":
            print(sim.cluster.store_listing(int(args[0])), file=out)
        elif cmd in ("show_metadata", "check"):  # "check" = reference alias
                                                 # (CheckInput, slave.go:608-610)
            for name, info in sim.cluster.master.files.items():
                print(f"{name}: v{info.version} @ {info.node_list}", file=out)
        elif cmd == "events":
            for ev in sim.events:
                print(ev, file=out)
        elif cmd == "metrics":
            # the uniform vitals line (obs.schema.VITALS_FIELDS): the
            # SAME counter set the deploy `Vitals` RPC renders per node;
            # fields this engine cannot know print as n/a, never 0
            from gossipfs_tpu.obs.schema import render_vitals

            print(render_vitals(sim.vitals()), file=out)
        elif cmd == "scenario":
            sub = args[0] if args else "status"
            if sub == "load":
                from gossipfs_tpu.scenarios import FaultScenario

                sim.load_scenario(FaultScenario.from_file(args[1]))
                st = sim.scenario_status()
                print(f"armed '{st['name']}' (horizon {st['horizon']} "
                      "rounds from now)", file=out)
            elif sub == "status":
                st = sim.scenario_status()
                if st is None:
                    print("no scenario armed", file=out)
                else:
                    print(f"{st['name']}: round {st['round']}, "
                          f"{'ACTIVE' if st['active'] else 'inactive'}; "
                          f"rules: {st['rules'] or 'none'}", file=out)
            elif sub == "clear":
                sim.clear_scenario()
                print("scenario cleared", file=out)
            else:
                print(f"unknown scenario verb: {sub} "
                      "(load <file.json> | status | clear)", file=out)
        elif cmd == "suspicion":
            sub = args[0] if args else "status"
            if sub == "status":
                st = sim.suspicion_status()
                if st is None:
                    print("no suspicion armed (start with --t-suspect N)",
                          file=out)
                else:
                    counts = st.get("suspect_counts") or {}
                    per = ", ".join(f"{i}:{c}" for i, c in sorted(counts.items()))
                    # fp_suppressed needs ground-truth aliveness: the
                    # socket engines omit it — render the unknowable as
                    # n/a, never as a measured zero
                    fps = st.get("fp_suppressed")
                    print(f"suspicion t_suspect={st['t_suspect']}: "
                          f"{st.get('suspects_now', 0)} suspect entries now"
                          f"{' (' + per + ')' if per else ''}; "
                          f"refutations={st.get('refutations', 0)} "
                          f"confirms={st.get('confirms', 0)} "
                          f"fp_suppressed={schema.na(fps)}",
                          file=out)
            else:
                print(f"unknown suspicion verb: {sub} (status)", file=out)
        elif cmd == "traffic":
            sub = args[0] if args else "status"
            if sub == "status":
                # the traffic-plane tail of obs.schema.VITALS_FIELDS; an
                # engine without an SDFS data plane omits the fields and
                # each renders n/a, never a measured 0 (the round-8 rule)
                st = (sim.traffic_status()
                      if hasattr(sim, "traffic_status") else {})
                fmt = lambda k: schema.na(st.get(k))  # noqa: E731
                # invariant_violations: present only when a streaming
                # monitor (obs/monitor.py) rides the attached recorder —
                # engines that can't know it render n/a, never 0
                # stripes_degraded/fragments_lost: stripe-mode-only
                # erasure vitals — replica-mode documents omit them, so
                # they render n/a here (a stripe run's clean 0 is a real
                # measurement)
                print(f"ops issued={fmt('ops_issued')} "
                      f"acked={fmt('ops_acked')}; "
                      f"repairs pending={fmt('repairs_pending')} "
                      f"done={fmt('repairs_done')}; "
                      f"stripes degraded={fmt('stripes_degraded')} "
                      f"fragments lost={fmt('fragments_lost')}; "
                      f"invariant_violations={fmt('invariant_violations')}",
                      file=out)
            else:
                print(f"unknown traffic verb: {sub} (status)", file=out)
        elif cmd == "grep":
            # ``grep [--node <k>] [--] <pattern>``: the explicit flag
            # scopes the search to node k's own log view (distributed-grep
            # analog); without it the pattern is searched verbatim, digits
            # included.  ``--`` ends flag parsing, and a ``--node`` whose
            # operand is not an int falls back to pattern text, so a
            # pattern literally starting with "--node" stays greppable
            # (ADVICE r3)
            node = None
            if len(args) >= 2 and args[0] == "--node":
                try:
                    node, args = int(args[1]), args[2:]
                except ValueError:
                    pass
            if args and args[0] == "--":
                args = args[1:]
            for entry in sim.log.grep(" ".join(args), node=node):
                print(entry, file=out)
        else:
            print(f"unknown command: {cmd}", file=out)
    except (IndexError, ValueError, FileNotFoundError, re.error,
            NotImplementedError) as e:
        # NotImplementedError: any future mode-gated verb must print an
        # error, not kill a session holding GBs of state ('join' was such
        # a verb until round 5 gave the packed frontier a join path)
        print(f"error: {e}", file=out)
    return True


def main(argv=None) -> None:
    parser = make_parser()
    args = parser.parse_args(argv)
    try:
        if args.packed:
            if args.arc_align > 1:
                lf = SimConfig.log_fanout(args.n)
                cfg = SimConfig.packed_rr(
                    args.n, topology="random_arc",
                    arc_align=args.arc_align,
                    fanout=-(-lf // args.arc_align) * args.arc_align,
                )
            else:
                cfg = SimConfig.packed_rr(args.n)
        else:
            extra = {}
            if args.gossip_only:
                extra = dict(remove_broadcast=False, fresh_cooldown=True)
            cfg = SimConfig(n=args.n, topology=args.topology,
                            fanout=args.fanout, **extra)
        if args.t_suspect > 0:
            # Round 11: the SWIM lifecycle runs natively on every merge
            # path (--packed's rr kernel included), so arming it is a
            # plain field set — __post_init__ owns the protocol-mode
            # check (gossip-only; suspicion/tensor.py).
            import dataclasses

            from gossipfs_tpu.suspicion import SuspicionParams

            cfg = dataclasses.replace(
                cfg, suspicion=SuspicionParams(t_suspect=args.t_suspect))
    except ValueError as e:
        parser.error(str(e))
    detector = None
    if args.packed:
        from gossipfs_tpu.detector.sim import PackedDetector

        detector = PackedDetector(cfg, seed=args.seed)
    sim = CoSim(cfg, seed=args.seed, detector=detector,
                redundancy=args.redundancy, stripe_k=args.stripe_k,
                stripe_m=args.stripe_m, rack_size=args.rack_size)
    mode = (f", stripe({args.stripe_k},{args.stripe_m})"
            if args.redundancy == "stripe" else "")
    print(f"gossipfs sim: {args.n} nodes, {cfg.topology} topology{mode}"
          f"{' (packed frontier mode)' if args.packed else ''}. "
          "'quit' to exit.")
    # Read stdin UNBUFFERED (byte-at-a-time lines): any buffered layer
    # (the ``for line in sys.stdin`` iterator's read-ahead, or even
    # TextIOWrapper.readline's internal chunking) would slurp pending
    # lines into user space, where the confirmation prompt's select() on
    # the raw fd cannot see them — a piped-in 'y' answer would look like
    # silence and falsely time out.
    stdin = open(sys.stdin.fileno(), "rb", buffering=0, closefd=False)
    for raw in iter(stdin.readline, b""):
        if not dispatch(sim, raw.decode(errors="replace"), in_stream=stdin,
                        confirm_timeout=args.confirm_timeout):
            break


if __name__ == "__main__":
    main()
