"""Interactive REPL — the reference CLI, backed by the TPU sim.

Command surface matches README.md:8-29 plus fault/time controls the sim adds:

  join <n> / leave <n> / crash <n>   membership verbs (+ CTRL+C equivalent)
  lsm <n>                            print node n's membership list
  IP                                 print node ids (the sim's "addresses")
  put <local> <sdfs>                 write a file into SDFS (quorum write)
  get <sdfs> <local>                 read it back (quorum read + repair)
  delete <sdfs> / ls <sdfs> / store <n>
  show_metadata | check              master's file->replica map
  advance <r>                        advance simulated time by r rounds
  events                             detection events so far
  grep <regex>                       search the event log (MP1 legacy verb)

Run: ``python -m gossipfs_tpu.shim.cli [--n 16] [--topology ring]``
"""

from __future__ import annotations

import argparse
import pathlib
import re
import sys

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="gossipfs", description=__doc__)
    p.add_argument("--n", type=int, default=16)
    p.add_argument("--topology", choices=["ring", "random"], default="ring")
    p.add_argument("--fanout", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    return p


def dispatch(sim: CoSim, line: str, out=sys.stdout) -> bool:
    """Execute one REPL command; returns False on quit."""
    parts = line.strip().split()
    if not parts:
        return True
    cmd, args = parts[0], parts[1:]
    try:
        if cmd in ("quit", "exit"):
            return False
        elif cmd == "join":
            sim.detector.join(int(args[0]))
        elif cmd == "leave":
            sim.detector.leave(int(args[0]))
        elif cmd == "crash":
            sim.detector.crash(int(args[0]))
        elif cmd == "lsm":
            print(sim.detector.membership(int(args[0])), file=out)
        elif cmd == "IP":
            print(sim.detector.alive_nodes(), file=out)
        elif cmd == "advance":
            sim.tick(int(args[0]) if args else 1)
            print(f"round={sim.round}", file=out)
        elif cmd == "put":
            data = pathlib.Path(args[0]).read_bytes()
            ok = sim.put(args[1], data)
            print("ok" if ok else "Write-Write conflicts!", file=out)
        elif cmd == "get":
            blob = sim.get(args[0])
            if blob is None:
                print("No File Found", file=out)
            else:
                pathlib.Path(args[1]).write_bytes(blob)
                print(f"wrote {len(blob)} bytes", file=out)
        elif cmd == "delete":
            print("ok" if sim.delete(args[0]) else "No File Found", file=out)
        elif cmd == "ls":
            print(sim.cluster.ls(args[0]), file=out)
        elif cmd == "store":
            print(sim.cluster.store_listing(int(args[0])), file=out)
        elif cmd in ("show_metadata", "check"):  # "check" = reference alias
                                                 # (CheckInput, slave.go:608-610)
            for name, info in sim.cluster.master.files.items():
                print(f"{name}: v{info.version} @ {info.node_list}", file=out)
        elif cmd == "events":
            for ev in sim.events:
                print(ev, file=out)
        elif cmd == "grep":
            for entry in sim.log.grep(" ".join(args)):
                print(entry, file=out)
        else:
            print(f"unknown command: {cmd}", file=out)
    except (IndexError, ValueError, FileNotFoundError, re.error) as e:
        print(f"error: {e}", file=out)
    return True


def main(argv=None) -> None:
    parser = make_parser()
    args = parser.parse_args(argv)
    try:
        cfg = SimConfig(n=args.n, topology=args.topology, fanout=args.fanout)
    except ValueError as e:
        parser.error(str(e))
    sim = CoSim(cfg, seed=args.seed)
    print(f"gossipfs sim: {args.n} nodes, {args.topology} topology. 'quit' to exit.")
    for line in sys.stdin:
        if not dispatch(sim, line):
            break


if __name__ == "__main__":
    main()
