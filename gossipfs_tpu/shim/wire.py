"""Shared shim wire definitions: service name + protobuf codec.

The wire speaks real protobuf over gRPC — ``gossipfs.proto`` is the
codegen-able contract (any language's gRPC toolchain produces a full
client from it; the reference's Go CLI included).  Python handlers keep
their plain-dict ergonomics: each method's serializer/deserializer
round-trips dict <-> protobuf message via ``google.protobuf.json_format``,
so the service/client code never touches message classes directly.

Dependency-light on purpose: the client (``shim/client.py``) must stay a
thin process that imports neither the server stack nor jax — only this
module, ``grpc`` and the generated message classes.
"""

from __future__ import annotations

from google.protobuf import json_format

from gossipfs_tpu.shim import gossipfs_pb2 as pb

SERVICE = "gossipfs.Shim"

# One message cap for both ends of the channel.  The reference's benchmark
# workload is multi-MB files (file1-10.txt, ~4 MB Wikipedia shards); raise
# gRPC's default 4 MB cap so a whole-file Put/Get (base64-inflated ~1.33x)
# fits in one message.  Client and server must agree or large transfers die
# with RESOURCE_EXHAUSTED on one side only.
MAX_MESSAGE_MB = 64

# method -> (request message class, response message class); the single
# source of truth tying the service surface to gossipfs.proto
METHOD_TYPES: dict[str, tuple] = {
    "Join": (pb.NodeRequest, pb.OkReply),
    "Leave": (pb.NodeRequest, pb.OkReply),
    "Crash": (pb.NodeRequest, pb.OkReply),
    "Lsm": (pb.LsmRequest, pb.LsmReply),
    "AliveNodes": (pb.Empty, pb.AliveNodesReply),
    "Advance": (pb.AdvanceRequest, pb.AdvanceReply),
    "AdvanceBulk": (pb.AdvanceBulkRequest, pb.AdvanceBulkReply),
    "Events": (pb.EventsRequest, pb.EventsReply),
    "Grep": (pb.GrepRequest, pb.GrepReply),
    "GetPutInfo": (pb.PutInfoRequest, pb.PutInfoReply),
    "GetFileData": (pb.NodeFileRequest, pb.FileDataReply),
    "GetFileInfo": (pb.FileRequest, pb.FileInfoReply),
    "AskForConfirmation": (pb.FileRequest, pb.ConfirmReply),
    "GetDeleteInfo": (pb.FileRequest, pb.DeleteInfoReply),
    "DeleteFileData": (pb.NodeFileRequest, pb.OkReply),
    "RemoteReput": (pb.ReputRequest, pb.OkReply),
    "PutFileData": (pb.PutFileDataRequest, pb.OkReply),
    "Vote": (pb.VoteRequest, pb.VoteReply),
    "AssignNewMaster": (pb.AssignRequest, pb.AssignReply),
    "UpdateFileVersion": (pb.UpdateVersionRequest, pb.OkReply),
    "GetUpdateMeta": (pb.UpdateMetaRequest, pb.UpdateMetaReply),
    "Put": (pb.PutRequest, pb.OkReply),
    "Get": (pb.FileRequest, pb.GetReply),
    "Delete": (pb.FileRequest, pb.OkReply),
    "Ls": (pb.FileRequest, pb.LsReply),
    "Store": (pb.NodeRequest, pb.StoreReply),
    "ShowMetadata": (pb.Empty, pb.MetadataReply),
    # scenario engine (deploy backend): extension verbs documented (not
    # declared) in gossipfs.proto — the rule table travels as
    # scenarios/schedule.py JSON in PutRequest.data_b64 (file = scenario
    # name; empty payload disarms); status rides GrepReply's Struct
    # lines.  Registered here only: gRPC dispatches by path string, so
    # reusing existing message shapes keeps the checked-in pb2 the
    # proto's exact codegen (no protoc needed; see the proto's
    # extension-verbs comment for the promotion path).
    "ScenarioLoad": (pb.PutRequest, pb.OkReply),
    "ScenarioStatus": (pb.Empty, pb.GrepReply),
    # suspicion subsystem (deploy backend): SuspicionParams JSON rides
    # PutRequest.data_b64 the same way a scenario rule table does (empty
    # payload disarms); per-node suspicion vitals ride ScenarioStatus's
    # Struct lines — no new reply shape needed
    "SuspicionLoad": (pb.PutRequest, pb.OkReply),
    # observability (obs/): the uniform vitals counter set
    # (obs.schema.VITALS_FIELDS) as GrepReply Struct lines — one line
    # from the embedded shim's CoSim, one line per node from the deploy
    # daemons; same extension-verb pattern as ScenarioStatus
    "Vitals": (pb.Empty, pb.GrepReply),
}


def message_size_options(max_message_mb: int = MAX_MESSAGE_MB):
    """grpc channel/server options raising the message size cap."""
    return [
        ("grpc.max_receive_message_length", max_message_mb * 1024 * 1024),
        ("grpc.max_send_message_length", max_message_mb * 1024 * 1024),
    ]


def _to_dict(msg) -> dict:
    # scalars without explicit presence always materialize (so handlers can
    # read req["file"] / reply["ok"] unconditionally); `optional` fields
    # keep presence semantics (e.g. as_of_round only from snapshot reads)
    return json_format.MessageToDict(
        msg,
        preserving_proto_field_name=True,
        always_print_fields_with_no_presence=True,
    )


def request_serializer(method: str):
    cls = METHOD_TYPES[method][0]
    return lambda obj: json_format.ParseDict(obj, cls()).SerializeToString()


def request_deserializer(method: str):
    cls = METHOD_TYPES[method][0]
    return lambda data: _to_dict(cls.FromString(data))


def response_serializer(method: str):
    cls = METHOD_TYPES[method][1]

    def ser(obj):
        try:
            return json_format.ParseDict(obj, cls()).SerializeToString()
        except Exception as e:
            # grpc's C core reports only "Failed to serialize response!"
            # and drops the Python cause; surface the method and shape of
            # the offending reply before re-raising
            import sys

            keys = list(obj) if isinstance(obj, dict) else type(obj)
            print(f"[wire] response serialize failed for {method}: "
                  f"{e!r}; reply keys={keys}", file=sys.stderr, flush=True)
            raise

    return ser


def response_deserializer(method: str):
    cls = METHOD_TYPES[method][1]
    return lambda data: _to_dict(cls.FromString(data))
