"""Shared shim wire definitions: service name + JSON codec.

Dependency-free on purpose: the client (``shim/client.py``) must stay a thin
process that imports neither the server stack nor jax — only this module and
``grpc``.  Messages are JSON dicts; the gRPC method path is
``/gossipfs.Shim/<Method>`` (see shim/service.py for the method map onto the
reference's net/rpc surface, server/server.go:19-251).
"""

from __future__ import annotations

import json

SERVICE = "gossipfs.Shim"

# One message cap for both ends of the channel.  The reference's benchmark
# workload is multi-MB files (file1-10.txt, ~4 MB Wikipedia shards); raise
# gRPC's default 4 MB cap so a whole-file Put/Get (base64-inflated ~1.33x)
# fits in one message.  Client and server must agree or large transfers die
# with RESOURCE_EXHAUSTED on one side only.
MAX_MESSAGE_MB = 64


def message_size_options(max_message_mb: int = MAX_MESSAGE_MB):
    """grpc channel/server options raising the message size cap."""
    return [
        ("grpc.max_receive_message_length", max_message_mb * 1024 * 1024),
        ("grpc.max_send_message_length", max_message_mb * 1024 * 1024),
    ]


def ser(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


def deser(data: bytes):
    return json.loads(data.decode("utf-8")) if data else {}
