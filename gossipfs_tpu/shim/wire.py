"""Shared shim wire definitions: service name + JSON codec.

Dependency-free on purpose: the client (``shim/client.py``) must stay a thin
process that imports neither the server stack nor jax — only this module and
``grpc``.  Messages are JSON dicts; the gRPC method path is
``/gossipfs.Shim/<Method>`` (see shim/service.py for the method map onto the
reference's net/rpc surface, server/server.go:19-251).
"""

from __future__ import annotations

import json

SERVICE = "gossipfs.Shim"


def ser(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


def deser(data: bytes):
    return json.loads(data.decode("utf-8")) if data else {}
