"""gRPC shim client — what the reference's ``rpc.Dial`` call sites become.

Every SDFS client op in the reference dials the master and calls a
string-named method (e.g. ``rpc.Dial("tcp", master:9000)`` then
``TCPServer.Get_put_info``, reference: slave/slave.go:669-678).  This client
is the same shape over gRPC: one channel, methods addressed by name under
``/gossipfs.Shim/``, protobuf messages per ``gossipfs.proto`` (dict in,
dict out — the json_format transcoding lives in wire.py).
"""

from __future__ import annotations

import base64

import grpc

from gossipfs_tpu.shim import retry, wire
from gossipfs_tpu.shim.wire import SERVICE


class ShimClient:
    """Thin dynamic proxy: ``client.call("GetFileInfo", file="x")``."""

    def __init__(
        self,
        address: str,
        timeout: float = 30.0,
        max_message_mb: int = wire.MAX_MESSAGE_MB,
    ):
        # same cap as the server (wire.py — multi-MB file payloads)
        self.channel = grpc.insecure_channel(
            address, options=wire.message_size_options(max_message_mb)
        )
        self.timeout = timeout
        self._methods: dict[str, grpc.UnaryUnaryMultiCallable] = {}

    def call(self, method: str, timeout: float | None = None,
             retries: bool = True, **request):
        """One RPC; ``timeout`` overrides the client default per call
        (bulk-data methods carry multi-MB payloads and need deadlines far
        past the control-plane default).  ``retries=False`` issues
        exactly one attempt — for callers that own their OWN retry
        policy (the launcher's ``_ctrl_call``), so two backoff loops
        never nest (a nested inner loop would multiply the outer
        policy's advertised time bound)."""
        fn = self._methods.get(method)
        if fn is None:
            fn = self._methods[method] = self.channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=wire.request_serializer(method),
                response_deserializer=wire.response_deserializer(method),
            )
        deadline = self.timeout if timeout is None else timeout
        if not retries:
            return fn(request, timeout=deadline)
        # RESOURCE_EXHAUSTED is the server's explicit backpressure (its
        # Advance handlers fail fast instead of holding workers parked on
        # the election lock — service.py ShimServicer._advance_slots):
        # retry with backoff rather than surfacing it to every caller.
        # Round 14: the raw loop became the shared bounded-backoff
        # discipline (shim/retry.py) — same 7-attempt 50ms-doubling
        # schedule, now with a hard ceiling on total retry time so a
        # saturated server cannot park callers open-endedly
        return retry.call_with_backoff(
            lambda: fn(request, timeout=deadline),
            retryable=retry.grpc_backpressure,
            attempts=7, base_delay=0.05, max_delay=1.0,
            total_deadline=10.0,
        )

    # -- convenience wrappers for the common verbs -------------------------
    def join(self, node: int) -> None:
        self.call("Join", node=node)

    def leave(self, node: int) -> None:
        self.call("Leave", node=node)

    def crash(self, node: int) -> None:
        self.call("Crash", node=node)

    def lsm(self, observer: int) -> list[int]:
        return self.call("Lsm", observer=observer)["members"]

    def alive_nodes(self) -> list[int]:
        return self.call("AliveNodes")["nodes"]

    def advance(self, rounds: int = 1) -> int:
        return self.call("Advance", rounds=rounds)["round"]

    def advance_bulk(self, rounds: int, snapshot_every: int | None = None) -> int:
        """One compiled scan; returns the target round immediately while the
        device runs.  Subsequent ``lsm``/``alive_nodes`` answer from the
        scan's snapshot stream (reply carries ``as_of_round``)."""
        req = {"rounds": rounds}
        if snapshot_every is not None:
            req["snapshot_every"] = snapshot_every
        return self.call("AdvanceBulk", **req)["round_target"]

    def put(self, file: str, data: bytes, confirm: bool = False) -> bool:
        return self.call(
            "Put", file=file, data_b64=base64.b64encode(data).decode(),
            confirm=confirm,
        )["ok"]

    def get(self, file: str) -> bytes | None:
        resp = self.call("Get", file=file)
        if not resp["found"]:
            return None
        return base64.b64decode(resp["data_b64"])

    def delete(self, file: str) -> bool:
        return self.call("Delete", file=file)["ok"]

    def ls(self, file: str) -> list[int]:
        return self.call("Ls", file=file)["replicas"]

    def store(self, node: int) -> dict[str, int]:
        return self.call("Store", node=node)["listing"]

    def grep(self, pattern: str) -> list[dict]:
        return self.call("Grep", pattern=pattern)["lines"]

    def close(self) -> None:
        self.channel.close()
