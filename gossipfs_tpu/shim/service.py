"""gRPC shim: the process boundary the BASELINE north star names.

The reference's control plane is a Go ``net/rpc`` server over TCP :9000
exposing 12 string-named methods (reference: server/server.go:19-251).  This
module is its TPU-native equivalent: a real gRPC server whose method surface
mirrors all 12 RPCs one-for-one, backed by the simulated detector + SDFS
control plane (``CoSim``), plus the membership verbs (join/leave/lsm) the
north star says external consumers keep using across the shim.

The wire is protobuf per ``shim/gossipfs.proto`` (see ``shim/wire.py``):
messages are real proto structs encoded/decoded at this server's boundary
through gRPC's generic-handler API, so any language's gRPC toolchain can
generate a full client from the ``.proto`` — tools/gossipfs_sh_client.sh
drives the server with protoc + curl alone (no Python, no gRPC runtime;
tests/test_sh_client.py runs it in CI).

Method map (reference server/server.go -> here):

  Response (remote grep, :55-72)        -> Grep
  Get_put_info (:74-121)                -> GetPutInfo
  Get_file_data (:123-131)              -> GetFileData
  Get_file_info (:133-142)              -> GetFileInfo
  Ask_for_confirmation (:155-177)       -> AskForConfirmation
  Get_delete_info (:214-219)            -> GetDeleteInfo
  Delete_file_data (:221-223)           -> DeleteFileData
  Remote_reput (:225-229)               -> RemoteReput
  Vote (:231-234)                       -> Vote
  Assign_new_master (:236-239)          -> AssignNewMaster
  Update_file_version (:241-245)        -> UpdateFileVersion
  Get_Update_Meta (:247-251)            -> GetUpdateMeta

plus Join/Leave/Crash/Lsm/AliveNodes/Advance/Events (membership seam,
slave/slave.go:288-336, 546-613) and whole-op verbs Put/Get/Delete/Ls/Store/
ShowMetadata matching the CLI surface (README.md:8-29).
"""

from __future__ import annotations

import base64
import threading
from concurrent import futures

import grpc

from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.sdfs import election
from gossipfs_tpu.sdfs.types import CONFIRM_TIMEOUT
from gossipfs_tpu.shim import wire
from gossipfs_tpu.shim.wire import SERVICE

__all__ = ["SERVICE", "ShimServicer", "ShimServer"]


class ShimServicer:
    """The RPC method implementations over one CoSim (single-writer lock).

    ``confirm_timeout``: wall-clock seconds the master waits on a
    write-conflict confirmation callback before defaulting to reject — the
    reference's 30 s ``Ask_for_confirmation`` timeout (server.go:155-177;
    1 round == 1 s, so CONFIRM_TIMEOUT doubles as both).
    ``confirm_handler``: this node's answer when *it* is asked (the
    interactive yes/no prompt site, server.go:144-153); None falls back to
    the ``auto_confirm`` policy.
    """

    def __init__(
        self,
        sim: CoSim,
        auto_confirm: bool = False,
        confirm_timeout: float = float(CONFIRM_TIMEOUT),
        confirm_handler=None,
    ):
        self.sim = sim
        self.auto_confirm = auto_confirm
        self.confirm_timeout = confirm_timeout
        self.confirm_handler = confirm_handler
        self.address: str | None = None  # set by ShimServer after binding
        self._self_client = None  # loopback channel for the election fan-out
        self._lock = threading.Lock()
        # serializes tick+election pairs: a concurrent Advance must not
        # mutate detector state while an election reads per-node views
        self._election_lock = threading.Lock()
        # set by ShimServer: caps concurrent Advance handlers below the
        # worker-pool size so the election's self-dialed Vote /
        # AssignNewMaster RPCs always find a free worker (otherwise
        # Advances parked on _election_lock could hold every worker and
        # starve the self-call until its deadline — a livelock)
        self._advance_slots: threading.BoundedSemaphore | None = None
        # Vote tallies: candidate -> set of voters (Receive_vote state,
        # reference: slave/slave.go:53-57, 968-984)
        self._votes: dict[int, set[int]] = {}
        # while an AdvanceBulk scan is in flight, membership reads answer
        # from its snapshot stream instead of blocking on device futures
        self._snapshots = None

    # -- membership verbs (the north-star seam) ----------------------------
    def Join(self, req, ctx):
        with self._lock:
            self.sim.detector.join(int(req["node"]))
        return {"ok": True}

    def Leave(self, req, ctx):
        with self._lock:
            self.sim.detector.leave(int(req["node"]))
        return {"ok": True}

    def Crash(self, req, ctx):
        with self._lock:
            self.sim.detector.crash(int(req["node"]))
        return {"ok": True}

    def Lsm(self, req, ctx):
        with self._lock:
            snap = self._snapshots.latest() if self._snapshots else None
            if snap is not None:
                obs = int(req["observer"])
                return {"members": snap.membership(obs), "as_of_round": snap.round}
            return {"members": self.sim.detector.membership(int(req["observer"]))}

    def AliveNodes(self, req, ctx):
        with self._lock:
            snap = self._snapshots.latest() if self._snapshots else None
            if snap is not None:
                import numpy as np

                return {
                    "nodes": np.nonzero(snap.alive)[0].tolist(),
                    "as_of_round": snap.round,
                }
            return {"nodes": self.sim.detector.alive_nodes()}

    def Advance(self, req, ctx):
        # fail fast when the worker pool is saturated with Advances rather
        # than park on _election_lock holding a worker thread — the
        # reserved headroom keeps the election's self-dialed RPCs
        # schedulable (see _advance_slots); ShimClient retries RESOURCE_
        # EXHAUSTED with backoff
        slots = self._advance_slots
        if slots is not None and not slots.acquire(blocking=False):
            ctx.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                "advance workers saturated; retry",
            )
        try:
            # the election lock (taken OUTSIDE the sim lock) serializes
            # whole tick+election sequences: no other Advance can mutate
            # detector state while run_pending_election reads per-node views
            with self._election_lock:
                with self._lock:
                    self._snapshots = None  # synchronous path resolves bulk scans
                    self.sim.tick(int(req.get("rounds", 1)))
                    out = {"round": self.sim.round}
                # sim lock released: the distributed election self-dials
                # Vote / AssignNewMaster on this server, whose handlers
                # take it
                self.run_pending_election()
            return out
        finally:
            if slots is not None:
                slots.release()

    # -- distributed election (reference: slave.go:930-1051) ---------------
    def _self_call(self, method: str, **req):
        from gossipfs_tpu.shim.client import ShimClient

        if self._self_client is None:
            self._self_client = ShimClient(self.address, timeout=30.0)
        return self._self_client.call(method, **req)

    def run_pending_election(self) -> bool:
        """Drive one election attempt through the real RPC surface.

        Mirrors the reference's per-node protocol: every live node whose own
        membership row lacks the master votes for the lowest member of ITS
        OWN row (revote_master, slave.go:930-948) via the Vote RPC; the
        tally elects on majority (Receive_vote, :968-984); the winner then
        fans out AssignNewMaster to collect registries and commits the
        rebuilt metadata (rebuild_file_meta, :986-1051).  Split views that
        produce no majority stall the election — it retries on the next
        Advance, like the reference's per-heartbeat revote.  Call under
        ``_election_lock`` with the sim lock RELEASED (the dialed handlers
        take it); the election lock keeps concurrent Advances from mutating
        detector state mid-election.  Returns True if a master was
        installed.
        """
        sim = self.sim
        if getattr(sim, "election", "local") != "rpc":
            return False
        with self._lock:
            if not sim.cluster.election_pending:
                return False
            old_master = sim.cluster.master_node
            now = sim.round
        det = sim.detector
        winner = None
        for voter in det.alive_nodes():
            row = det.membership(voter)
            if not row or old_master in row:
                continue  # this node still believes in the old master
            candidate = min(row)  # MemberList[0] in id order (slave.go:936)
            reply = self._self_call("Vote", candidate=candidate, voter=voter)
            if reply.get("elected"):
                winner = candidate
                break
        if winner is None:
            return False  # split view / insufficient votes: stall + retry
        # the winner collects registries from every member it can reach
        with self._lock:
            members = [x for x in sim.cluster.live if x in sim.cluster.reachable]
        registries: dict[int, dict[str, int]] = {}
        for node in members:
            reply = self._self_call("AssignNewMaster", node=node, master=winner)
            registries[node] = reply["listing"]
        with self._lock:
            if winner not in set(det.alive_nodes()):
                # master crashed during the rebuild: abort the commit; the
                # next Advance detects the vacancy and re-elects
                sim.cluster.election_pending = True
                return False
            sim.cluster.install_rebuilt_master(winner, registries, now)
            sim.cluster.election_pending = False
            sim.log.write(
                f"Elected new master {winner} via Vote/AssignNewMaster "
                f"(was {old_master})",
                round=now,
                kind="election",
                node=winner,  # the winner announces (slave.go:968-984)
            )
        return True

    def AdvanceBulk(self, req, ctx):
        """Advance many rounds as ONE compiled scan (SURVEY §7.4's async
        boundary): jax's async dispatch returns before the device finishes,
        and an in-scan host callback streams membership snapshots, so
        ``Lsm``/``AliveNodes`` answer from the freshest snapshot (tagged
        ``as_of_round``) while the scan runs instead of blocking on device
        futures.  The next synchronous verb joins the scan and drops back
        to exact reads.

        Bulk advancement trades the per-round SDFS co-sim reactions for
        throughput (the detector still detects; the control plane reacts at
        the next ``Advance``) — the same trade ``bench.run.run_cosim``
        makes between recovery cadences.
        """
        rounds = int(req.get("rounds", 1))
        every = int(req.get("snapshot_every", max(1, rounds // 10)))
        with self._lock:
            start = int(self.sim.detector.state.round)  # resolved pre-dispatch
            self._snapshots = self.sim.detector.advance_bulk(
                rounds, snapshot_every=every
            )
            return {"round_target": start + rounds, "snapshot_every": every}

    def Vitals(self, req, ctx):
        """The uniform vitals counter set (obs.schema.VITALS_FIELDS) as
        one GrepReply Struct line — the same verb the deploy daemons
        serve per node, so one client renders live counters identically
        across engines (sim-only fields are simply absent elsewhere)."""
        with self._lock:
            return {"lines": [self.sim.vitals()]}

    def Events(self, req, ctx):
        """Detection events from cursor ``since`` (default 0) on; the reply's
        ``next`` is the cursor for the following poll, so long-running
        monitors don't re-download (or double-count) the whole history."""
        since = int(req.get("since", 0))
        with self._lock:
            events = self.sim.events[since:]
            return {
                "events": [
                    {
                        "round": e.round,
                        "observer": e.observer,
                        "subject": e.subject,
                        "false_positive": e.false_positive,
                    }
                    for e in events
                ],
                "next": since + len(events),
            }

    # -- the 12 reference RPCs --------------------------------------------
    def Grep(self, req, ctx):
        """TCPServer.Response — distributed log grep (server.go:55-72).

        An optional ``node`` restricts the search to that machine's own log
        view, matching the reference's grep-one-machine's-Machine.log
        semantics; without it the whole cluster's stream is searched.
        """
        with self._lock:
            node = req.get("node")
            return {
                "lines": self.sim.log.grep(
                    req["pattern"], node=None if node is None else int(node)
                )
            }

    def _ask_confirmation(self, callback: str, name: str) -> bool:
        """Master -> requester confirmation round-trip (server.go:155-177).

        Dials the requester's own shim server at ``callback`` and asks; any
        error or no answer within ``confirm_timeout`` seconds is the
        reference's 30 s-timeout outcome: reject.
        """
        from gossipfs_tpu.shim.client import ShimClient

        client = ShimClient(callback, timeout=self.confirm_timeout)
        try:
            reply = client.call("AskForConfirmation", file=name)
            return bool(reply.get("confirm", False))
        except Exception:
            return False
        finally:
            client.close()

    def _resolve_conflict(self, req, name: str) -> bool:
        """Whether a conflicting put may proceed.  Precedence: explicit
        ``confirm`` flag (programmatic client) > server auto-confirm policy >
        callback round-trip to the requester > reject.  Call with the sim
        lock RELEASED — the callback is a network round-trip.
        """
        if req.get("confirm", False) or self.auto_confirm:
            return True
        callback = req.get("callback")
        if callback:
            return self._ask_confirmation(callback, name)
        return False

    def GetPutInfo(self, req, ctx):
        """Conflict check + placement + version bump (server.go:74-121).

        On a write within the 60-round window the master asks the
        *requester* for confirmation: a ``callback`` address in the request
        names the requester's own shim server, which the master dials with
        a ``confirm_timeout``-second deadline defaulting to reject
        (Ask_for_confirmation, server.go:144-177).  The callback runs with
        the lock released (only this request blocks, like the reference's
        per-connection goroutine); the conflict window is re-checked under
        the lock before committing, so a put that raced in during the
        callback still needs its own confirmation.
        """
        name = req["file"]
        with self._lock:
            now = self.sim.round
            master = self.sim.cluster.master
            conflict = master.updated_recently(name, now)
            # version observed when the confirmation was asked: the answer
            # covers overwriting THIS write, not one that races in later
            _, seen_version = master.file_info(name)
        confirmed = self._resolve_conflict(req, name) if conflict else False
        if conflict and not confirmed:
            return {"ok": False, "conflict": True}
        with self._lock:
            master = self.sim.cluster.master
            _, cur_version = master.file_info(name)
            if master.updated_recently(name, self.sim.round) and (
                not confirmed or cur_version != seen_version
            ):
                # a concurrent put landed while the lock was released (e.g.
                # during the confirmation callback): it needs its own
                # confirmation — any earlier answer was about the version
                # observed then, so re-reject and let the client retry
                return {"ok": False, "conflict": True}
            replicas, version = master.handle_put(name, self.sim.round)
            return {"ok": bool(replicas), "replicas": replicas, "version": version}

    def GetFileData(self, req, ctx):
        """Replica-side version report (server.go:123-131, slave.go:799-813)."""
        with self._lock:
            store = self.sim.cluster.stores[int(req["node"])]
            return {"local_version": store.version(req["file"])}

    def GetFileInfo(self, req, ctx):
        """Replica list + version; ([], -1) when absent (server.go:133-142)."""
        with self._lock:
            replicas, version = self.sim.cluster.master.file_info(req["file"])
            return {"replicas": replicas, "version": version}

    def AskForConfirmation(self, req, ctx):
        """The requester-side conflict prompt (server.go:144-177): the
        master dialed THIS node back about ``file``.  ``confirm_handler``
        is the interactive yes/no site; without one, the ``auto_confirm``
        policy answers (and the master's timeout covers a hung prompt)."""
        if self.confirm_handler is not None:
            return {"confirm": bool(self.confirm_handler(req.get("file", "")))}
        return {"confirm": self.auto_confirm}

    def GetDeleteInfo(self, req, ctx):
        """Master drops metadata, returns old replicas (server.go:214-219)."""
        with self._lock:
            return {"old_replicas": self.sim.cluster.master.delete(req["file"])}

    def DeleteFileData(self, req, ctx):
        """Replica-local delete (server.go:221-223, sdfs_slave.go:63-77)."""
        with self._lock:
            ok = self.sim.cluster.stores[int(req["node"])].delete(req["file"])
            return {"ok": ok}

    def RemoteReput(self, req, ctx):
        """Ask a healthy source to push a file to a new replica
        (server.go:225-229 -> slave.Re_put, slave.go:1093-1120)."""
        with self._lock:
            stores = self.sim.cluster.stores
            blob = stores[int(req["source"])].get(req["file"])
            if blob is None:
                return {"ok": False}
            stores[int(req["target"])].put(req["file"], blob, int(req["version"]))
            return {"ok": True}

    def Vote(self, req, ctx):
        """Election vote (server.go:231-234 -> Receive_vote, slave.go:968-984):
        candidate counts distinct voters; on majority of the current view it
        becomes master."""
        candidate, voter = int(req["candidate"]), int(req["voter"])
        with self._lock:
            voters = self._votes.setdefault(candidate, set())
            voters.add(voter)
            # only count voters still in the current view: a tally that
            # persists across a stalled round must not let since-dead
            # voters push a later, smaller majority over the line
            live = set(self.sim.cluster.live)
            elected = election.tally(voters & live, len(live))
            if elected:
                self.sim.cluster.master_node = candidate
                # election over: clear ALL tallies so losers' votes can't
                # leak into a later election (VoteStatus reset,
                # slave.go:968-975)
                self._votes.clear()
            return {"elected": elected, "votes": len(voters)}

    def AssignNewMaster(self, req, ctx):
        """Tell a node the new master; it answers with its local registry for
        the metadata rebuild (server.go:236-239 -> slave.go:1045-1051)."""
        with self._lock:
            self.sim.cluster.master_node = int(req["master"])
            listing = self.sim.cluster.stores[int(req["node"])].listing()
            return {"listing": listing}

    def UpdateFileVersion(self, req, ctx):
        """Registry-only version write on a replica (server.go:241-245 ->
        sdfs_slave.go:20-25)."""
        with self._lock:
            store = self.sim.cluster.stores[int(req["node"])]
            store.set_version(req["file"], int(req["version"]))
            return {"ok": True}

    def GetUpdateMeta(self, req, ctx):
        """Feed a membership snapshot, get the repair plan back
        (server.go:247-251 -> master.go:74-127).  Planning only — executing
        the copies and committing is the caller's job, like the reference;
        the cluster's own view/reachability/master state is untouched (the
        snapshot may be stale relative to the detector)."""
        with self._lock:
            cluster = self.sim.cluster
            view = sorted(int(x) for x in req["membership"])
            reach = cluster.reachable & set(view)
            plans = cluster.master.plan_repairs(view, reachable=reach)
            return {
                "plans": [
                    {
                        "file": p.file,
                        "source": p.source,
                        "version": p.version,
                        "new_nodes": list(p.new_nodes),
                        "survivors": list(p.survivors),
                    }
                    for p in plans
                ]
            }

    # -- whole-op verbs (CLI surface, README.md:8-29) ----------------------
    def Put(self, req, ctx):
        data = base64.b64decode(req["data_b64"])
        name = req["file"]
        # resolve any needed confirmation BEFORE taking the lock: the
        # callback is a network round-trip (up to confirm_timeout) that must
        # not stall every other RPC.  The pre-resolved answer feeds the
        # in-lock put; a conflict that appears only while we were unlocked
        # gets a None confirm and rejects conservatively.
        with self._lock:
            conflict = self.sim.cluster.master.updated_recently(
                name, self.sim.round
            )
        confirm = None
        if conflict:
            allowed = self._resolve_conflict(req, name)
            confirm = (lambda: allowed)  # noqa: E731
        elif req.get("confirm") or self.auto_confirm:
            confirm = lambda: True  # noqa: E731
        with self._lock:
            ok = self.sim.put(name, data, confirm=confirm)
            return {"ok": ok}

    def Get(self, req, ctx):
        with self._lock:
            blob = self.sim.get(req["file"])
        if blob is None:
            return {"found": False}
        return {"found": True, "data_b64": base64.b64encode(blob).decode()}

    def Delete(self, req, ctx):
        with self._lock:
            return {"ok": self.sim.delete(req["file"])}

    def Ls(self, req, ctx):
        with self._lock:
            return {"replicas": self.sim.cluster.ls(req["file"])}

    def Store(self, req, ctx):
        with self._lock:
            return {"listing": self.sim.cluster.store_listing(int(req["node"]))}

    def ShowMetadata(self, req, ctx):
        with self._lock:
            return {
                "files": {
                    name: {
                        "version": info.version,
                        "node_list": list(info.node_list),
                    }
                    for name, info in self.sim.cluster.master.files.items()
                }
            }

    # -- plumbing -----------------------------------------------------------
    METHODS = [
        "Join", "Leave", "Crash", "Lsm", "AliveNodes", "Advance",
        "AdvanceBulk", "Events", "Vitals",
        "Grep", "GetPutInfo", "GetFileData", "GetFileInfo",
        "AskForConfirmation", "GetDeleteInfo", "DeleteFileData", "RemoteReput",
        "Vote", "AssignNewMaster", "UpdateFileVersion", "GetUpdateMeta",
        "Put", "Get", "Delete", "Ls", "Store", "ShowMetadata",
    ]

    def generic_handler(self) -> grpc.GenericRpcHandler:
        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                getattr(self, name),
                request_deserializer=wire.request_deserializer(name),
                response_serializer=wire.response_serializer(name),
            )
            for name in self.METHODS
        }
        return grpc.method_handlers_generic_handler(SERVICE, handlers)


class ShimServer:
    """Owns the grpc.Server lifecycle around one ShimServicer."""

    def __init__(
        self,
        sim: CoSim,
        port: int = 0,
        host: str = "127.0.0.1",
        auto_confirm: bool = False,
        confirm_timeout: float = float(CONFIRM_TIMEOUT),
        confirm_handler=None,
        max_workers: int = 8,
        max_message_mb: int = wire.MAX_MESSAGE_MB,
    ):
        self.servicer = ShimServicer(
            sim, auto_confirm=auto_confirm, confirm_timeout=confirm_timeout,
            confirm_handler=confirm_handler,
        )
        # same cap as the client (wire.py — multi-MB file payloads)
        opts = wire.message_size_options(max_message_mb)
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers), options=opts
        )
        # leave >= 2 workers free for the election's self-dialed Vote /
        # AssignNewMaster RPCs (see ShimServicer._advance_slots)
        self.servicer._advance_slots = threading.BoundedSemaphore(
            max(1, max_workers - 2)
        )
        self.server.add_generic_rpc_handlers((self.servicer.generic_handler(),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.address = f"{host}:{self.port}"
        self.servicer.address = self.address

    def start(self) -> "ShimServer":
        self.server.start()
        return self

    def stop(self, grace: float = 0.5) -> None:
        if self.servicer._self_client is not None:
            self.servicer._self_client.close()
            self.servicer._self_client = None
        self.server.stop(grace).wait()


def main(argv=None) -> None:
    """Standalone shim process — the reference's ``./main`` for the service:

        python -m gossipfs_tpu.shim.service --n 100 --port 9000

    Serves /gossipfs.Shim/* until interrupted; advance the simulated clock
    via the Advance/AdvanceBulk RPCs (shim/client.py) or --auto-tick.
    """
    import argparse
    import time as _time

    from gossipfs_tpu.config import SimConfig

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--n", type=int, default=10)
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--topology", choices=["ring", "random"], default="ring")
    p.add_argument("--auto-confirm", action="store_true",
                   help="answer write-conflict confirmations yes (30 s-timeout default is no)")
    p.add_argument("--auto-tick", type=float, default=0.0, metavar="SECONDS",
                   help="advance one round every SECONDS of wall time (the "
                        "reference's 1 s driver: --auto-tick 1.0); default: "
                        "clients drive time via Advance")
    args = p.parse_args(argv)

    fanout = 3 if args.topology == "ring" else SimConfig.log_fanout(args.n)
    cfg = SimConfig(n=args.n, topology=args.topology, fanout=fanout)
    sim = CoSim(cfg)
    server = ShimServer(sim, port=args.port, auto_confirm=args.auto_confirm).start()
    print(f"gossipfs shim serving {SERVICE} on {server.address} (n={args.n})",
          flush=True)
    try:
        while True:
            if args.auto_tick > 0:
                _time.sleep(args.auto_tick)
                with server.servicer._election_lock:
                    with server.servicer._lock:
                        # like Advance: the synchronous path resolves any
                        # bulk scan, so Lsm/AliveNodes can't stay pinned to
                        # a stale bulk snapshot while the state moves on
                        server.servicer._snapshots = None
                        sim.tick(1)
                    server.servicer.run_pending_election()
            else:
                _time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main()
