"""Bounded exponential backoff for control-plane RPCs — ONE owner.

Before round 14 every retry loop in the deploy/shim lane was
hand-rolled: ``ShimClient.call`` had a fixed-count RESOURCE_EXHAUSTED
loop with open-coded delay doubling, and the launcher's control-plane
fan-outs (``load_scenario``/``load_suspicion``/``vitals``) were
one-shot ``try/except`` — a node hiccuping for one scheduling quantum
(a kill -9 storm, a correlated outage, an overloaded CI host) dropped
its push silently.  Raw retry loops also have no TOTAL time bound: six
doublings from 50 ms is fine, but a loop around a 30 s data-plane
deadline could park a caller for minutes.

:func:`call_with_backoff` is the one discipline: bounded attempt count,
exponential delay with a cap, and a hard ceiling on the TOTAL time
spent sleeping — the property the deploy campaign runner
(campaigns/engines.py) relies on when it calls "a campaign surviving a
correlated outage" evidence of graceful degradation (a runner that can
hang is not graceful).  Callers pass a *retryable* predicate so the
policy stays per-call-site: the shim client retries only
RESOURCE_EXHAUSTED (the server's explicit backpressure — anything else
is the caller's to see), the launcher's idempotent control-plane verbs
also retry UNAVAILABLE/DEADLINE_EXCEEDED (a node mid-restart or a
starved host, both transient by design there).

Pure stdlib; the grpc predicates import grpc lazily so the jax-free
deploy tooling can import this module without it.
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

T = TypeVar("T")


def call_with_backoff(
    fn: Callable[[], T],
    *,
    retryable: Callable[[BaseException], bool],
    attempts: int = 6,
    base_delay: float = 0.05,
    max_delay: float = 1.0,
    total_deadline: float = 10.0,
) -> T:
    """Call ``fn`` retrying transient failures with bounded backoff.

    Retries only exceptions ``retryable`` accepts; everything else
    propagates immediately.  The delay doubles from ``base_delay`` up to
    ``max_delay`` per attempt, and the SUM of all sleeps never exceeds
    ``total_deadline`` (each sleep is clipped to the remaining budget;
    an exhausted budget re-raises without sleeping) — so the worst-case
    wall time of a call is bounded by
    ``attempts * <per-call deadline> + min(total_deadline, geometric
    sum)`` no matter how the failures interleave.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    t0 = time.monotonic()
    delay = base_delay
    last: BaseException | None = None
    for i in range(attempts):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — predicate decides
            if not retryable(e):
                raise
            last = e
            if i == attempts - 1:
                break
            remaining = total_deadline - (time.monotonic() - t0)
            if remaining <= 0:
                break
            time.sleep(min(delay, max_delay, remaining))
            delay = min(delay * 2, max_delay)
    assert last is not None
    raise last


def grpc_backpressure(e: BaseException) -> bool:
    """The shim server's explicit backpressure: RESOURCE_EXHAUSTED only
    (its Advance handlers fail fast instead of parking workers on the
    election lock — shim/service.py)."""
    import grpc

    return isinstance(e, grpc.RpcError) and (
        e.code() is grpc.StatusCode.RESOURCE_EXHAUSTED
    )


def grpc_transient(e: BaseException) -> bool:
    """Transient-by-design failures of an IDEMPOTENT control-plane verb:
    backpressure, a node mid-restart (UNAVAILABLE), or a starved host
    missing a short deadline (DEADLINE_EXCEEDED).  NOT for data-plane
    writes — a retried non-idempotent Put could double-apply."""
    import grpc

    return isinstance(e, grpc.RpcError) and e.code() in (
        grpc.StatusCode.RESOURCE_EXHAUSTED,
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
    )
