"""gossipfs-lint core: the rule registry and the repo source index.

The repo's load-bearing invariants used to be enforced by ad-hoc greps
scattered across three test modules (the quorum regex in
``tests/test_traffic.py``, the schema LINT maps in ``tests/test_obs.py``,
the scratch-budget reconciliation in ``tests/test_merge_pallas.py``) —
each new subsystem re-invented the pattern and nothing shared the file
walking, the AST parsing, or the reporting.  This module is the ONE
framework: declarative :class:`Rule` objects over a cached
:class:`RepoIndex`, runnable as a library (``tests/test_analysis.py``,
the migrated wrappers) and as a CLI (``tools/lint.py``, exit-code 1 on
any finding).

Two rule kinds:

* ``"ast"`` — pure stdlib-``ast`` source analysis; no project imports,
  no jax.  These run everywhere (the tier-1 fast lane, the bare CLI).
* ``"probe"`` — checks that must import the package (the rr
  scratch-budget reconciliation spies on ``pl.pallas_call``).  The CLI
  includes them only with ``--probe``; the wrappers in the test modules
  keep them on the fast lane.

Every rule names a fixture under ``tests/fixtures/lint/`` that makes it
fire, mounted over the index via ``overlay`` — the analyzer is itself
tested (``tests/test_analysis.py``), not trusted.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Callable, Iterable

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]

# Directories the AST rules walk by default.  tests/ is deliberately out:
# fixtures must be mountable without tripping the repo-clean check, and
# test code may quote forbidden idioms when pinning them.  The analyzer
# itself (gossipfs_tpu/analysis/) is excluded for the same reason — its
# rule messages and matchers quote the idioms they forbid.
DEFAULT_SCAN = ("gossipfs_tpu", "tools")
_SELF = "gossipfs_tpu/analysis/"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source line."""

    rule: str
    path: str       # repo-relative posix path
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    description: str
    check: Callable[["RepoIndex"], list[Finding]]
    kind: str = "ast"               # "ast" | "probe"
    fixture: str | None = None      # tests/fixtures/lint/<fixture>
    fixture_at: str | None = None   # virtual repo path the fixture mounts at


REGISTRY: dict[str, Rule] = {}


def rule(name: str, description: str, *, kind: str = "ast",
         fixture: str | None = None, fixture_at: str | None = None):
    """Register a rule.  ``fixture``/``fixture_at`` wire the committed
    trigger case: ``RepoIndex(overlay={fixture_at: fixtures/<fixture>})``
    must make the rule produce at least one finding."""

    def deco(fn: Callable[["RepoIndex"], list[Finding]]):
        if name in REGISTRY:
            raise ValueError(f"duplicate rule name: {name}")
        REGISTRY[name] = Rule(name, description, fn, kind, fixture,
                              fixture_at)
        return fn

    return deco


class RepoIndex:
    """Cached source + AST access over the repo tree, with an overlay.

    ``overlay`` maps *virtual* repo-relative paths to real files on
    disk: an overlaid path joins every :meth:`py_files` listing whose
    prefix matches and SHADOWS a real file at the same path — the
    mechanism the fixture tests use to inject a violating module (or a
    violating stand-in for ``config.py``) without touching the tree.
    """

    def __init__(self, root: pathlib.Path | str = REPO_ROOT,
                 overlay: dict[str, pathlib.Path | str] | None = None):
        self.root = pathlib.Path(root)
        self.overlay = {k: pathlib.Path(v) for k, v in (overlay or {}).items()}
        self._src: dict[str, str] = {}
        self._tree: dict[str, ast.Module] = {}

    # -- file access --------------------------------------------------------
    def _real(self, rel: str) -> pathlib.Path:
        return self.overlay.get(rel, self.root / rel)

    def exists(self, rel: str) -> bool:
        return self._real(rel).is_file()

    def source(self, rel: str) -> str:
        if rel not in self._src:
            self._src[rel] = self._real(rel).read_text(encoding="utf-8")
        return self._src[rel]

    def tree(self, rel: str) -> ast.Module:
        if rel not in self._tree:
            self._tree[rel] = ast.parse(self.source(rel), filename=rel)
        return self._tree[rel]

    def py_files(self, *prefixes: str) -> list[str]:
        """Repo-relative posix paths of every ``.py`` file under the
        prefixes (default scan set when none given), overlay included."""
        prefixes = prefixes or DEFAULT_SCAN
        out: set[str] = set()
        for pre in prefixes:
            base = self.root / pre
            if base.is_dir():
                for p in base.rglob("*.py"):
                    if "__pycache__" in p.parts:
                        continue
                    rel = p.relative_to(self.root).as_posix()
                    if rel.startswith(_SELF):
                        continue
                    out.add(rel)
            elif base.is_file():
                out.add(pre)
            for virt in self.overlay:
                if virt == pre or virt.startswith(pre.rstrip("/") + "/"):
                    out.add(virt)
        return sorted(out)


# ---------------------------------------------------------------------------
# Shared AST helpers (used by every rules_* module)
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def functions(tree: ast.AST) -> Iterable[ast.FunctionDef | ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def literal_dict(tree: ast.Module, name: str) -> dict | None:
    """Evaluate a module-level ``NAME = {...literal...}`` assignment."""
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets, value = [node.target.id], node.value
        else:
            continue
        if name in targets and value is not None:
            try:
                return ast.literal_eval(value)
            except ValueError:
                return None
    return None


def namedtuple_fields(tree: ast.Module, class_name: str) -> list[str] | None:
    """Annotated field names of a ``class X(NamedTuple)`` definition."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
    return None


def run_rules(index: RepoIndex | None = None,
              names: Iterable[str] | None = None,
              kinds: Iterable[str] = ("ast",)) -> list[Finding]:
    """Run the selected rules and return every finding, stably ordered."""
    index = index or RepoIndex()
    kinds = set(kinds)
    findings: list[Finding] = []
    for name, r in sorted(REGISTRY.items()):
        if names is not None and name not in set(names):
            continue
        if names is None and r.kind not in kinds:
            continue
        findings.extend(r.check(index))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
