"""Single-ownership rules: owned expressions re-derived anywhere else flag.

The repo's correctness story leans on a handful of formulas each having
exactly ONE owner module (quorum arithmetic, the bounded-backoff
schedule, obs event-line parsing, the latency quantile rollup, VMEM
scratch specs, the ``n/a``-not-0 vitals rendering).  Review caught every
historical drift by eye; these rules catch the *shape* of a re-derivation
mechanically, so a new subsystem cannot quietly fork the math.
"""

from __future__ import annotations

import ast

from gossipfs_tpu.analysis.framework import (
    Finding,
    RepoIndex,
    const_str,
    dotted,
    functions,
    names_in,
    rule,
)

# ---------------------------------------------------------------------------
# quorum arithmetic — owner: gossipfs_tpu/sdfs/quorum.py
# ---------------------------------------------------------------------------

_QUORUM_OWNER = "gossipfs_tpu/sdfs/quorum.py"


def _is_const(node: ast.AST, value: int) -> bool:
    return isinstance(node, ast.Constant) and node.value == value


def _is_quorum_expr(node: ast.AST) -> bool:
    """``(x + 1) // 2`` or ``x // 2 + 1`` — the idiomatic int forms of
    floor/ceil((n+1)/2) the reference derives quorums from."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.FloorDiv):
        if _is_const(node.right, 2) and isinstance(node.left, ast.BinOp) \
                and isinstance(node.left.op, ast.Add) \
                and (_is_const(node.left.left, 1)
                     or _is_const(node.left.right, 1)):
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        for half, one in ((node.left, node.right), (node.right, node.left)):
            if _is_const(one, 1) and isinstance(half, ast.BinOp) \
                    and isinstance(half.op, ast.FloorDiv) \
                    and _is_const(half.right, 2):
                return True
    return False


@rule(
    "quorum-ownership",
    "W/R quorum arithmetic ((x+1)//2, x//2+1) may appear only in "
    "sdfs/quorum.py; every other module imports the named functions",
    fixture="quorum_ownership.py",
    fixture_at="gossipfs_tpu/traffic/_lint_fixture.py",
)
def check_quorum(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        if rel == _QUORUM_OWNER:
            continue
        for node in ast.walk(index.tree(rel)):
            if _is_quorum_expr(node):
                out.append(Finding(
                    "quorum-ownership", rel, node.lineno,
                    "quorum arithmetic re-derived here — import "
                    "read_quorum/write_quorum from gossipfs_tpu.sdfs.quorum",
                ))
    return out


# ---------------------------------------------------------------------------
# stripe quorum arithmetic — owner: gossipfs_tpu/sdfs/quorum.py
# ---------------------------------------------------------------------------

_STRIPE_NAMES = {"k", "m", "stripe_k", "stripe_m", "STRIPE_K", "STRIPE_M"}


def _is_stripe_threshold(node: ast.AST) -> bool:
    """``k + m - slack`` used as a COMPARISON bound — the stripe
    write-quorum shape (``acks >= k + m - f``).  ``k + m`` alone (a
    stripe width, a fragment count, a loop bound) is legal everywhere;
    only subtracting slack from the width *inside a comparison*
    re-derives the erasure threshold math."""
    if not isinstance(node, ast.Compare):
        return False
    for comp in [node.left, *node.comparators]:
        if isinstance(comp, ast.BinOp) and isinstance(comp.op, ast.Sub) \
                and isinstance(comp.left, ast.BinOp) \
                and isinstance(comp.left.op, ast.Add) \
                and len(names_in(comp.left) & _STRIPE_NAMES) >= 2:
            return True
    return False


@rule(
    "stripe-quorum-ownership",
    "the stripe threshold shape (acks >= k + m - slack, k-of-(k+m) "
    "bounds) may appear only in sdfs/quorum.py; erasure/traffic/bench "
    "import stripe_read_quorum/stripe_write_quorum",
    fixture="stripe_quorum_ownership.py",
    fixture_at="gossipfs_tpu/erasure/_lint_fixture.py",
)
def check_stripe_quorum(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        if rel == _QUORUM_OWNER:
            continue
        for node in ast.walk(index.tree(rel)):
            if _is_stripe_threshold(node):
                out.append(Finding(
                    "stripe-quorum-ownership", rel, node.lineno,
                    "stripe threshold arithmetic (k + m - slack in a "
                    "comparison) re-derived here — import "
                    "stripe_read_quorum/stripe_write_quorum from "
                    "gossipfs_tpu.sdfs.quorum",
                ))
    return out


# ---------------------------------------------------------------------------
# exponential backoff — owner: gossipfs_tpu/shim/retry.py
# ---------------------------------------------------------------------------

_BACKOFF_OWNER = "gossipfs_tpu/shim/retry.py"
_SLEEPS = {"time.sleep", "asyncio.sleep"}


def _grows_geometrically(loop: ast.AST, name: str) -> bool:
    """True if ``name`` GROWS geometrically inside the loop — the
    exponential-schedule shapes ``delay *= 2``, ``delay = delay * k``
    (self-referential growth, min/max-capped included) and
    ``delay = base ** attempt``.  A multiplication that does not feed
    the name back into itself (``delay = 0.05 * attempt`` — linear;
    ``delay = 0.1 * random()`` — jitter) is NOT a backoff schedule."""
    for node in ast.walk(loop):
        if isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == name \
                and isinstance(node.op, (ast.Mult, ast.Pow)):
            return True
        if isinstance(node, ast.Assign):
            targets = {t.id for t in node.targets if isinstance(t, ast.Name)}
            if name in targets:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.BinOp) and (
                            isinstance(sub.op, ast.Pow)
                            or (isinstance(sub.op, ast.Mult)
                                and name in names_in(sub))):
                        return True
    return False


@rule(
    "backoff-ownership",
    "retry loops with a geometrically-growing sleep re-derive the "
    "bounded-backoff schedule; call shim.retry.call_with_backoff",
    fixture="backoff_ownership.py",
    fixture_at="gossipfs_tpu/deploy/_lint_fixture.py",
)
def check_backoff(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        if rel == _BACKOFF_OWNER:
            continue
        for loop in ast.walk(index.tree(rel)):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.Call) \
                        and dotted(node.func) in _SLEEPS:
                    for name in names_in(node):
                        if _grows_geometrically(loop, name):
                            out.append(Finding(
                                "backoff-ownership", rel, node.lineno,
                                "exponential retry backoff re-derived "
                                "here — use shim.retry.call_with_backoff "
                                "(the one bounded-backoff discipline)",
                            ))
                            break
    return out


# ---------------------------------------------------------------------------
# obs event-line parsing — owners: gossipfs_tpu/obs/*, tools/timeline.py
# ---------------------------------------------------------------------------

_OBS_PARSE_OWNERS = ("gossipfs_tpu/obs/", "tools/timeline.py")


@rule(
    "obsparse-ownership",
    "hand-parsing obs event lines (json.loads + the \"kind\" key in one "
    "function) outside obs/ and tools/timeline.py; use "
    "obs.schema.Event.from_record / obs.recorder.load_stream",
    fixture="obsparse_ownership.py",
    fixture_at="gossipfs_tpu/campaigns/_lint_fixture.py",
)
def check_obsparse(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        if rel.startswith(_OBS_PARSE_OWNERS[0]) or rel == _OBS_PARSE_OWNERS[1]:
            continue
        for fn in functions(index.tree(rel)):
            loads_line = None
            touches_kind = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and dotted(node.func) == "json.loads":
                    loads_line = loads_line or node.lineno
                if const_str(node) == "kind":
                    touches_kind = True
            if loads_line is not None and touches_kind:
                out.append(Finding(
                    "obsparse-ownership", rel, loads_line,
                    f"{fn.name}() json.loads-parses records and reads "
                    "their \"kind\" by hand — route through "
                    "obs.schema.Event.from_record / obs.recorder."
                    "load_stream so schema changes stay one-owner",
                ))
    return out


# ---------------------------------------------------------------------------
# latency quantile rollup — owner: gossipfs_tpu/traffic/workload.py
# ---------------------------------------------------------------------------

_QUANTILE_OWNER = "gossipfs_tpu/traffic/workload.py"
_QUANTILE_KEYS = {"p50_ms", "p95_ms"}


@rule(
    "quantile-ownership",
    "the p50/p95 nearest-rank rollup convention has one owner "
    "(traffic.workload.quantiles); building those keys by hand or "
    "calling statistics.quantiles re-derives it",
    fixture="quantile_ownership.py",
    fixture_at="gossipfs_tpu/bench/_lint_fixture.py",
)
def check_quantiles(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        if rel == _QUANTILE_OWNER:
            continue
        for node in ast.walk(index.tree(rel)):
            if isinstance(node, ast.Dict):
                keys = {const_str(k) for k in node.keys if k is not None}
                if keys & _QUANTILE_KEYS:
                    out.append(Finding(
                        "quantile-ownership", rel, node.lineno,
                        "p50/p95 rollup keys built by hand — call "
                        "traffic.workload.quantiles (the one "
                        "nearest-rank convention)",
                    ))
            if isinstance(node, ast.Call) \
                    and dotted(node.func) == "statistics.quantiles":
                out.append(Finding(
                    "quantile-ownership", rel, node.lineno,
                    "statistics.quantiles re-derives the latency rollup "
                    "— call traffic.workload.quantiles",
                ))
    return out


# ---------------------------------------------------------------------------
# VMEM scratch specs — owner: gossipfs_tpu/ops/merge_pallas.py
# ---------------------------------------------------------------------------

_VMEM_OWNER = "gossipfs_tpu/ops/merge_pallas.py"


@rule(
    "vmem-scratch-ownership",
    "pltpu.VMEM scratch allocation outside ops/merge_pallas.py — new "
    "kernels must extend the owned spec builders so the byte budgets "
    "(rr_align_scratch_bytes et al.) keep covering every allocation",
    fixture="vmem_ownership.py",
    fixture_at="gossipfs_tpu/ops/_lint_fixture.py",
)
def check_vmem(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files("gossipfs_tpu"):
        if rel == _VMEM_OWNER:
            continue
        for node in ast.walk(index.tree(rel)):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute) \
                    and node.func.attr == "VMEM":
                out.append(Finding(
                    "vmem-scratch-ownership", rel, node.lineno,
                    "VMEM scratch allocated outside ops/merge_pallas.py "
                    "— the scratch-budget reconciliation "
                    "(rr-scratch-budget probe) cannot see it",
                ))
    return out


# ---------------------------------------------------------------------------
# "n/a" vitals rendering — owner: gossipfs_tpu/obs/schema.py
# ---------------------------------------------------------------------------

_NA_OWNER = "gossipfs_tpu/obs/schema.py"


@rule(
    "na-render-ownership",
    "the n/a-not-0 vitals rule has one renderer (obs.schema.render_vitals"
    " / obs.schema.na); a literal \"n/a\" anywhere else is a re-derived "
    "copy that can drift into fabricating clean zeros",
    fixture="na_ownership.py",
    fixture_at="gossipfs_tpu/shim/_lint_fixture.py",
)
def check_na(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        if rel == _NA_OWNER:
            continue
        for node in ast.walk(index.tree(rel)):
            if const_str(node) == "n/a":
                out.append(Finding(
                    "na-render-ownership", rel, node.lineno,
                    "literal \"n/a\" rendered outside obs/schema.py — "
                    "use obs.schema.na(value) / render_vitals so the "
                    "absent-not-zero convention stays one-owner",
                ))
    return out
