"""jit-hygiene for ``core/`` and ``ops/`` — the traced-code floor.

The tensor hot path (core/rounds.py scans, ops/ kernels) must stay a
pure device program: a host clock call inside a jitted function silently
freezes at trace time, a ``.item()``/``np.`` sync inside a scan body
serializes the whole scan, and a Python ``if`` on a traced value is a
TracerBoolConversionError at best and a trace-time constant-fold at
worst.  These are the "Date.now-class" bugs review keeps catching by
eye; the rules catch their shape mechanically.
"""

from __future__ import annotations

import ast

from gossipfs_tpu.analysis.framework import (
    Finding,
    RepoIndex,
    dotted,
    rule,
)

_SCAN_DIRS = ("gossipfs_tpu/core", "gossipfs_tpu/ops")

# Host calls that have no business anywhere in the traced modules: the
# value they return is frozen into the jaxpr at trace time.
_HOST_PREFIXES = ("time.", "datetime.", "random.", "np.random.",
                  "numpy.random.")

# Additionally forbidden inside scan/loop bodies: each forces a device
# sync (or a host transfer) once per scan step.
_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_SYNC_CALLS = {"jax.device_get", "np.asarray", "np.array", "print",
               "breakpoint"}

# Attribute reads that are STATIC under tracing (shape metadata), so a
# Python branch on them is fine even when the base object is traced.
_STATIC_ATTRS = {"shape", "size", "ndim", "dtype", "aval", "sharding"}

_LOOP_FNS = {"lax.scan", "jax.lax.scan", "lax.fori_loop",
             "jax.lax.fori_loop", "lax.while_loop", "jax.lax.while_loop"}


def _host_call(node: ast.Call) -> str | None:
    name = dotted(node.func)
    if name is None:
        return None
    for pre in _HOST_PREFIXES:
        if name.startswith(pre):
            return name
    return None


def _local_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef)}


def _scan_bodies(tree: ast.Module) -> list[ast.FunctionDef]:
    """FunctionDefs passed by name to lax.scan / fori_loop / while_loop."""
    fns = _local_functions(tree)
    bodies = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted(node.func) in _LOOP_FNS:
            for arg in node.args[:2]:
                if isinstance(arg, ast.Name) and arg.id in fns:
                    bodies.append(fns[arg.id])
    return bodies


def _traced_names(fn: ast.FunctionDef) -> set[str]:
    """The body's parameters plus first-level tuple-unpack aliases of
    them (``hb, age = carry``) — the names that hold tracers."""
    params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
    traced = set(params)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            src_names = {v.id for v in ast.walk(val)
                         if isinstance(v, ast.Name)}
            if src_names & params and isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in ast.walk(tgt):
                    if isinstance(elt, ast.Name):
                        traced.add(elt.id)
    return traced


def _branches_on_traced(test: ast.AST, traced: set[str]) -> bool:
    """A traced name used in a branch test other than through static
    shape metadata or an ``is (not) None`` identity check.  Exemptions
    are PER OCCURRENCE, not per name or per test: in
    ``if carry is None or carry > 0`` only the identity occurrence is
    exempt — the ``carry > 0`` clause still flags, since that raw bool
    conversion is exactly the TracerBoolConversionError class the rule
    exists for."""
    exempt_occurrences: set[int] = set()
    for node in ast.walk(test):
        under = None
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            under = node  # arm selection on an optional
        elif isinstance(node, ast.Attribute) \
                and node.attr in _STATIC_ATTRS:
            under = node  # static-metadata subtree: x.shape[0] etc.
        if under is not None:
            exempt_occurrences |= {id(sub) for sub in ast.walk(under)
                                   if isinstance(sub, ast.Name)}
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in traced \
                and id(node) not in exempt_occurrences:
            return True
    return False


@rule(
    "jit-hygiene",
    "core/ and ops/ stay a pure device program: no host clock/crng "
    "calls anywhere, and no sync calls (.item/np./print) or Python "
    "branches on traced values inside lax.scan/fori/while bodies",
    fixture="jit_hygiene.py",
    fixture_at="gossipfs_tpu/core/_lint_fixture.py",
)
def check_jit_hygiene(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files(*_SCAN_DIRS):
        tree = index.tree(rel)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                host = _host_call(node)
                if host is not None:
                    out.append(Finding(
                        "jit-hygiene", rel, node.lineno,
                        f"host call {host}() in a traced module — its "
                        "value freezes into the jaxpr at trace time",
                    ))
        for body in _scan_bodies(tree):
            traced = _traced_names(body)
            for node in ast.walk(body):
                if isinstance(node, ast.Call):
                    name = dotted(node.func)
                    is_sync = (name in _SYNC_CALLS) or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_ATTRS)
                    if is_sync:
                        out.append(Finding(
                            "jit-hygiene", rel, node.lineno,
                            f"sync/host call {name or node.func.attr}() "
                            f"inside scan body {body.name}() — one "
                            "device round-trip per scan step",
                        ))
                if isinstance(node, (ast.If, ast.While)) \
                        and _branches_on_traced(node.test, traced):
                    out.append(Finding(
                        "jit-hygiene", rel, node.lineno,
                        f"Python branch on a traced value inside scan "
                        f"body {body.name}() — use jnp.where/lax.cond "
                        "(shape metadata like .shape/.size is fine)",
                    ))
    return out
