"""Config-gate documentation consistency.

``SimConfig.__post_init__`` and ``core/rounds._use_rr`` are the repo's
capability gates: every ``raise ValueError`` / eligibility test there
encodes a hardware or protocol constraint (VMEM budgets, dtype windows,
dissemination-mode requirements).  BASELINE.md carries the human-facing
capability story; this rule pins the two together — every config FIELD a
gate tests must have a row in BASELINE.md's config-gate matrix (a table
row starting with the backticked field name), so a new gate cannot ship
undocumented and a renamed field cannot leave a stale row behind
silently.
"""

from __future__ import annotations

import ast
import re

from gossipfs_tpu.analysis.framework import Finding, RepoIndex, rule

_CONFIG = "gossipfs_tpu/config.py"
_ROUNDS = "gossipfs_tpu/core/rounds.py"
_BASELINE = "BASELINE.md"

# The matrix is the region from its bold marker to the next bold
# marker / heading — rows in OTHER tables (scenario matrix, capability
# matrices) must not satisfy the documentation requirement, or any
# field name mentioned anywhere would count as documented.
_MATRIX_MARKER = "**Config-gate matrix**"
_MATRIX_END = re.compile(r"^(\*\*|#)", re.MULTILINE)
_DOC_ROW = re.compile(r"^\s*\|\s*`([a-z_]+)`", re.MULTILINE)


def _documented_fields(baseline_text: str) -> set[str] | None:
    start = baseline_text.find(_MATRIX_MARKER)
    if start < 0:
        return None
    body = baseline_text[start + len(_MATRIX_MARKER):]
    end = _MATRIX_END.search(body)
    if end is not None:
        body = body[:end.start()]
    return set(_DOC_ROW.findall(body))


def _config_fields(tree: ast.Module) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SimConfig":
            return {
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return set()


def _attrs_of(node: ast.AST, base: str) -> set[str]:
    """Attribute names read off ``<base>.<attr>`` within the node."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and isinstance(sub.value,
                                                         ast.Name) \
                and sub.value.id == base:
            out.add(sub.attr)
    return out


def _gated_fields(fn: ast.AST, base: str) -> dict[str, int]:
    """Fields referenced by an If test whose body raises (post_init
    gates) or by a boolean-return eligibility test (_use_rr): maps
    field -> first gating line."""
    gated: dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.If) and any(
                isinstance(s, ast.Raise) for s in ast.walk(node)):
            for attr in _attrs_of(node.test, base):
                gated.setdefault(attr, node.lineno)
        if isinstance(node, (ast.Return, ast.BoolOp, ast.If)) \
                and base == "config":
            # _use_rr gates by returning False, not raising — every
            # config attribute it consults is a capability input
            for attr in _attrs_of(node, base):
                gated.setdefault(attr, getattr(node, "lineno", 1))
    return gated


@rule(
    "config-gate-docs",
    "every config field tested by a capability gate "
    "(SimConfig.__post_init__ raise sites, core/rounds._use_rr) has a "
    "documented row (| `field` ...) in BASELINE.md's config-gate matrix",
    fixture="config_gate_docs.py",
    fixture_at="gossipfs_tpu/config.py",
)
def check_config_gates(index: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    documented = _documented_fields(index.source(_BASELINE))
    if documented is None:
        return [Finding(
            "config-gate-docs", _BASELINE, 1,
            f"BASELINE.md has no {_MATRIX_MARKER} section — the gate "
            "documentation rule went blind",
        )]

    cfg_tree = index.tree(_CONFIG)
    fields = _config_fields(cfg_tree)
    if not fields:
        return [Finding("config-gate-docs", _CONFIG, 1,
                        "SimConfig class not found — the gate rule went "
                        "blind")]
    post_init = None
    for node in ast.walk(cfg_tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "__post_init__":
            post_init = node
            break
    if post_init is None:
        return [Finding("config-gate-docs", _CONFIG, 1,
                        "SimConfig.__post_init__ not found — the gate "
                        "rule went blind")]
    gates = {f: (_CONFIG, ln)
             for f, ln in _gated_fields(post_init, "self").items()
             if f in fields}

    if index.exists(_ROUNDS):
        for node in ast.walk(index.tree(_ROUNDS)):
            if isinstance(node, ast.FunctionDef) and node.name == "_use_rr":
                for f, ln in _gated_fields(node, "config").items():
                    if f in fields:
                        gates.setdefault(f, (_ROUNDS, ln))

    for f in sorted(gates):
        if f not in documented:
            rel, ln = gates[f]
            out.append(Finding(
                "config-gate-docs", rel, ln,
                f"capability gate tests `{f}` but BASELINE.md's "
                f"config-gate matrix has no row for `{f}` — document "
                "the constraint (BASELINE.md, Static analysis section)",
            ))
    return out
