"""gossipfs-lint: the repo-wide invariant analyzer.

One AST-based framework (stdlib ``ast`` only for the default rules)
absorbing the lint checks that used to live as ad-hoc greps in three
test modules, plus the checks every review re-derived by eye:

* single-ownership of owned expressions (quorum math, backoff
  schedules, obs line parsing, quantile rollups, VMEM scratch, the
  ``n/a`` rendering) — ``rules_ownership``
* obs-schema coverage of every metric field and log site — ``rules_obs``
* the native engine's event kinds / vitals fields vs the schema, across
  the language boundary — ``rules_native``
* config capability gates documented in BASELINE.md — ``rules_config``
* jit-hygiene for ``core/``/``ops/`` — ``rules_jit``
* asyncio-hygiene for the socket engine — ``rules_asyncio``
* the rr scratch-budget reconciliation (probe) — ``probes``
* gossipfs-spec: the machine-readable protocol contract
  (``protocol_spec``) statically diffed against all three engines —
  transitions, rate limits, dissemination bounds, @gfs annotations in
  the native engine, and the scan-carry arity seam — ``rules_spec``

Run it: ``python tools/lint.py`` (exit 1 on any finding), or
``run_rules()`` from tests.  Every rule has a committed fixture under
``tests/fixtures/lint/`` proving it fires (``tests/test_analysis.py``).
"""

from gossipfs_tpu.analysis.framework import (  # noqa: F401
    REGISTRY,
    Finding,
    RepoIndex,
    Rule,
    rule,
    run_rules,
)

# Importing the rule modules populates REGISTRY.
from gossipfs_tpu.analysis import (  # noqa: E402,F401
    probes,
    rules_asyncio,
    rules_config,
    rules_conformance,
    rules_jit,
    rules_native,
    rules_obs,
    rules_ownership,
    rules_spec,
)

__all__ = ["REGISTRY", "Finding", "RepoIndex", "Rule", "rule", "run_rules"]
