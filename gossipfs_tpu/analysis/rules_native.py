"""Cross-language obs ownership — the native engine's kind strings.

Round 16 made the C++ epoll engine (``native/engine.cc``) an obs-plane
producer: it buffers structured event lines that
``gossipfs_tpu/native.py`` renders through the ``FlightRecorder``, and
serves a uniform-vitals text over ``gfs_vitals``.  The schema's single
ownership (``obs/schema.py``) must hold ACROSS the language boundary —
a kind string minted in C++ that EVENT_KINDS doesn't know would write
streams ``obs.recorder.load_stream`` silently drops rows from, and a
vitals field outside VITALS_FIELDS would bypass the n/a-not-0 rendering
contract.

Pure text scan over the one engine source: every ``ObsEmit("<kind>"``
literal must be an ``EVENT_KINDS`` key, and every
``AppendVital(os, "<field>"`` literal a ``VITALS_FIELDS`` member (both
literal-evaluated from the schema module, like the other obs rules).
The emission helpers are the engine's ONLY writers by construction —
the rule also fails if it finds no sites at all (the extractor drifted
from the emission idiom).
"""

from __future__ import annotations

import re

from gossipfs_tpu.analysis.framework import (
    Finding,
    RepoIndex,
    literal_dict,
    rule,
)

_ENGINE = "native/engine.cc"
_SCHEMA = "gossipfs_tpu/obs/schema.py"

# ObsEmit("<kind>", ...) — both the (kind, observer, subject, detail)
# and the (kind, observer, subject_addr, detail) overloads
_OBS_RE = re.compile(r'ObsEmit\(\s*"([a-z_]+)"')
# AppendVital(os, "<field>", ...)
_VITAL_RE = re.compile(r'AppendVital\([^,()]*,\s*"([a-z_]+)"')


def _line_of(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


@rule(
    "native-obs-kinds",
    "every event-kind string literal the native engine emits "
    "(ObsEmit sites in native/engine.cc) must be an obs.schema "
    "EVENT_KINDS kind, and every gfs_vitals field (AppendVital sites) "
    "a VITALS_FIELDS member — schema ownership enforced across the "
    "language boundary",
    fixture="native_obs_kinds.cc",
    fixture_at="native/engine.cc",
)
def check_native_obs_kinds(index: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    tree = index.tree(_SCHEMA)
    kinds = literal_dict(tree, "EVENT_KINDS")
    vitals = literal_dict(tree, "VITALS_FIELDS")
    if kinds is None:
        out.append(Finding(
            "native-obs-kinds", _SCHEMA, 1,
            "EVENT_KINDS is no longer a literal dict — the native "
            "kind-ownership rule cannot statically read it"))
        kinds = {}
    if vitals is None:
        out.append(Finding(
            "native-obs-kinds", _SCHEMA, 1,
            "VITALS_FIELDS is no longer a literal tuple — the native "
            "vitals-ownership rule cannot statically read it"))
        vitals = ()
    if not index.exists(_ENGINE):
        out.append(Finding(
            "native-obs-kinds", _ENGINE, 1,
            "native/engine.cc not found — the native obs rule went "
            "blind"))
        return out
    src = index.source(_ENGINE)
    obs_sites = list(_OBS_RE.finditer(src))
    vital_sites = list(_VITAL_RE.finditer(src))
    if not obs_sites or not vital_sites:
        out.append(Finding(
            "native-obs-kinds", _ENGINE, 1,
            "no ObsEmit/AppendVital sites found (the extractor drifted "
            "from the engine's emission idiom?)"))
    for m in obs_sites:
        if m.group(1) not in kinds:
            out.append(Finding(
                "native-obs-kinds", _ENGINE, _line_of(src, m.start()),
                f"native engine emits kind {m.group(1)!r} which is not "
                "an obs.schema.EVENT_KINDS kind — streams would "
                "silently drop these rows at load_stream"))
    vital_set = set(vitals if isinstance(vitals, (tuple, list)) else ())
    for m in vital_sites:
        if m.group(1) not in vital_set:
            out.append(Finding(
                "native-obs-kinds", _ENGINE, _line_of(src, m.start()),
                f"gfs_vitals serves field {m.group(1)!r} which is not "
                "in obs.schema.VITALS_FIELDS — the uniform-vitals "
                "surface would drift from the schema"))
    return out
