"""Probe rules — invariant checks that must import the package.

The AST rules in the sibling modules run on source alone; the checks
here execute code (jax in interpret mode) to reconcile a *formula*
against the *artifact it budgets*.  They register in the same registry
(kind="probe"): ``tools/lint.py --probe`` runs them, and the thin
test wrappers keep them on the tier-1 fast lane.
"""

from __future__ import annotations

import itertools
import math

from gossipfs_tpu.analysis.framework import Finding, RepoIndex, rule

_MP = "gossipfs_tpu/ops/merge_pallas.py"

# Each reconciliation run must actually re-enter pl.pallas_call (the spy
# captures nothing on a jit-cache hit).  ``window`` is a STATIC argument
# of resident_round_blocked that the scratch geometry never reads, so a
# unique value per call scopes the cache miss to this one entry point —
# a process-wide jax.clear_caches() here would force every other test's
# already-traced scan to recompile.
_CACHE_BUST = itertools.count()


@rule(
    "rr-scratch-budget",
    "rr_align_scratch_bytes must equal the kernel's ACTUAL pltpu scratch "
    "allocations (spec list verbatim in the pallas_call, byte sums "
    "equal), the flags block must bill at rr_flags_bytes, and the "
    "rotated row-budget acceptance shapes must hold (probe: runs the "
    "interpret kernel)",
    kind="probe",
    fixture="rr_scratch_budget.py",
    fixture_at=None,  # probe rules trigger via their _fixture_check hook
)
def check_rr_scratch_budget(index: RepoIndex) -> list[Finding]:
    return _reconcile()


def _reconcile(spec_drop: int = 0) -> list[Finding]:
    """The round-9 scratch-budget reconciliation, as findings.

    ``spec_drop`` exists for the analyzer's own fixture test: dropping
    N trailing specs from the budget list simulates the drift this
    probe exists to catch (a kernel allocation the budget stops
    charging), without touching the real kernel.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    out: list[Finding] = []
    n, nloc, fanout, align, c_blk = 2048, 512, 16, 8, 512
    window = 126 - next(_CACHE_BUST)  # unique static arg: see _CACHE_BUST

    # random packed-lane inputs at the shard shape where the row budget
    # binds (mirrors tests/test_merge_pallas._rr_tall_skinny_inputs)
    nc, cs = nloc // c_blk, c_blk // mp.LANE
    ks = jax.random.split(jax.random.PRNGKey(29), 5)
    hb = jax.random.randint(ks[0], (nc, n, cs, mp.LANE), -128, 127,
                            jnp.int8)
    age = jax.random.randint(ks[1], (nc, n, cs, mp.LANE), 1, 40, jnp.int32)
    st = jax.random.randint(ks[2], (nc, n, cs, mp.LANE), 0, 3, jnp.int32)
    asl = mp.pack_age_status(age, st)
    fl = jnp.where(jax.random.uniform(ks[3], (n,)) > 0.1, 5, 4).astype(
        jnp.int8)
    flags = fl.reshape(n // mp.LANE, mp.LANE)
    sa = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    sb = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    g = jnp.full((nc, cs, mp.LANE), -120, jnp.int32)
    bases = (jax.random.randint(ks[4], (n,), 0, n // align, jnp.int32)
             * align).reshape(n, 1)

    captured: dict = {}
    real = pl.pallas_call

    def spy(kernel, **kwargs):
        captured["scratch"] = kwargs.get("scratch_shapes")
        captured["in_specs"] = kwargs.get("in_specs")
        return real(kernel, **kwargs)

    mp.pl.pallas_call = spy
    try:
        mp.resident_round_blocked(
            bases, hb, asl, flags, sa, sb, g,
            fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
            failed=int(FAILED), age_clamp=AGE_CLAMP, window=window,
            t_fail=5, t_cooldown=12, block_r=128, arc_align=align,
            resident=True, interpret=True)
    finally:
        mp.pl.pallas_call = real

    def key(s):
        return (tuple(s.shape), jnp.dtype(s.dtype))

    ch = mp.rr_view_chunk(n, c_blk, resident=True, arc_align=align)
    specs = mp.rr_align_scratch_specs(n, fanout, c_blk, align, chunk=ch)
    if spec_drop:
        specs = specs[:-spec_drop]
    alloc = []
    for s in captured.get("scratch") or ():
        try:
            alloc.append(key(s))
        except TypeError:
            pass  # DMA semaphore specs carry no numeric dtype
    for s in specs:
        if key(s) not in alloc:
            out.append(Finding(
                "rr-scratch-budget", _MP, 1,
                f"budget charges scratch {key(s)} but the kernel does "
                "not allocate it — rr_align_scratch_specs drifted from "
                "the pallas_call",
            ))
    spec_bytes = sum(math.prod(s.shape) * jnp.dtype(s.dtype).itemsize
                     for s in specs)
    budget = mp.rr_align_scratch_bytes(n, fanout, c_blk, align, chunk=ch)
    if spec_bytes != budget:
        out.append(Finding(
            "rr-scratch-budget", _MP, 1,
            f"spec-list bytes {spec_bytes} != rr_align_scratch_bytes "
            f"{budget} — the row budget no longer sums the kernel's "
            "actual allocations",
        ))
    # ring-rotated: ONLY the int8 W buffer scales with rows — the bf16
    # ring + head are fixed-size (chunk + halo geometry)
    nb, nw = n // align, fanout // align
    expect = nb * c_blk + ((ch // align) + 2 * (nw - 1)) * c_blk * 2
    if not spec_drop and spec_bytes != expect:
        out.append(Finding(
            "rr-scratch-budget", _MP, 1,
            f"rotated-layout closed form {expect} B != spec bytes "
            f"{spec_bytes} — a new allocation started scaling with rows",
        ))
    # flags input block: LANE-compacted [N/LANE, LANE], billed at
    # rr_flags_bytes
    fspec = (captured.get("in_specs") or [None, None, None])[2]
    if fspec is None or tuple(fspec.block_shape) != (n // mp.LANE, mp.LANE):
        out.append(Finding(
            "rr-scratch-budget", _MP, 1,
            "flags input block is not the LANE-compacted [N/LANE, LANE] "
            "layout the budget charges",
        ))
    if mp.rr_flags_bytes(n, c_blk, block_r=128, resident=True,
                         arc_align=align) != n:
        out.append(Finding(
            "rr-scratch-budget", _MP, 1,
            "rr_flags_bytes no longer bills the compact layout at "
            "1 B/row",
        ))
    # acceptance: the rotated layouts admit the capacity-ladder shapes
    # (>= 512k rows at c_blk=512) and still reject the round-5 layouts
    for rows, want, kw in (
        (524288, True, {}),
        (786432, True, {}),
        (393216, False, {"rotate": False}),
        (262144, True, {"block_c": 2048}),
    ):
        block_c = kw.pop("block_c", 512)
        got = mp.rr_supported(rows, 24, block_c, 16384, arc_align=8,
                              block_r=512, **kw)
        if got != want:
            out.append(Finding(
                "rr-scratch-budget", _MP, 1,
                f"rr_supported({rows}, block_c={block_c}, "
                f"{kw or 'rotate=True'}) = {got}, expected {want} — the "
                "row-budget acceptance envelope moved",
            ))
    return out


def fixture_findings() -> list[Finding]:
    """The committed trigger case for the probe (tests/test_analysis.py):
    a budget list missing the kernel's last allocation must fire."""
    return _reconcile(spec_drop=1)
