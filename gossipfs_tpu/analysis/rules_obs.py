"""Obs-schema coverage rules — the generalized tests/test_obs.py LINT maps.

Every ``RoundMetrics``/``MetricsCarry`` field and every deploy/cosim log
site must map into the event schema (``obs/schema.py``) or sit in an
explicit unexported list with a reason — adding a metric or a log site
without deciding its observability story is a finding.  These are the
round-10 lint maps, absorbed into the registry: the old tests become
thin wrappers, and the CLI enforces the same contract outside pytest.

All checks are pure-AST: the NamedTuple fields come from the class
definitions' annotations and the schema maps from their literal-dict
assignments, so the rules run without importing jax (or the package).
"""

from __future__ import annotations

import ast

from gossipfs_tpu.analysis.framework import (
    Finding,
    RepoIndex,
    const_str,
    literal_dict,
    namedtuple_fields,
    rule,
)

_ROUNDS = "gossipfs_tpu/core/rounds.py"
_SCHEMA = "gossipfs_tpu/obs/schema.py"
_NODE = "gossipfs_tpu/deploy/node.py"
_COSIM = "gossipfs_tpu/cosim.py"


def _schema_maps(index: RepoIndex, names: tuple[str, ...],
                 rule_name: str) -> tuple[dict, list[Finding]]:
    """Literal-evaluate the named schema maps; a map that stopped being
    a literal dict is itself a finding (the rules would go blind)."""
    tree = index.tree(_SCHEMA)
    maps, out = {}, []
    for name in names:
        d = literal_dict(tree, name)
        if d is None:
            out.append(Finding(
                rule_name, _SCHEMA, 1,
                f"{name} is no longer a literal dict — the schema "
                "coverage rules cannot statically read it",
            ))
            d = {}
        maps[name] = d
    return maps, out


@rule(
    "obs-scan-coverage",
    "every RoundMetrics/MetricsCarry field maps to a schema event kind "
    "(obs.schema.SCAN_FIELD_MAP) or is explicitly unexported "
    "(SCAN_UNEXPORTED); mapped kinds must exist in EVENT_KINDS",
    fixture="obs_scan_coverage.py",
    fixture_at="gossipfs_tpu/core/rounds.py",
)
def check_scan_coverage(index: RepoIndex) -> list[Finding]:
    maps, out = _schema_maps(
        index, ("SCAN_FIELD_MAP", "SCAN_UNEXPORTED", "EVENT_KINDS"),
        "obs-scan-coverage")
    field_map, unexported, kinds = (maps["SCAN_FIELD_MAP"],
                                    maps["SCAN_UNEXPORTED"],
                                    maps["EVENT_KINDS"])
    tree = index.tree(_ROUNDS)
    for cls in ("RoundMetrics", "MetricsCarry"):
        fields = namedtuple_fields(tree, cls)
        if fields is None:
            out.append(Finding(
                "obs-scan-coverage", _ROUNDS, 1,
                f"{cls} NamedTuple definition not found — the scan-field "
                "coverage rule went blind",
            ))
            continue
        for f in fields:
            if f not in field_map and f not in unexported:
                out.append(Finding(
                    "obs-scan-coverage", _ROUNDS, 1,
                    f"{cls}.{f} is neither mapped to a schema event kind "
                    "(obs.schema.SCAN_FIELD_MAP) nor explicitly "
                    "unexported (SCAN_UNEXPORTED)",
                ))
    for f, kind in field_map.items():
        if kind not in kinds:
            out.append(Finding(
                "obs-scan-coverage", _SCHEMA, 1,
                f"SCAN_FIELD_MAP[{f!r}] -> {kind!r} is not an EVENT_KINDS "
                "kind",
            ))
    return out


def _node_log_sites(tree: ast.Module) -> list[tuple[str, int]]:
    """``self.log("<kind>", ...)`` call sites in deploy/node.py."""
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "log" and node.args:
            kind = const_str(node.args[0])
            if kind is not None:
                sites.append((kind, node.lineno))
    return sites


def _cosim_kind_sites(tree: ast.Module) -> list[tuple[str, int]]:
    """``kind="<kind>"`` keyword sites in cosim.py."""
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "kind":
                    kind = const_str(kw.value)
                    if kind is not None:
                        sites.append((kind, node.lineno))
    return sites


@rule(
    "obs-logsite-coverage",
    "every deploy-daemon log(\"<kind>\") site and every cosim "
    "kind=\"<kind>\" site maps into the schema (LOG_KIND_MAP), is a "
    "schema kind already, or is listed unexported with a reason",
    fixture="obs_logsite_coverage.py",
    fixture_at="gossipfs_tpu/cosim.py",
)
def check_logsite_coverage(index: RepoIndex) -> list[Finding]:
    maps, out = _schema_maps(
        index, ("LOG_KIND_MAP", "UNEXPORTED_LOG_KINDS", "EVENT_KINDS"),
        "obs-logsite-coverage")
    known = (set(maps["LOG_KIND_MAP"]) | set(maps["UNEXPORTED_LOG_KINDS"])
             | set(maps["EVENT_KINDS"]))
    for rel, extract in ((_NODE, _node_log_sites),
                         (_COSIM, _cosim_kind_sites)):
        sites = extract(index.tree(rel))
        if not sites:
            out.append(Finding(
                "obs-logsite-coverage", rel, 1,
                "no log sites found (the extractor drifted from the "
                "logging idiom?)",
            ))
        for kind, line in sites:
            if kind not in known:
                out.append(Finding(
                    "obs-logsite-coverage", rel, line,
                    f"log site kind {kind!r} bypasses the schema: add it "
                    "to obs.schema.LOG_KIND_MAP or UNEXPORTED_LOG_KINDS",
                ))
    for k, v in maps["LOG_KIND_MAP"].items():
        if v not in maps["EVENT_KINDS"]:
            out.append(Finding(
                "obs-logsite-coverage", _SCHEMA, 1,
                f"LOG_KIND_MAP[{k!r}] -> {v!r} is not an EVENT_KINDS kind",
            ))
    return out
