"""gossipfs-spec extractors: statically recover each engine's
implemented protocol and diff it against ``protocol_spec``.

One rule per drift class, each with a committed seeded-drift fixture
(tests/fixtures/lint/spec_*) asserted to fire it:

* ``spec-dissemination`` — the new-suspicion SUSPECT push must honor
  the contract's dissemination bound: under the campaign profile
  (``push == "random"``) subject + fanout sample, never an
  unconditional all-peers broadcast.  This is the rule that flagged
  the ENTRY-broadcast asymmetry at head (detector/udp.py broadcast to
  all peers where native bounded it — the red half of this PR's
  red->green evidence).
* ``spec-delta-dissemination`` — the delta-piggyback membership
  refresh (``protocol_spec.DELTA_GOSSIP``) must keep its entry
  selection rule in BOTH socket engines: changed-since-cursor entries
  most-recent-first, round-robin refresh of the stable tail, capped
  per datagram; the anti-entropy full-list cadence cluster-round
  aligned; the engine defaults byte-identical to the contract dict;
  and the ``anti_entropy_every < t_fail`` constraint enforced at
  construction (a refresh gap past the detection window manufactures
  false positives).
* ``spec-refute-rate-limit`` — both socket engines must rate-limit the
  REFUTE broadcast to once per period (compare-then-stamp on the
  last-refute clock).
* ``spec-transition-order`` — the tensor ``_tick`` must compute the
  SUSPECT->FAILED confirm mask from PRE-WRITE status, then write
  SUSPECT, then FAILED: an entry always spends >= 1 round SUSPECT
  before it can confirm.  Also holds the confirm-window formula to the
  contract's names (t_fail / t_suspect / lh_multiplier).
* ``spec-runtime-protocol`` — ``suspicion/runtime.py`` (the per-node
  reference semantics the socket engines mirror) must carry the full
  lifecycle verb set and the degraded / stretched-window formulas.
* ``spec-native-annotations`` — the C++ side, built from ``// @gfs:``
  annotations in engine.cc, cross-checked BOTH ways: every annotation
  must match a contract row, and every lifecycle ``ObsEmit`` kind must
  be dominated by a matching annotation — the round-11
  ``native-obs-kinds`` ownership pattern extended across semantics,
  not just names.
* ``spec-obs-kind-coverage`` — obs/schema.py ``LIFECYCLE_KINDS`` and
  the contract's emit kinds must be the SAME set (and every emit kind
  an ``EVENT_KINDS`` entry): a new lifecycle state cannot ship without
  a contract row.
* ``scan-carry-arity`` — the rr scan carry tuple, ``parallel/mesh.py``
  out_specs and the PackedDetector threading must agree in arity and
  field order (the seam-bug class the round-9 suspect-count side
  output had to hand-patch).
"""

from __future__ import annotations

import ast
import re

from . import protocol_spec as spec
from .framework import Finding, dotted, namedtuple_fields, rule

_UDP = "gossipfs_tpu/detector/udp.py"
_RUNTIME = "gossipfs_tpu/suspicion/runtime.py"
_ROUNDS = "gossipfs_tpu/core/rounds.py"
_MESH = "gossipfs_tpu/parallel/mesh.py"
_SIM = "gossipfs_tpu/detector/sim.py"
_ENGINE = "native/engine.cc"
_SCHEMA = "gossipfs_tpu/obs/schema.py"


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _func(tree: ast.AST, name: str):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _attrs_in(node: ast.AST) -> set[str]:
    return {n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)}


def _line_of(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


def _assign_line(tree: ast.Module, name: str) -> int:
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target.id]
        if name in targets:
            return node.lineno
    return 1


def _literal_tuple(tree: ast.Module, name: str):
    """Module-level ``NAME = (...)`` literal, or None."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets, value = [node.target.id], node.value
        else:
            continue
        if name in targets and value is not None:
            try:
                return ast.literal_eval(value)
            except ValueError:
                return None
    return None


def _compares_push_random(test: ast.AST) -> bool:
    """True for a ``<x>.push == "random"`` (or reversed) comparison."""
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        names = set()
        consts = set()
        for s in sides:
            if isinstance(s, ast.Attribute):
                names.add(s.attr)
            elif isinstance(s, ast.Name):
                names.add(s.id)
            elif isinstance(s, ast.Constant) and isinstance(s.value, str):
                consts.add(s.value)
        if "push" in names and "random" in consts:
            return True
    return False


# ---------------------------------------------------------------------------
# spec-dissemination
# ---------------------------------------------------------------------------

@rule(
    "spec-dissemination",
    "new-suspicion SUSPECT dissemination must honor the contract bound: "
    "campaign profile (push=random) = subject + fanout sample, never an "
    "unconditional all-peers broadcast (protocol_spec.DISSEMINATION)",
    fixture="spec_udp_widened.py",
    fixture_at=_UDP,
)
def spec_dissemination(index) -> list[Finding]:
    findings: list[Finding] = []
    row = spec.dissemination_row("new_suspect", "campaign")
    # -- udp engine: the rt.suspect(...) branch of UdpNode.tick is the
    # one place a NEW suspicion is disseminated
    tree = index.tree(_UDP)
    fn = _func(tree, "tick")
    branch = None
    if fn is not None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            for call in ast.walk(node.test):
                if isinstance(call, ast.Call):
                    d = dotted(call.func)
                    if d is not None and d.endswith(".suspect"):
                        branch = node
                        break
            if branch is not None:
                break
    if branch is None:
        findings.append(Finding(
            "spec-dissemination", _UDP, 1,
            "extractor went blind: UdpNode.tick's rt.suspect(...) branch "
            "not found — the analyzer cannot see the new-suspicion "
            "dissemination it exists to bound",
        ))
    else:
        bounded = False
        for sub in ast.walk(branch):
            if not isinstance(sub, ast.If) \
                    or not _compares_push_random(sub.test):
                continue
            gated_attrs: set[str] = set()
            gated_calls: set[str] = set()
            for stmt in sub.body:
                gated_attrs |= _attrs_in(stmt)
                for c in ast.walk(stmt):
                    if isinstance(c, ast.Call):
                        d = dotted(c.func)
                        if d is not None:
                            gated_calls.add(d.rsplit(".", 1)[-1])
            if "fanout" in gated_attrs and "sample" in gated_calls:
                bounded = True
        if not bounded:
            findings.append(Finding(
                "spec-dissemination", _UDP, branch.lineno,
                "new-suspicion SUSPECT dissemination is not bounded under "
                f"the campaign profile: the contract row requires "
                f"'{row.bound}' there (a push == \"random\" gate sending "
                "to the subject plus an rng.sample(..., fanout) draw) — "
                "found an unconditional broadcast, O(suspects x N) per "
                "round at cohort sizes",
            ))
    # -- native engine: the newly_suspect loop must carry the same gate
    src = index.source(_ENGINE)
    pos = src.find("newly_suspect)")
    if pos < 0:
        findings.append(Finding(
            "spec-dissemination", _ENGINE, 1,
            "extractor went blind: the newly_suspect dissemination loop "
            "was not found in the native Tick",
        ))
    else:
        window = src[pos:pos + 2500]
        if "push_random" not in window or "fanout" not in window:
            findings.append(Finding(
                "spec-dissemination", _ENGINE, _line_of(src, pos),
                "native newly-suspect dissemination lost its campaign "
                f"bound: the contract row requires '{row.bound}' behind "
                "a push_random gate with a fanout-sized sample",
            ))
    return findings


# ---------------------------------------------------------------------------
# spec-delta-dissemination
# ---------------------------------------------------------------------------

_CODEC_H = "native/codec.h"


def _ctor_defaults(tree: ast.Module, cls_name: str):
    """{param: literal default} for ``cls_name.__init__``, or None."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == cls_name):
            continue
        for f in node.body:
            if not (isinstance(f, ast.FunctionDef)
                    and f.name == "__init__"):
                continue
            out: dict[str, object] = {}
            a = f.args
            pos = a.args[len(a.args) - len(a.defaults):]
            for arg, d in list(zip(pos, a.defaults)) + [
                    (k, v) for k, v in zip(a.kwonlyargs, a.kw_defaults)
                    if v is not None]:
                try:
                    out[arg.arg] = ast.literal_eval(d)
                except ValueError:
                    pass
            return out, f
    return None, None


@rule(
    "spec-delta-dissemination",
    "the delta-piggyback membership refresh must match "
    "protocol_spec.DELTA_GOSSIP in both socket engines: changed-first "
    "+ rr-tail + capped entry selection, cluster-round-aligned "
    "anti-entropy cadence, contract-identical defaults, and the "
    "anti_entropy_every < t_fail constraint enforced at construction",
    fixture="spec_delta_dissemination.py",
    fixture_at=_UDP,
)
def spec_delta_dissemination(index) -> list[Finding]:
    findings: list[Finding] = []
    dg = spec.DELTA_GOSSIP
    # -- udp engine: wire mark literal
    tree = index.tree(_UDP)
    mark = _literal_tuple(tree, "DELTA_MARK")
    if mark != dg["wire_mark"]:
        findings.append(Finding(
            "spec-delta-dissemination", _UDP,
            _assign_line(tree, "DELTA_MARK"),
            f"udp DELTA_MARK is {mark!r} where the contract wire mark "
            f"is {dg['wire_mark']!r} — delta frames would stop "
            "dispatching through the hardened merge on one side",
        ))
    # -- udp engine: the entry-selection rule lives in _encode_delta
    fn = _func(tree, "_encode_delta")
    if fn is None:
        findings.append(Finding(
            "spec-delta-dissemination", _UDP, 1,
            "extractor went blind: UdpNode._encode_delta not found — "
            "the delta entry-selection rule the contract bounds is "
            "invisible",
        ))
    else:
        attrs = _attrs_in(fn)
        recent_first = any(
            isinstance(c, ast.Call)
            and (dotted(c.func) or "").endswith(".sort")
            and any(kw.arg == "reverse"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in c.keywords)
            for c in ast.walk(fn))
        missing = []
        if "_sent_ver" not in attrs:
            missing.append("per-peer change cursor (_sent_ver)")
        if "ver" not in attrs:
            missing.append("monotone entry version (ver)")
        if not recent_first:
            missing.append("most-recent-first sort (reverse=True)")
        if "_refresh_pos" not in attrs:
            missing.append("round-robin stable-tail refresh "
                           "(_refresh_pos)")
        if "delta_entries" not in attrs:
            missing.append("per-datagram cap (delta_entries)")
        if missing:
            findings.append(Finding(
                "spec-delta-dissemination", _UDP, fn.lineno,
                "udp _encode_delta drifted from the contract selection "
                f"rule '{dg['bound']}' — lost: " + "; ".join(missing),
            ))
    # -- udp engine: anti-entropy cadence in tick (cluster-round mod)
    fn = _func(tree, "tick")
    cadence = fn is not None and any(
        isinstance(node, ast.Compare)
        and isinstance(node.left, ast.BinOp)
        and isinstance(node.left.op, ast.Mod)
        and {"rounds", "anti_entropy_every"} <= _attrs_in(node)
        for node in ast.walk(fn))
    if not cadence:
        findings.append(Finding(
            "spec-delta-dissemination", _UDP,
            fn.lineno if fn is not None else 1,
            "udp tick lost the cluster-round-aligned anti-entropy "
            "cadence (rounds % anti_entropy_every == 0 pushing the "
            "FULL list) — a lost delta could wedge convergence forever",
        ))
    # -- udp engine: defaults + construction constraint
    defaults, init = _ctor_defaults(tree, "UdpCluster")
    if defaults is None:
        findings.append(Finding(
            "spec-delta-dissemination", _UDP, 1,
            "extractor went blind: UdpCluster.__init__ not found — the "
            "delta knob defaults cannot be diffed against the contract",
        ))
    else:
        for knob, key in (("delta_entries", "max_entries"),
                          ("anti_entropy_every", "anti_entropy_every")):
            if defaults.get(knob) != dg[key]:
                findings.append(Finding(
                    "spec-delta-dissemination", _UDP, init.lineno,
                    f"udp default {knob}={defaults.get(knob)!r} drifted "
                    f"from the contract's {key}={dg[key]} — the two "
                    "socket engines would ship different wire shapes "
                    "under identical case configs",
                ))
        guarded = any(
            isinstance(sub, ast.If)
            and {"anti_entropy_every", "t_fail"} <= {
                n.id for n in ast.walk(sub.test)
                if isinstance(n, ast.Name)}
            and any(isinstance(s, ast.Raise) for s in sub.body)
            for sub in ast.walk(init))
        if not guarded:
            findings.append(Finding(
                "spec-delta-dissemination", _UDP, init.lineno,
                f"udp UdpCluster dropped the '{dg['constraint']}' "
                "construction guard — an anti-entropy gap past the "
                "detection window manufactures false positives",
            ))
    # -- native engine: annotated cadence + selection tokens
    src = index.source(_ENGINE)
    pos = src.find("membership_refresh profile=delta")
    if pos < 0:
        findings.append(Finding(
            "spec-delta-dissemination", _ENGINE, 1,
            "extractor went blind: the @gfs:dissemination "
            "membership_refresh annotation is gone from the native Tick",
        ))
    else:
        window = src[pos:pos + 2000]
        if "anti_entropy_every" not in window \
                or "PushRefresh" not in window:
            findings.append(Finding(
                "spec-delta-dissemination", _ENGINE, _line_of(src, pos),
                "native Tick's annotated delta push lost its shape: the "
                "annotation must dominate the anti_entropy_every cadence "
                "and the PushRefresh per-peer selection call",
            ))
    for knob, key in (("delta_entries", "max_entries"),
                      ("anti_entropy_every", "anti_entropy_every")):
        m = re.search(rf"int\s+{knob}\s*=\s*(\d+)\s*;", src)
        if m is None or int(m.group(1)) != dg[key]:
            findings.append(Finding(
                "spec-delta-dissemination", _ENGINE,
                _line_of(src, m.start()) if m else 1,
                f"native default {knob} drifted from the contract's "
                f"{key}={dg[key]}",
            ))
    if not re.search(
            r"delta\s*&&\s*cfg_\.anti_entropy_every\s*>=\s*cfg_\.t_fail",
            src):
        findings.append(Finding(
            "spec-delta-dissemination", _ENGINE, 1,
            f"native gfs_configure dropped the '{dg['constraint']}' "
            "reject — the knob combination that manufactures false "
            "positives must not start loops",
        ))
    csrc = index.source(_CODEC_H)
    if f'kDeltaMark[] = "{dg["wire_mark"]}"' not in csrc:
        findings.append(Finding(
            "spec-delta-dissemination", _CODEC_H, 1,
            f"native kDeltaMark no longer equals the contract wire "
            f"mark {dg['wire_mark']!r}",
        ))
    return findings


# ---------------------------------------------------------------------------
# spec-refute-rate-limit
# ---------------------------------------------------------------------------

@rule(
    "spec-refute-rate-limit",
    "the REFUTE broadcast must be rate-limited to once per heartbeat "
    "period in both socket engines (protocol_spec.RATE_LIMITS "
    "refute_broadcast: compare-then-stamp on the last-refute clock)",
    fixture="spec_refute_rate_limit.py",
    fixture_at=_UDP,
)
def spec_refute_rate_limit(index) -> list[Finding]:
    findings: list[Finding] = []
    limit = spec.rate_limit("refute_broadcast")
    # -- udp engine: _on_suspect must early-return inside the period and
    # stamp the clock before bumping/broadcasting
    tree = index.tree(_UDP)
    fn = _func(tree, "_on_suspect")
    if fn is None:
        findings.append(Finding(
            "spec-refute-rate-limit", _UDP, 1,
            "extractor went blind: UdpNode._on_suspect not found — the "
            "analyzer cannot see the refute path it rate-limits",
        ))
    else:
        guarded = any(
            isinstance(sub, ast.If)
            and "_last_refute_t" in _attrs_in(sub.test)
            and "period" in _attrs_in(sub.test)
            and any(isinstance(s, ast.Return) for s in sub.body)
            for sub in ast.walk(fn)
        )
        stamped = any(
            isinstance(sub, ast.Assign)
            and any(
                isinstance(t, ast.Attribute) and t.attr == "_last_refute_t"
                for t in sub.targets
            )
            for sub in ast.walk(fn)
        )
        if not (guarded and stamped):
            findings.append(Finding(
                "spec-refute-rate-limit", _UDP, fn.lineno,
                f"udp _on_suspect dropped the refute rate limit "
                f"({limit.window}): it must compare now against "
                "self._last_refute_t (early return inside the period) "
                "and stamp it before bumping — without it, k suspectors "
                "amplify one episode to O(k x N) REFUTE datagrams",
            ))
    # -- native engine: OnSuspect carries the same compare-then-stamp
    src = index.source(_ENGINE)
    if "last_refute_t_" not in src:
        findings.append(Finding(
            "spec-refute-rate-limit", _ENGINE, 1,
            "extractor went blind: last_refute_t_ not found in the "
            "native engine — the refute rate-limit clock is gone",
        ))
    else:
        compared = re.search(r"last_refute_t_\s*<", src)
        stamped = re.search(r"last_refute_t_\s*=\s*now", src)
        if not (compared and stamped):
            miss = compared or stamped
            findings.append(Finding(
                "spec-refute-rate-limit", _ENGINE,
                _line_of(src, miss.start()) if miss else 1,
                f"native OnSuspect dropped the refute rate limit "
                f"({limit.window}): the last_refute_t_ clock must be "
                "compared against cfg.period AND stamped",
            ))
    return findings


# ---------------------------------------------------------------------------
# spec-transition-order
# ---------------------------------------------------------------------------

@rule(
    "spec-transition-order",
    "the tensor _tick must compute the confirm mask from PRE-WRITE "
    "status, then write SUSPECT, then FAILED (an entry always spends "
    ">= 1 round SUSPECT before it can confirm), with the confirm "
    "window built from the contract's t_fail/t_suspect/lh_multiplier",
    fixture="spec_transition_order.py",
    fixture_at=_ROUNDS,
)
def spec_transition_order(index) -> list[Finding]:
    findings: list[Finding] = []
    tree = index.tree(_ROUNDS)
    fn = _func(tree, "_tick")
    if fn is None:
        return [Finding(
            "spec-transition-order", _ROUNDS, 1,
            "extractor went blind: _tick not found — the analyzer "
            "cannot see the tensor transition ordering it pins",
        )]

    def _where_write(node, arg0: str, arg1: str) -> bool:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            return False
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id == "status"):
            return False
        v = node.value
        return (
            isinstance(v, ast.Call)
            and dotted(v.func) == "jnp.where"
            and len(v.args) >= 2
            and isinstance(v.args[0], ast.Name) and v.args[0].id == arg0
            and isinstance(v.args[1], ast.Name) and v.args[1].id == arg1
        )

    confirm_line = suspect_line = failed_line = None
    formula_ok = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if node.targets[0].id == "confirm" and confirm_line is None:
                confirm_line = node.lineno
            attrs = _attrs_in(node.value)
            if {"t_fail", "t_suspect", "lh_multiplier"} <= attrs:
                formula_ok = True
        if _where_write(node, "suspect_new", "SUSPECT") \
                and suspect_line is None:
            suspect_line = node.lineno
        if _where_write(node, "confirm", "FAILED") and failed_line is None:
            failed_line = node.lineno
    if None in (confirm_line, suspect_line, failed_line):
        findings.append(Finding(
            "spec-transition-order", _ROUNDS, fn.lineno,
            "extractor went blind: _tick no longer carries the "
            "recognizable confirm-mask / SUSPECT-write / FAILED-write "
            "statements the contract orders",
        ))
    elif not (confirm_line < suspect_line < failed_line):
        findings.append(Finding(
            "spec-transition-order", _ROUNDS, suspect_line,
            "reordered transition guard: _tick must compute `confirm` "
            "from PRE-WRITE status BEFORE writing SUSPECT and FAILED "
            f"(found confirm@{confirm_line}, SUSPECT-write@"
            f"{suspect_line}, FAILED-write@{failed_line}) — writing "
            "SUSPECT first lets a same-round entry satisfy the confirm "
            "compare and skip its suspect window entirely",
        ))
    if not formula_ok and not findings:
        findings.append(Finding(
            "spec-transition-order", _ROUNDS, confirm_line or fn.lineno,
            "the confirm window no longer references the contract "
            "formula names (t_fail + t_suspect stretched by "
            "lh_multiplier while degraded): "
            + spec.THRESHOLDS["confirm_window"],
        ))
    return findings


# ---------------------------------------------------------------------------
# spec-runtime-protocol
# ---------------------------------------------------------------------------

# The per-node lifecycle verb set SuspicionRuntime must expose: the
# socket engines mirror these semantics method-for-method.
_RUNTIME_VERBS = ("suspect", "adopt", "expired", "refute", "confirm",
                  "drop", "degraded", "t_suspect_window")


@rule(
    "spec-runtime-protocol",
    "suspicion/runtime.py must carry the full contract lifecycle verb "
    "set plus the degraded and Lifeguard-stretched-window formulas "
    "(protocol_spec.THRESHOLDS degraded / confirm_window)",
    fixture="spec_runtime_drift.py",
    fixture_at=_RUNTIME,
)
def spec_runtime_protocol(index) -> list[Finding]:
    findings: list[Finding] = []
    tree = index.tree(_RUNTIME)
    cls = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SuspicionRuntime":
            cls = node
            break
    if cls is None:
        return [Finding(
            "spec-runtime-protocol", _RUNTIME, 1,
            "extractor went blind: SuspicionRuntime not found — the "
            "reference lifecycle semantics the socket engines mirror "
            "are gone",
        )]
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for verb in _RUNTIME_VERBS:
        if verb not in methods:
            findings.append(Finding(
                "spec-runtime-protocol", _RUNTIME, cls.lineno,
                f"SuspicionRuntime lost lifecycle verb `{verb}` — every "
                "contract transition needs its runtime method (the "
                "socket engines mirror them method-for-method)",
            ))
    deg = methods.get("degraded")
    if deg is not None and "lh_frac" not in _attrs_in(deg):
        findings.append(Finding(
            "spec-runtime-protocol", _RUNTIME, deg.lineno,
            "degraded() no longer implements the contract formula "
            f"({spec.THRESHOLDS['degraded']})",
        ))
    win = methods.get("t_suspect_window")
    if win is not None:
        attrs = _attrs_in(win)
        if not {"t_suspect", "lh_multiplier", "degraded"} <= attrs:
            findings.append(Finding(
                "spec-runtime-protocol", _RUNTIME, win.lineno,
                "t_suspect_window() dropped the Lifeguard stretch: the "
                "contract window is "
                + spec.THRESHOLDS["confirm_window"],
            ))
    return findings


# ---------------------------------------------------------------------------
# spec-native-annotations
# ---------------------------------------------------------------------------

_ANN_RE = re.compile(r"//\s*@gfs:(\w+)[ \t]+([^\n]*)")
_OBS_RE = re.compile(r'ObsEmit\(\s*"([a-z_]+)"')
_TRANSITION_RE = re.compile(r"^(\w+)->(\w+)\s+guard=([\w-]+)\s*$")
_DISSEM_RE = re.compile(r"^(\w+)\s+profile=(\w+)\s+bound=([\w+]+)\s*$")

# How far above an ObsEmit a dominating annotation may sit (lines).
_DOMINATION_WINDOW = 30


def _parse_annotations(src: str):
    """[(line, tag, payload, emits-or-None, matches_spec)] for every
    ``// @gfs:`` annotation in the native source."""
    out = []
    for m in _ANN_RE.finditer(src):
        tag, payload = m.group(1), m.group(2).strip()
        line = _line_of(src, m.start())
        emits, ok = None, False
        if tag == "transition":
            tm = _TRANSITION_RE.match(payload)
            if tm:
                row = spec.transition(tm.group(1), tm.group(2), tm.group(3))
                if row is not None and "native" in row.engines:
                    ok, emits = True, row.emits
        elif tag == "verb":
            ok = payload in spec.WIRE_VERBS
        elif tag == "rate_limit":
            row = spec.rate_limit(payload)
            ok = row is not None and "native" in row.engines
        elif tag == "dissemination":
            dm = _DISSEM_RE.match(payload)
            if dm:
                row = spec.dissemination_row(dm.group(1), dm.group(2))
                ok = row is not None and row.bound == dm.group(3) \
                    and "native" in row.engines
        elif tag == "inject":
            row = spec.injection(payload)
            if row is not None:
                ok, emits = True, row.emits
        out.append((line, tag, payload, emits, ok))
    return out


@rule(
    "spec-native-annotations",
    "engine.cc's // @gfs: annotations are the native protocol "
    "extraction, cross-checked both ways: every annotation must match "
    "a contract row, every lifecycle ObsEmit must be dominated by a "
    "matching annotated transition/injection, and every native "
    "contract row must be annotated",
    fixture="spec_native_annotations.cc",
    fixture_at=_ENGINE,
)
def spec_native_annotations(index) -> list[Finding]:
    findings: list[Finding] = []
    src = index.source(_ENGINE)
    anns = _parse_annotations(src)
    sites = [(_line_of(src, m.start()), m.group(1))
             for m in _OBS_RE.finditer(src)]
    if not anns and not sites:
        return [Finding(
            "spec-native-annotations", _ENGINE, 1,
            "extractor went blind: no @gfs: annotations and no ObsEmit "
            "sites found — the native protocol surface is invisible",
        )]
    # 1) forward: every annotation matches a contract row
    for line, tag, payload, _emits, ok in anns:
        if not ok:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, line,
                f"annotation `@gfs:{tag} {payload}` matches no "
                "protocol_spec row (native engines column included) — "
                "either the annotation drifted or the contract is "
                "missing a row",
            ))
    # 2) domination: every lifecycle ObsEmit kind is declared by a
    # matching annotation within the preceding window
    lifecycle = spec.lifecycle_emit_kinds()
    for line, kind in sites:
        if kind not in lifecycle:
            continue
        declared = {
            emits for aline, _t, _p, emits, ok in anns
            if ok and emits is not None
            and line - _DOMINATION_WINDOW <= aline < line
        }
        if kind not in declared:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, line,
                f'lifecycle ObsEmit("{kind}") is not dominated by a '
                "matching @gfs:transition/@gfs:inject annotation in the "
                f"preceding {_DOMINATION_WINDOW} lines — the native "
                "emission has no declared contract edge",
            ))
    # 3) reverse: every native contract row is annotated somewhere
    ok_anns = [(tag, payload) for _l, tag, payload, _e, ok in anns if ok]
    for t in spec.TRANSITIONS:
        if t.emits is None or "native" not in t.engines:
            continue
        want = f"{t.src}->{t.dst} guard={t.guard}"
        if ("transition", want) not in ok_anns:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, 1,
                f"contract transition `{want}` (emits {t.emits}) has no "
                "@gfs:transition annotation in the native engine",
            ))
    for verb in spec.WIRE_VERBS:
        if ("verb", verb) not in ok_anns:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, 1,
                f"wire verb `{verb}` has no @gfs:verb annotation at the "
                "native dispatch",
            ))
    for r in spec.RATE_LIMITS:
        if "native" in r.engines and ("rate_limit", r.name) not in ok_anns:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, 1,
                f"rate limit `{r.name}` has no @gfs:rate_limit "
                "annotation in the native engine",
            ))
    for d in spec.DISSEMINATION:
        if not (d.annotated and "native" in d.engines):
            continue
        want = f"{d.event} profile={d.profile} bound={d.bound}"
        if ("dissemination", want) not in ok_anns:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, 1,
                f"dissemination row `{want}` has no @gfs:dissemination "
                "annotation in the native engine",
            ))
    for i in spec.INJECTIONS:
        if ("inject", i.name) not in ok_anns:
            findings.append(Finding(
                "spec-native-annotations", _ENGINE, 1,
                f"injection `{i.name}` has no @gfs:inject annotation at "
                "the native injection seam",
            ))
    return findings


# ---------------------------------------------------------------------------
# spec-obs-kind-coverage
# ---------------------------------------------------------------------------

@rule(
    "spec-obs-kind-coverage",
    "obs/schema.py LIFECYCLE_KINDS and the contract's emit kinds must "
    "be the same set, and every emit kind an EVENT_KINDS entry — a new "
    "lifecycle state cannot ship without a contract row",
    fixture="spec_obs_kinds.py",
    fixture_at=_SCHEMA,
)
def spec_obs_kind_coverage(index) -> list[Finding]:
    findings: list[Finding] = []
    tree = index.tree(_SCHEMA)
    kinds = _literal_tuple(tree, "EVENT_KINDS")
    lifecycle = _literal_tuple(tree, "LIFECYCLE_KINDS")
    if not isinstance(kinds, dict) or not isinstance(lifecycle, tuple):
        return [Finding(
            "spec-obs-kind-coverage", _SCHEMA, 1,
            "extractor went blind: EVENT_KINDS / LIFECYCLE_KINDS are no "
            "longer module-level literals the contract can diff against",
        )]
    line = _assign_line(tree, "LIFECYCLE_KINDS")
    spec_kinds = spec.lifecycle_emit_kinds()
    for k in sorted(spec_kinds - set(lifecycle)):
        findings.append(Finding(
            "spec-obs-kind-coverage", _SCHEMA, line,
            f"the contract emits `{k}` but schema LIFECYCLE_KINDS lacks "
            "it — the lifecycle timeline would silently drop a contract "
            "event",
        ))
    for k in sorted(set(lifecycle) - spec_kinds):
        findings.append(Finding(
            "spec-obs-kind-coverage", _SCHEMA, line,
            f"schema lifecycle kind `{k}` has no contract "
            "transition/injection row — add the protocol_spec row "
            "before shipping the state",
        ))
    for k in sorted(spec_kinds - set(kinds)):
        findings.append(Finding(
            "spec-obs-kind-coverage", _SCHEMA, line,
            f"contract emit kind `{k}` is missing from EVENT_KINDS",
        ))
    return findings


# ---------------------------------------------------------------------------
# scan-carry-arity
# ---------------------------------------------------------------------------

@rule(
    "scan-carry-arity",
    "the rr scan carry tuple, parallel/mesh.py out_specs and the "
    "PackedDetector threading must agree in arity and field order "
    "(MetricsCarry/RoundMetrics construction checked against the "
    "NamedTuple definitions; the 9-ary scan return against its unpack)",
    fixture="spec_scan_carry_arity.py",
    fixture_at=_MESH,
)
def scan_carry_arity(index) -> list[Finding]:
    findings: list[Finding] = []
    rtree = index.tree(_ROUNDS)
    mc_fields = namedtuple_fields(rtree, "MetricsCarry")
    rm_fields = namedtuple_fields(rtree, "RoundMetrics")
    if mc_fields is None or rm_fields is None:
        findings.append(Finding(
            "scan-carry-arity", _ROUNDS, 1,
            "extractor went blind: MetricsCarry / RoundMetrics "
            "NamedTuple definitions not found",
        ))
    # -- rr scan internal consistency: base carry arity A, step unpacks
    # {A, A+1} (lh arms an extra sus_counts slot), out_carry == A,
    # final unpack A non-star targets + star, return tuple arity R
    ret_arity = None
    fn = _func(rtree, "_scan_rounds_rr_packed")
    if fn is None:
        findings.append(Finding(
            "scan-carry-arity", _ROUNDS, 1,
            "extractor went blind: _scan_rounds_rr_packed not found",
        ))
    else:
        base = out = None
        base_line = fn.lineno
        unpacks: list[tuple[int, int]] = []
        final_np = None
        final_star = False
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            t, v = node.targets[0], node.value
            if isinstance(t, ast.Name) and isinstance(v, ast.Tuple):
                if t.id == "carry0" and base is None:
                    base, base_line = len(v.elts), node.lineno
                elif t.id == "out_carry" and out is None:
                    out = len(v.elts)
            if isinstance(t, ast.Tuple) and isinstance(v, ast.Name):
                stars = sum(isinstance(e, ast.Starred) for e in t.elts)
                if v.id == "carry":
                    unpacks.append((len(t.elts), node.lineno))
                elif v.id == "final":
                    final_np = len(t.elts) - stars
                    final_star = stars > 0
        for node in fn.body:
            if isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Tuple):
                ret_arity = len(node.value.elts)
        if base is None or ret_arity is None:
            findings.append(Finding(
                "scan-carry-arity", _ROUNDS, base_line,
                "extractor went blind: the rr carry0 tuple / packed "
                "return tuple are no longer recognizable",
            ))
        else:
            for arity, line in unpacks:
                if arity not in (base, base + 1):
                    findings.append(Finding(
                        "scan-carry-arity", _ROUNDS, line,
                        f"rr step unpacks {arity} carry slots where "
                        f"carry0 threads {base} (or {base + 1} with "
                        "local health armed) — a silently shifted field "
                        "order corrupts every downstream counter",
                    ))
            if out is not None and out != base:
                findings.append(Finding(
                    "scan-carry-arity", _ROUNDS, base_line,
                    f"rr out_carry has {out} slots where carry0 has "
                    f"{base} — the scan would re-thread misaligned state",
                ))
            if final_np is not None \
                    and (final_np != base or not final_star):
                findings.append(Finding(
                    "scan-carry-arity", _ROUNDS, base_line,
                    f"the final carry unpack names {final_np} slots "
                    f"(star={final_star}) where carry0 threads {base} "
                    "plus the starred lh tail",
                ))
    # -- constructor-call arity at the seams (mesh out_specs, sim)
    for path in (_MESH, _SIM):
        tree = index.tree(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            last = d.rsplit(".", 1)[-1]
            if last == "MetricsCarry":
                want = mc_fields
            elif last == "RoundMetrics":
                want = rm_fields
            else:
                continue
            if want is None \
                    or any(isinstance(a, ast.Starred) for a in node.args) \
                    or any(kw.arg is None for kw in node.keywords):
                continue
            got = len(node.args) + len(node.keywords)
            if got != len(want):
                findings.append(Finding(
                    "scan-carry-arity", path, node.lineno,
                    f"{last}(...) constructed with {got} fields where "
                    f"core.rounds defines {len(want)} "
                    f"({', '.join(want)}) — shard specs / threaded "
                    "metrics would bind to the wrong slots",
                ))
    # -- PackedDetector threading: the scan's return arity must match
    # the advance-path unpack
    if ret_arity is not None:
        stree = index.tree(_SIM)
        for node in ast.walk(stree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            t, v = node.targets[0], node.value
            if not (isinstance(t, ast.Tuple) and isinstance(v, ast.Call)):
                continue
            d = dotted(v.func)
            if d is None or not d.endswith("._step"):
                continue
            if len(t.elts) != ret_arity:
                findings.append(Finding(
                    "scan-carry-arity", _SIM, node.lineno,
                    f"PackedDetector unpacks {len(t.elts)} values from "
                    f"the packed scan step where it returns {ret_arity}",
                ))
    return findings
