"""gossipfs-lint: the conformance corpus must keep pace with the
contract.

``protocol_spec`` (round 17) is the one machine-readable protocol
contract; the conformance fuzzer (round 19) is its dynamic twin.  The
seam between them is the ``FAMILIES`` table in
``gossipfs_tpu/conformance/schedules.py`` — each family declares which
wire verbs and injection verbs its schedules exercise, and
``schedules.coverage()`` checks the union at runtime.  This rule is
the STATIC half of that check: a contract row added to
``protocol_spec`` (a new wire verb, a new injection) without a family
exercising it fails lint before any fuzz run happens — the same
one-ownership discipline the spec-* rules apply to the engines.

The declarations are trusted because the generators are validated
against them: ``schedules.validate`` rejects a case whose steps use a
verb outside its family's list, and the round-trip tests run every
family through it.
"""

from __future__ import annotations

from . import protocol_spec as spec
from .framework import Finding, literal_dict, rule

_SCHEDULES = "gossipfs_tpu/conformance/schedules.py"


@rule(
    "conformance-verb-coverage",
    "every protocol_spec wire verb and injection verb must be exercised "
    "by at least one conformance schedule family (schedules.FAMILIES), "
    "and every family's declared verbs/injections must exist in the "
    "contract — the corpus cannot silently fall behind the contract",
    fixture="conformance_verb_coverage.py",
    fixture_at=_SCHEDULES,
)
def conformance_verb_coverage(index) -> list[Finding]:
    findings: list[Finding] = []
    tree = index.tree(_SCHEDULES)
    families = literal_dict(tree, "FAMILIES")
    if not isinstance(families, dict) or not families:
        return [Finding(
            "conformance-verb-coverage", _SCHEDULES, 1,
            "extractor went blind: the FAMILIES literal was not found — "
            "the analyzer cannot see the corpus's declared coverage",
        )]

    covered_verbs: set[str] = set()
    covered_inj: set[str] = set()
    for name, fam in families.items():
        if not isinstance(fam, dict):
            findings.append(Finding(
                "conformance-verb-coverage", _SCHEDULES, 1,
                f"family {name!r} is not a declaration dict",
            ))
            continue
        verbs = set(fam.get("verbs", ()))
        injections = set(fam.get("injections", ()))
        unknown_v = verbs - set(spec.WIRE_VERBS)
        if unknown_v:
            findings.append(Finding(
                "conformance-verb-coverage", _SCHEDULES, 1,
                f"family {name!r} declares wire verbs outside the "
                f"contract: {sorted(unknown_v)} (protocol_spec.WIRE_VERBS)",
            ))
        unknown_i = injections - {i.name for i in spec.INJECTIONS}
        if unknown_i:
            findings.append(Finding(
                "conformance-verb-coverage", _SCHEDULES, 1,
                f"family {name!r} declares injections outside the "
                f"contract: {sorted(unknown_i)} (protocol_spec.INJECTIONS)",
            ))
        covered_verbs |= verbs & set(spec.WIRE_VERBS)
        covered_inj |= injections & {i.name for i in spec.INJECTIONS}

    missing_verbs = set(spec.WIRE_VERBS) - covered_verbs
    if missing_verbs:
        findings.append(Finding(
            "conformance-verb-coverage", _SCHEDULES, 1,
            f"contract wire verbs with NO exercising schedule family: "
            f"{sorted(missing_verbs)} — add a family (or extend one) "
            "before the verb ships untested",
        ))
    missing_inj = {i.name for i in spec.INJECTIONS} - covered_inj
    if missing_inj:
        findings.append(Finding(
            "conformance-verb-coverage", _SCHEDULES, 1,
            f"contract injection verbs with NO exercising schedule "
            f"family: {sorted(missing_inj)}",
        ))
    return findings
