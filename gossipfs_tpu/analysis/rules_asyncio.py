"""asyncio-hygiene — the socket-engine event loop must never block.

The asyncio UDP engine (``detector/udp.py``) is one loop carrying every
node's heartbeat task: a single blocking call inside a coroutine stalls
the whole cohort's clock (heartbeats stop advancing, peers see mass
staleness — a self-inflicted correlated failure), and an un-retained
``create_task`` handle is Python's documented garbage-collection
footgun (the task can vanish mid-flight).  UDPCAMPAIGN_r14's honest
n<=64 envelope exists precisely because the loop's latency budget is
already tight — blocking regressions must not reach it by review luck.
"""

from __future__ import annotations

import ast

from gossipfs_tpu.analysis.framework import (
    Finding,
    RepoIndex,
    dotted,
    rule,
)

# Calls that block the event loop outright.
_BLOCKING = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output", "os.system",
    "socket.create_connection", "urllib.request.urlopen",
}


@rule(
    "asyncio-hygiene",
    "no blocking calls (time.sleep, subprocess.*, ...) inside "
    "coroutines, and every asyncio.create_task handle is retained "
    "(assigned/awaited), never dropped as a bare expression",
    fixture="asyncio_hygiene.py",
    fixture_at="gossipfs_tpu/detector/_lint_fixture.py",
)
def check_asyncio(index: RepoIndex) -> list[Finding]:
    out = []
    for rel in index.py_files():
        for fn in ast.walk(index.tree(rel)):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and dotted(node.func) in _BLOCKING:
                    out.append(Finding(
                        "asyncio-hygiene", rel, node.lineno,
                        f"blocking call {dotted(node.func)}() inside "
                        f"coroutine {fn.name}() — it stalls every "
                        "node's heartbeat task on the shared loop "
                        "(use await asyncio.sleep / an executor)",
                    ))
                if isinstance(node, ast.Expr) \
                        and isinstance(node.value, ast.Call) \
                        and isinstance(node.value.func, ast.Attribute) \
                        and node.value.func.attr == "create_task":
                    out.append(Finding(
                        "asyncio-hygiene", rel, node.lineno,
                        f"create_task handle dropped in {fn.name}() — "
                        "an unreferenced task may be garbage-collected "
                        "mid-flight; retain or await it",
                    ))
    return out
