"""gossipfs-spec: THE machine-readable protocol contract.

The SWIM suspect/refute lifecycle (PAPERS.md #2) and the Lifeguard
local-health stretch (PAPERS.md #3) are implemented three times — the
tensor tick/merge (``core/rounds.py`` + ``ops/merge_pallas.py``), the
asyncio engine (``detector/udp.py``) and the C++ epoll engine
(``native/engine.cc``) — and until this module every drift between them
was found only at runtime by knife-edge campaign parity (the per-member
lh-window divergence of round 16; the ENTRY-broadcast asymmetry this
PR's satellite closes).  This module is the single contract the three
implementations are *statically* diffed against by ``rules_spec.py``:

* :data:`STATES` / :data:`TRANSITIONS` — the lifecycle state machine,
  every edge carrying its guard (a :data:`THRESHOLDS` key) and the
  ``obs/schema.py`` event kind it emits when taken.
* :data:`INJECTIONS` — ground-truth fault-injection events (observer
  ``-1``): not protocol transitions, but every engine's injection seam
  must emit them, so they are contract rows too.
* :data:`WIRE_VERBS` — the control-verb vocabulary of the socket
  engines' wire (``<arg><CMD>VERB`` datagrams).
* :data:`RATE_LIMITS` — protocol back-pressure rules (SWIM refutes once
  per incarnation: one REFUTE broadcast per period, not one per
  received SUSPECT copy).
* :data:`DISSEMINATION` — who hears about an event, per protocol
  profile.  The load-bearing row: a NEW suspicion under the campaign
  profile (``push=random``) reaches the subject plus a fanout-sized
  random sample — never all peers (O(suspects x N) at cohort sizes; the
  measured 26 s tick / 73k-FP storm documented in ``native/engine.cc``).
* :data:`THRESHOLDS` — the guard formulas, written once.  The rules
  check each engine's implementation *structurally* against these rows
  (which names/attributes must appear, in which statement order), not
  by string equality.

C++ has no AST here, so ``native/engine.cc`` carries lightweight
structured annotations the extractor parses and cross-checks BOTH ways
(every annotation must match a contract row; every lifecycle ``ObsEmit``
must be dominated by a matching annotation)::

    // @gfs:transition SUSPECT->FAILED guard=confirm_window
    // @gfs:verb SUSPECT
    // @gfs:rate_limit refute_broadcast
    // @gfs:dissemination new_suspect profile=campaign bound=subject+fanout
    // @gfs:inject crash

This module is pure data — stdlib ``dataclasses`` only, importable from
the AST rules and the tier-1 tests without jax.  tests/
test_protocol_spec.py holds the contract itself to the schema: every
``LIFECYCLE_KINDS`` entry maps to a row here and vice versa, so a new
lifecycle state cannot ship without a contract row.
"""

from __future__ import annotations

import dataclasses

# The three engines the contract binds.  "tensor" is the scan path
# (core/rounds.py tick/merge + the ops/merge_pallas.py fused kernels —
# one implementation, pinned bit-identical by the parity tests).
ENGINES = ("tensor", "udp", "native")

# Lifecycle states.  The socket engines represent them positionally —
# MEMBER = listed, SUSPECT = listed + suspects entry, FAILED = on the
# fail list (cooldown suppression), UNKNOWN = in neither structure —
# while the tensor engine stores them as status codes (core/rounds.py).
STATES = ("UNKNOWN", "MEMBER", "SUSPECT", "FAILED")


@dataclasses.dataclass(frozen=True)
class Transition:
    """One lifecycle edge: ``src -> dst`` when ``guard`` holds.

    ``emits`` is the obs/schema.py event kind the edge emits when taken
    (None for silent bookkeeping edges); ``engines`` lists which
    implementations carry the edge.
    """

    src: str
    dst: str
    guard: str           # key into THRESHOLDS
    emits: str | None    # obs/schema.py EVENT_KINDS kind, or None
    engines: tuple = ENGINES


@dataclasses.dataclass(frozen=True)
class Injection:
    """A ground-truth fault-injection event (observer -1): stamped at
    the injection seam, not produced by a protocol transition."""

    name: str
    emits: str


@dataclasses.dataclass(frozen=True)
class RateLimit:
    """A protocol back-pressure rule (who may send what, how often)."""

    name: str
    scope: str
    window: str
    engines: tuple


@dataclasses.dataclass(frozen=True)
class Dissemination:
    """Who hears about ``event`` under ``profile``.

    ``annotated`` marks rows the native extractor requires an explicit
    ``@gfs:dissemination`` annotation for (the drift-prone ones).
    """

    event: str
    profile: str     # "campaign" (push=random) | "reference" | "any"
    bound: str       # "subject+fanout" | "all_peers"
    engines: tuple
    annotated: bool = False


TRANSITIONS = (
    # Learned of a peer: JOIN through the introducer, an introducer
    # full-list push, or an unknown list-gossip entry NOT on the fail
    # list (cooldown suppression wins over resurrection).
    Transition("UNKNOWN", "MEMBER", "join_or_merge_add", None, ENGINES),
    # First local staleness evidence: the entry enters SUSPECT (and the
    # suspicion is disseminated — see DISSEMINATION).  With suspicion
    # disarmed (t_suspect == 0) this edge is skipped and `stale`
    # confirms directly (the MEMBER->FAILED row below).
    Transition("MEMBER", "SUSPECT", "stale", "suspect", ENGINES),
    # Evidence of life while SUSPECT — a heartbeat/incarnation advance
    # via merge, or an explicit REFUTE — cancels the pending failure.
    Transition("SUSPECT", "MEMBER", "refute_evidence", "refute", ENGINES),
    # The suspect window (Lifeguard-stretched while the observer is
    # degraded) expired without refuting evidence: declare failure.
    Transition("SUSPECT", "FAILED", "confirm_window", "confirm", ENGINES),
    # Suspicion disarmed: staleness past t_fail confirms directly.
    Transition("MEMBER", "FAILED", "stale", "confirm", ENGINES),
    # Verb-driven removal (LEAVE / a peer's REMOVE) or the removal a
    # local confirm causes: the entry moves to the fail list and the
    # membership drop is emitted as `remove`.
    Transition("MEMBER", "FAILED", "leave_or_remove", "remove", ENGINES),
    # Fail-list cooldown expiry: the entry is forgotten and may rejoin.
    Transition("FAILED", "UNKNOWN", "cooldown_expiry", None, ENGINES),
)

INJECTIONS = (
    Injection("crash", "crash"),
    Injection("hb_freeze", "hb_freeze"),
    Injection("leave", "leave"),
    Injection("join", "join"),
)

# Control-verb vocabulary of the socket wire (detector/udp.py handle()
# and native/engine.cc HandleDatagram dispatch on exactly this set).
WIRE_VERBS = ("JOIN", "LEAVE", "REMOVE", "SUSPECT", "REFUTE")

RATE_LIMITS = (
    # SWIM refutes once per incarnation: k observers suspecting the same
    # episode each disseminate SUSPECT, so O(k x fanout) copies land at
    # the subject — one incarnation bump + ONE REFUTE broadcast per
    # heartbeat period answers the whole episode instead of amplifying
    # to O(k x N) datagrams.  (The tensor engine refutes implicitly by
    # merge, so it has no broadcast to limit.)
    RateLimit(
        "refute_broadcast",
        scope="per node, as the suspected subject",
        window="one REFUTE broadcast per heartbeat period",
        engines=("udp", "native"),
    ),
)

DISSEMINATION = (
    # THE drift-prone row (this PR's satellite fix): a NEW suspicion
    # under the campaign profile reaches the subject (its active
    # incarnation-bump refute is the point) plus a fanout-sized random
    # sample — O(fanout) per new suspicion, like every other push in
    # this mode.  All-peers here is O(suspects x N) per round: at n=256
    # a rack outage makes ~250 observers suspect 8 nodes in one tick.
    Dissemination("new_suspect", "campaign", "subject+fanout",
                  ("udp", "native"), annotated=True),
    # Reference-faithful mode (ring push): all-peers broadcast, kept
    # verbatim for the small-n udp-parity lane.
    Dissemination("new_suspect", "reference", "all_peers",
                  ("udp", "native"), annotated=True),
    # The REFUTE answer goes to all peers in both profiles — it is
    # rate-limited at the source instead (RATE_LIMITS above).
    Dissemination("refute", "any", "all_peers", ("udp", "native")),
    # Delta-piggyback membership refresh (round 20): under the delta
    # profile the per-round push carries a BOUNDED slice of the view —
    # recently-changed entries first (per-peer change cursor over a
    # monotone entry version), round-robin refresh of the stable tail
    # in any leftover capacity — instead of the full O(N) list.  The
    # selection rule and the anti-entropy cadence live in DELTA_GOSSIP;
    # both socket engines are structurally diffed against it by the
    # spec-delta-dissemination rule.
    Dissemination("membership_refresh", "delta", "changed+rr_tail+capped",
                  ("udp", "native"), annotated=True),
)

# Delta-piggyback dissemination knobs (the membership_refresh/delta row
# above, written once).  SWIM piggybacks *changes* on dissemination
# (PAPERS.md #2) and van Renesse's analysis says correctness needs only
# eventual max-merge (PAPERS.md #1) — so the wire payload shrinks from
# O(N) to O(cap) per datagram provided a periodic full-list
# anti-entropy push bounds every entry's refresh gap:
#
# * ``wire_mark`` — delta frames are the full-list wire format prefixed
#   by this marker; the receiver strips it and runs the SAME hardened
#   per-entry max-merge (a truncated or replayed delta degrades to a
#   smaller merge, never a protocol error).
# * ``max_entries`` — the per-datagram cap.  Selection: entries whose
#   version advanced past the per-peer cursor, most recent first, then
#   round-robin tail refresh in any leftover slots.  A peer with no
#   cursor yet (first contact) gets the full list.
# * ``anti_entropy_every`` — every K-th round (cluster-round aligned)
#   pushes the FULL list so a lost delta can never wedge convergence;
#   Pittel's bound stays the reconvergence oracle.  K must stay
#   strictly below t_fail (2x margin recommended: a 1.33x margin
#   manufactured a quiet-cluster FP at n=256).
# * ``freshness`` — in delta mode ONLY, the merge also max-merges the
#   wire ``ts`` on EQUAL heartbeat counters, clamped to local now.
#   Without it, ts refreshes only on hb ADVANCE, and a synchronized
#   anti-entropy round equalizes counters cluster-wide so the NEXT
#   full push can't refresh many pairs — at n=1024 staleness crossed
#   t_fail on a quiet cluster (a 7k-FP storm).  Live nodes keep
#   stamping fresh ts into their own pushes, so the rule propagates
#   liveness; a crashed node's copies converge to a constant max, so
#   staleness still grows globally and crash detection is preserved.
#
# This dict is a pure literal: the lint extractor reads the defaults
# without importing the engines, and the engines' own defaults must
# match it exactly (spec-delta-dissemination goes red on drift).
DELTA_GOSSIP = {
    "event": "membership_refresh",
    "profile": "delta",
    "bound": "changed+rr_tail+capped",
    "wire_mark": "<#DELTA#>",
    "max_entries": 16,
    "anti_entropy_every": 4,
    "selection": ("changed_first", "rr_tail", "capped"),
    "constraint": "anti_entropy_every < t_fail",
    "freshness": "equal_hb_wire_ts_max_merge",
}

# Guard formulas, written once.  `period` is the heartbeat period (the
# tensor engine's unit round); `age` is time since the entry's last
# local stamp; `hb > hb_grace` is the reference's hb<=1 detection grace
# (a just-added entry is undetectable until its counter advances).
THRESHOLDS = {
    "stale": "hb > hb_grace and age > t_fail * period",
    "confirm_window": (
        "age_suspect > t_suspect * (1 + (lh_multiplier if degraded "
        "else 0)) * period, recomputed PER MEMBER at expiry check"
    ),
    "degraded": "len(suspects) > lh_frac * len(listed)",
    "refute_evidence": (
        "heartbeat/incarnation advance observed while SUSPECT "
        "(list-gossip merge or an explicit REFUTE)"
    ),
    "leave_or_remove": "LEAVE or REMOVE verb received, or a local confirm",
    "cooldown_expiry": "age_on_fail_list > t_cooldown * period",
    "join_or_merge_add": (
        "JOIN / introducer push / unknown list-gossip entry, unless "
        "fail-listed (cooldown suppression wins)"
    ),
}


# ---------------------------------------------------------------------------
# Lookup helpers (the rules_spec extractors and the completeness tests)
# ---------------------------------------------------------------------------

def lifecycle_emit_kinds() -> set[str]:
    """Every event kind the contract emits — must equal
    obs.schema.LIFECYCLE_KINDS exactly (tests/test_protocol_spec.py)."""
    kinds = {t.emits for t in TRANSITIONS if t.emits is not None}
    kinds.update(i.emits for i in INJECTIONS)
    return kinds


def transition(src: str, dst: str, guard: str) -> Transition | None:
    for t in TRANSITIONS:
        if (t.src, t.dst, t.guard) == (src, dst, guard):
            return t
    return None


def injection(name: str) -> Injection | None:
    for i in INJECTIONS:
        if i.name == name:
            return i
    return None


def rate_limit(name: str) -> RateLimit | None:
    for r in RATE_LIMITS:
        if r.name == name:
            return r
    return None


def dissemination_row(event: str, profile: str) -> Dissemination | None:
    for d in DISSEMINATION:
        if (d.event, d.profile) == (event, profile):
            return d
    return None
