"""Online health plane: a streaming invariant monitor over the event
schema.

PR 5's flight recorder + ``tools/timeline.py`` can say what went wrong
AFTER a run ends; this module says it WHILE the events stream.  A
:class:`StreamMonitor` consumes ``gossipfs-obs/v1`` records one at a
time — attachable wherever ``attach_recorder`` works today (SimDetector,
UdpCluster, CoSim, the bulk-scan decode) via :class:`MonitorRecorder`,
and over deploy log tails / written traces via :meth:`StreamMonitor.
feed_jsonl` — and maintains two things:

* **incremental estimators** — rolling TTD and FPR, suppression ratio,
  the false-positive-confirm (split-brain evidence) window, and the
  acked-write durability ledger (``traffic.audit.DurabilityReplay``,
  the SAME state machine the post-hoc replay runs, so the two
  accountings cannot drift);

* **a declarative invariant table** (:data:`INVARIANTS`) — SWIM's
  accuracy story as machine-checkable rows: no confirm without a
  preceding SUSPECT, no acked write lost, reconvergence within a bound,
  rolling FPR under a storm threshold.  A violation is itself emitted
  as a schema event (``invariant_violation``), so ``tools/timeline.py``
  and the recorder lint maps stay the single source of truth for what
  can appear in a stream.

The monitor's :meth:`~StreamMonitor.summary` mirrors
``tools/timeline.py``'s post-hoc ``analyze`` estimator for estimator;
:func:`estimator_parity` is the standing ``monitor_parity`` oracle
(``verify_claims.py``): on the same stream the streaming and post-hoc
derivations must agree EXACTLY — any drift is a real accounting bug in
one of them.

Pure python + stdlib (the obs convention): the deploy lane's jax-free
tooling can tail its node logs through this too.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import statistics

from gossipfs_tpu.obs import schema
from gossipfs_tpu.obs.recorder import FlightRecorder
from gossipfs_tpu.obs.schema import Event
from gossipfs_tpu.traffic.audit import DurabilityReplay
from gossipfs_tpu.traffic.workload import quantiles

# ---------------------------------------------------------------------------
# The invariant table — what "healthy" means, as declarative rows
# ---------------------------------------------------------------------------

INVARIANTS: dict[str, str] = {
    "no_confirm_without_suspect":
        "with the SWIM lifecycle armed, NO subject is confirmed FAILED "
        "without a preceding SUSPECT event (SWIM's accuracy mechanism; "
        "checked per confirm event as it streams)",
    "no_acked_write_lost":
        "every acked write survives on >= 1 event-known live replica at "
        "end of stream (the durability ledger's verdict; the traffic "
        "plane's standing claim)",
    "reconverge_bound":
        "every tracked crash is REMOVED cluster-wide within "
        "`reconverge_bound` rounds of max(crash round, clock_floor, any "
        "later scenario_clear) — t_fail + gossip diameter (+ slack) per "
        "Pittel's log-N bound; a miss is a stuck or split-brained view",
    "fpr_storm":
        "the rolling false-positive rate over the last `fpr_window` "
        "round_ticks stays <= `fpr_threshold` — the Lifeguard gray-"
        "failure signature (flapping, lossy links) is exactly an FPR "
        "storm, caught the round it starts instead of post-hoc",
}


@dataclasses.dataclass(frozen=True)
class MonitorParams:
    """Invariant thresholds (JSON-loadable — campaign case files carry
    one).  ``None`` disables the corresponding invariant row.

    ``expect_suspicion``: force the no-confirm-without-SUSPECT check on
    (``True``) or off (``False``); ``None`` infers it from the stream
    (suspicion counters present in ``round_tick`` rows — the same
    inference ``analyze`` uses).  ``clock_floor``: earliest round the
    reconvergence clock may start (a campaign sets it to the scenario
    horizon so convergence legitimately delayed by an armed fault
    window isn't flagged).
    """

    fpr_threshold: float | None = 1e-4
    fpr_window: int = 10
    reconverge_bound: int | None = None
    clock_floor: int = 0
    expect_suspicion: bool | None = None

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_dict(cls, doc: dict) -> "MonitorParams":
        return cls(**{k: doc[k] for k in
                      (f.name for f in dataclasses.fields(cls))
                      if k in doc})


class StreamMonitor:
    """Consume schema events online; keep estimators + check invariants.

    Feed order must be round-ordered (every recorder stream is; merged
    multi-node streams go through ``timeline.merge`` first).  ``observe``
    returns the violations THAT event raised (usually ``[]``) so an
    inline wrapper can append them to the same stream; ``finish`` runs
    the end-of-stream invariants (durability, unconverged crashes) and
    returns theirs.
    """

    def __init__(self, params: MonitorParams | None = None,
                 n: int | None = None):
        self.params = params or MonitorParams()
        self.n = n
        self.n_effective: int | None = None
        self.violations: list[Event] = []
        self._finished = False
        # -- analyze-mirror accounting (tools/timeline.py)
        self.crash_rounds: dict[int, int] = {}
        self._firsts: dict[str, dict[int, int]] = {}
        self._confirm_fp: dict[int, bool] = {}
        self.rounds = 0              # round_tick rows seen
        self.events_seen = 0
        self.true_detections = 0
        self.false_positives = 0
        self._alive_sum = 0
        self.suspicion = False
        self.suspects_entered = 0
        self.refutations = 0
        self.fp_suppressed = 0
        self._has_traffic = False
        self._client_ops: list[float] = []
        self._client_issued = 0
        self._client_acked = 0
        # -- durability ledger (shared state machine with audit.py); the
        # one-round buffer reorders crash/join ahead of same-round data
        # rows, so the incremental walk equals the post-hoc sorted one
        self._replay = DurabilityReplay()
        self._replay_round: int | None = None
        self._replay_buf: list[Event] = []
        # -- invariant state
        self._last_round = -1
        self._scenario_clears: list[int] = []
        self._fpr_win: collections.deque[tuple[int, int]] = \
            collections.deque(maxlen=max(1, self.params.fpr_window))
        self._in_storm = False
        self.storm_rounds = 0
        self.worst_window_fpr = 0.0
        # reconvergence clocks per ACTIVE crash episode: latest crash
        # round, cleared by the episode-ending remove (or a rejoin).
        # Separate from ``crash_rounds`` (which keeps the FIRST crash,
        # analyze's TTD convention): a rejoin + re-crash re-clocks the
        # deadline here without disturbing estimator parity.
        self._crash_episode: dict[int, int] = {}
        # split-brain evidence: the window over which ground-truth-alive
        # subjects stood confirmed FAILED (an event-derived lower bound)
        self._fp_confirm_first: int | None = None
        self._fp_confirm_last: int | None = None

    # -- feeding ------------------------------------------------------------
    def observe_header(self, header: dict) -> None:
        if self.n is None and header.get("n"):
            self.n = int(header["n"])
        if self.n_effective is None and header.get("n_effective"):
            self.n_effective = int(header["n_effective"])
        for k, v in (header.get("crash_rounds") or {}).items():
            self.crash_rounds[int(k)] = int(v)
            self._crash_episode.setdefault(int(k), int(v))

    def observe(self, ev: Event) -> list[Event]:
        """Consume one event; returns violations it raised (often [])."""
        if ev.kind == "invariant_violation":
            # a previously-monitored stream replaying through a fresh
            # monitor: re-derive, don't double-count
            return []
        out: list[Event] = []
        self.events_seen += 1
        self._last_round = max(self._last_round, ev.round)
        k = ev.kind

        if k == "crash" and ev.subject >= 0:
            self.crash_rounds.setdefault(ev.subject, ev.round)
            self._crash_episode[ev.subject] = ev.round  # latest wins
        elif k == "join" and ev.subject >= 0:
            # a rejoin ends any pending crash episode: the old entry's
            # convergence story is over (the carry resets too)
            self._crash_episode.pop(ev.subject, None)
        elif k == "round_tick":
            d = ev.detail
            self.rounds += 1
            self.true_detections += d.get("true_detections", 0)
            fp = d.get("false_positives", 0)
            self.false_positives += fp
            alive = d.get("n_alive", 0)
            self._alive_sum += alive
            if "suspects_entered" in d:
                self.suspicion = True
                self.suspects_entered += d.get("suspects_entered", 0)
                self.refutations += d.get("refutations", 0)
                self.fp_suppressed += d.get("fp_suppressed", 0)
            self._fpr_win.append((fp, alive))
            out.extend(self._check_fpr_storm(ev.round))
        elif k == "scenario_clear":
            self._scenario_clears.append(ev.round)
        elif k == "client_op":
            self._has_traffic = True
            self._client_issued += 1
            self._client_acked += bool(ev.detail.get("ok"))
            self._client_ops.append(ev.detail.get("ms", 0.0))

        if ev.subject >= 0 and k in ("suspect", "confirm", "remove"):
            slot = self._firsts.setdefault(k, {})
            if ev.subject not in slot:
                slot[ev.subject] = ev.round
                if k == "confirm" and "false_positive" in ev.detail:
                    self._confirm_fp[ev.subject] = bool(
                        ev.detail["false_positive"])
            if k == "confirm":
                out.extend(self._check_confirm(ev))
            elif k == "remove":
                out.extend(self._check_remove(ev))

        if k in ("replica_put", "stripe_put"):
            # the SAME gate analyze uses (put | client_op, set above) —
            # a repair/delete-only tail must not grow a durability doc
            # the post-hoc side omits (monitor_parity)
            self._has_traffic = True
        self._replay_observe(ev)
        return out

    def feed(self, events) -> list[Event]:
        out: list[Event] = []
        for ev in events:
            out.extend(self.observe(ev))
        return out

    def feed_jsonl(self, path) -> list[Event]:
        """Tail a written stream (bench ``--trace`` artifact, a deploy
        ``node<i>.log``) through the monitor — the file-attachment mode
        for engines the monitor can't sit inside.  Parses through
        ``obs.recorder.load_stream`` (the one reader timeline.py also
        uses, so the parity oracle's two sides read identically)."""
        from gossipfs_tpu.obs.recorder import load_stream

        header, events = load_stream(path)
        if header is not None:
            self.observe_header(header)
        return self.feed(events)

    # -- durability replay (one-round reorder buffer) -----------------------
    def _replay_observe(self, ev: Event) -> None:
        if ev.kind not in ("crash", "join", "replica_put",
                           "replica_repair", "replica_delete",
                           "stripe_put", "stripe_repair"):
            return
        if self._replay_round is not None and ev.round > self._replay_round:
            self._replay_flush()
        self._replay_round = (ev.round if self._replay_round is None
                              else max(self._replay_round, ev.round))
        self._replay_buf.append(ev)

    def _replay_flush(self) -> None:
        for e in sorted(self._replay_buf,
                        key=lambda e: 0 if e.kind in ("crash", "join")
                        else 1):
            self._replay.observe(e)
        self._replay_buf = []

    # -- invariant checks ---------------------------------------------------
    def _violate(self, round_: int, invariant: str, subject: int = -1,
                 **detail) -> list[Event]:
        ev = Event(round=round_, observer=-1, subject=subject,
                   kind="invariant_violation",
                   detail={"invariant": invariant, **detail})
        self.violations.append(ev)
        return [ev]

    def _suspicion_armed(self) -> bool:
        if self.params.expect_suspicion is not None:
            return self.params.expect_suspicion
        return self.suspicion

    def _check_confirm(self, ev: Event) -> list[Event]:
        out: list[Event] = []
        if self._confirm_fp.get(ev.subject) or bool(
                ev.detail.get("false_positive")):
            if self._fp_confirm_first is None:
                self._fp_confirm_first = ev.round
            self._fp_confirm_last = ev.round
        if not self._suspicion_armed():
            return out
        s = self._firsts.get("suspect", {}).get(ev.subject)
        if s is None or s > ev.round:
            out += self._violate(
                ev.round, "no_confirm_without_suspect",
                subject=ev.subject, observer_confirm=ev.observer,
                suspect_round=s)
        return out

    def _reconv_deadline(self, crash_round: int) -> int | None:
        bound = self.params.reconverge_bound
        if bound is None:
            return None
        floor = max(crash_round, self.params.clock_floor,
                    *[c for c in self._scenario_clears
                      if c >= crash_round] or [crash_round])
        return floor + bound

    def _check_remove(self, ev: Event) -> list[Event]:
        # the episode-ending remove: evaluated once per crash episode
        # (repeat per-observer remove rows find the episode cleared)
        r0 = self._crash_episode.pop(ev.subject, None)
        if r0 is None:
            return []
        deadline = self._reconv_deadline(r0)
        if deadline is not None and ev.round > deadline:
            return self._violate(
                ev.round, "reconverge_bound", subject=ev.subject,
                crash_round=r0, deadline=deadline)
        return []

    def _check_fpr_storm(self, round_: int) -> list[Event]:
        thr = self.params.fpr_threshold
        if thr is None:
            return []
        fp = sum(f for f, _ in self._fpr_win)
        alive = sum(a for _, a in self._fpr_win)
        denom = float(alive) * max((self.n_effective or self.n or 1) - 1, 1)
        wfpr = (fp / denom) if denom else 0.0
        self.worst_window_fpr = max(self.worst_window_fpr, wfpr)
        if wfpr > thr:
            self.storm_rounds += 1
            if not self._in_storm:
                self._in_storm = True
                return self._violate(
                    round_, "fpr_storm", window_fpr=wfpr, threshold=thr,
                    window_rounds=len(self._fpr_win),
                    window_false_positives=fp)
        else:
            self._in_storm = False
        return []

    def finish(self) -> list[Event]:
        """End-of-stream invariants; idempotent."""
        if self._finished:
            return []
        self._finished = True
        self._replay_flush()
        out: list[Event] = []
        lost = self._replay.lost_files()
        if lost:
            out += self._violate(
                self._last_round, "no_acked_write_lost",
                files=lost, lost=len(lost))
        if self.params.reconverge_bound is not None:
            # crash episodes still open at end of stream: flag the ones
            # whose deadline the horizon has already passed
            for node, r0 in sorted(self._crash_episode.items()):
                deadline = self._reconv_deadline(r0)
                if deadline is not None and self._last_round > deadline:
                    out += self._violate(
                        self._last_round, "reconverge_bound", subject=node,
                        crash_round=r0, deadline=deadline, removed=False)
        return out

    # -- estimators ---------------------------------------------------------
    def summary(self) -> dict:
        """The estimator document — mirrors ``tools/timeline.py``'s
        ``analyze`` field for field (:data:`PARITY_FIELDS`), plus the
        monitor-only rows (violations, storm/stability extras)."""
        self.finish()
        firsts = self._firsts
        ttd_first, ttd_conv, ttd_sus, sus2conf = {}, {}, {}, {}
        for node, r0 in self.crash_rounds.items():
            c = firsts.get("confirm", {}).get(node)
            ttd_first[node] = (c - r0) if c is not None else -1
            rm = firsts.get("remove", {}).get(node)
            ttd_conv[node] = (rm - r0) if rm is not None else -1
            s = firsts.get("suspect", {}).get(node)
            if s is not None:
                ttd_sus[node] = s - r0
                if c is not None:
                    sus2conf[node] = c - s
        n_eff = self.n_effective or self.n
        opportunities = float(self._alive_sum) * max((n_eff or 1) - 1, 1)
        fpr = (self.false_positives / opportunities) if opportunities else 0.0
        ttd_vals = [v for v in ttd_first.values() if v >= 0]
        doc = {
            "schema": schema.SCHEMA,
            "n": self.n,
            "rounds": self.rounds,
            "events": self.events_seen,
            "tracked_crashes": len(self.crash_rounds),
            "detected": len(ttd_vals),
            "ttd_first": ttd_first,
            "ttd_converged": ttd_conv,
            "ttd_first_median": statistics.median(ttd_vals)
            if ttd_vals else None,
            "true_detections": self.true_detections,
            "false_positives": self.false_positives,
            "false_positive_rate": fpr,
            "suspicion": self.suspicion,
        }
        if self.suspicion:
            doc.update(
                suspects_entered=self.suspects_entered,
                refutations=self.refutations,
                fp_suppressed=self.fp_suppressed,
                ttd_suspect=ttd_sus,
                suspect_to_confirm=sus2conf,
                suspect_before_confirm=all(
                    subj in firsts.get("suspect", {})
                    and firsts["suspect"][subj] <= r
                    for subj, r in firsts.get("confirm", {}).items()
                ),
            )
        if self._confirm_fp:
            doc["confirm_false_positives"] = sum(self._confirm_fp.values())
        if self._has_traffic:
            doc["durability"] = self._replay.facts()
            if self._client_issued:
                doc["client_ops"] = {
                    "issued": self._client_issued,
                    "acked": self._client_acked,
                    **quantiles(self._client_ops),
                }
        # -- monitor-only rows (outside the parity surface)
        doc.update(
            invariant_violations=len(self.violations),
            violations=[v.to_record() for v in self.violations],
            suppression_ratio=(self.fp_suppressed / self.refutations
                               if self.refutations else None),
            storm_rounds=self.storm_rounds,
            worst_window_fpr=self.worst_window_fpr,
            split_brain_rounds=(
                self._fp_confirm_last - self._fp_confirm_first + 1
                if self._fp_confirm_first is not None else 0),
        )
        return doc

    def verdict(self) -> dict:
        """The compact machine verdict bench/campaign surfaces stamp."""
        self.finish()
        by: dict[str, int] = {}
        for v in self.violations:
            name = v.detail.get("invariant", "?")
            by[name] = by.get(name, 0) + 1
        return {
            "ok": not self.violations,
            "invariant_violations": len(self.violations),
            "by_invariant": by,
            "invariants_checked": sorted(self._checked_invariants()),
        }

    def _checked_invariants(self) -> list[str]:
        rows = ["no_acked_write_lost"]
        if self._suspicion_armed():
            rows.append("no_confirm_without_suspect")
        if self.params.fpr_threshold is not None:
            rows.append("fpr_storm")
        if self.params.reconverge_bound is not None:
            rows.append("reconverge_bound")
        return rows


class MonitorRecorder(FlightRecorder):
    """A FlightRecorder with a StreamMonitor riding inline.

    Drop-in wherever ``attach_recorder`` takes a FlightRecorder: every
    emitted event is observed as it happens, and any violation it raises
    is appended to the SAME stream (so the written artifact carries its
    own online verdict).  ``close``/``finish`` run the end-of-stream
    invariants first.
    """

    def __init__(self, path=None, monitor: StreamMonitor | None = None,
                 params: MonitorParams | None = None, source: str = "sim",
                 n: int | None = None, **meta):
        super().__init__(path, source=source, n=n, **meta)
        self.monitor = monitor or StreamMonitor(params=params, n=n)
        self.monitor.observe_header(self.header)

    def emit(self, ev: Event) -> None:
        super().emit(ev)
        if ev.kind == "invariant_violation":
            return
        for v in self.monitor.observe(ev):
            super().emit(v)

    def finish(self) -> None:
        for v in self.monitor.finish():
            super().emit(v)

    def close(self) -> None:
        self.finish()
        super().close()


# ---------------------------------------------------------------------------
# monitor_parity: streaming == post-hoc, exactly
# ---------------------------------------------------------------------------

# The estimator fields the streaming summary and tools/timeline.py's
# analyze() must agree on EXACTLY (absent-in-one == mismatch).  "events"
# and the monitor-only rows stay out: a monitored stream re-analyzed
# from disk legitimately carries the extra invariant_violation rows.
PARITY_FIELDS = (
    "n", "rounds", "tracked_crashes", "detected",
    "ttd_first", "ttd_converged", "ttd_first_median",
    "true_detections", "false_positives", "false_positive_rate",
    "suspicion", "suspects_entered", "refutations", "fp_suppressed",
    "ttd_suspect", "suspect_to_confirm", "suspect_before_confirm",
    "confirm_false_positives", "durability", "client_ops",
)

_MISSING = object()


def estimator_parity(post_hoc: dict, streaming: dict) -> dict:
    """Exact field-for-field comparison over :data:`PARITY_FIELDS`.

    Returns ``{"ok": bool, "mismatches": {field: [post, stream]}}`` —
    the ``monitor_parity`` claim requires ``ok`` on the selfcheck
    stream (tools/timeline.py ``--selfcheck --monitor``).
    """
    mismatches = {}
    for f in PARITY_FIELDS:
        a, b = post_hoc.get(f, _MISSING), streaming.get(f, _MISSING)
        if a is _MISSING and b is _MISSING:
            continue
        if a != b:
            mismatches[f] = [None if a is _MISSING else a,
                             None if b is _MISSING else b]
    return {"ok": not mismatches, "mismatches": mismatches}


def monitor_verdict(events, n: int, params: MonitorParams | None = None,
                    header: dict | None = None) -> dict:
    """One-call verdict for bench surfaces: stream decoded events through
    a fresh monitor, return ``verdict()`` + the headline estimators."""
    mon = StreamMonitor(params=params, n=n)
    if header:
        mon.observe_header(header)
    mon.feed(events)
    mon.finish()
    s = mon.summary()
    return {
        **mon.verdict(),
        "false_positive_rate": s["false_positive_rate"],
        "worst_window_fpr": s["worst_window_fpr"],
        "ttd_first_median": s["ttd_first_median"],
        "violations": s["violations"],
    }
