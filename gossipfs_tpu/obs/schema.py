"""The versioned, typed observability event schema — ONE language for
what happened, across all three transport engines.

Before this module the repo's detection lifecycles lived in three
disjoint forms: ``RoundMetrics`` arrays out of the tensor scan, per-
process free-text-ish log files in deploy, and ``ScenarioStatus`` vitals
over gRPC — answering "what happened to node 777 between crash and
repair" meant hand-correlating artifacts.  SWIM (PAPERS.md #2) and
Lifeguard (PAPERS.md #3) both argue from *per-event* evidence
(suspect/refute/confirm sequences, local-health signals); this schema
makes that evidence streamable and machine-checkable.

One record shape everywhere::

    {"round": r, "observer": i, "subject": j, "kind": k, "detail": {...}}

``observer``/``subject`` are node ids; ``-1`` means "not a single node"
(cluster-wide / ground-truth events).  Streams are JSONL whose FIRST row
is a header (``{"schema": SCHEMA, "source": ..., "n": ...}``) so every
artifact is self-describing; ``tools/timeline.py`` merges streams, and
``obs/recorder.py`` holds the three producers (post-scan decoder, the
``UdpNode`` seam hook, the deploy daemons' structured log).

The maps at the bottom are the LINT surface (tests/test_obs.py): every
``RoundMetrics``/``MetricsCarry`` field and every deploy/cosim log site
must map to a schema kind or be explicitly listed as unexported — new
metrics cannot silently bypass the recorder.
"""

from __future__ import annotations

import dataclasses
import json

# Version tag stamped into every stream header.  Bump on any breaking
# record-shape change; the analyzer refuses unknown majors.
SCHEMA = "gossipfs-obs/v1"

# Sibling schema for the profiler artifacts (ROUNDPROF_*.jsonl /
# stub-bisect rows): a header row stamped by bench/roundprof.py and
# tools/stub_bisect.py so old and new profile artifacts are
# self-describing and the analyzer can ingest them.
ROUNDPROF_SCHEMA = "gossipfs-roundprof/v1"

# ---------------------------------------------------------------------------
# Event kinds — the full lifecycle
# ---------------------------------------------------------------------------

EVENT_KINDS: dict[str, str] = {
    # -- time / per-round observables
    "round_tick": "one completed protocol round; detail carries the "
                  "round's scalar counters (n_alive, true_detections, "
                  "false_positives, suspects_entered, refutations, "
                  "fp_suppressed) — the RoundMetrics row, as an event",
    # -- ground-truth membership events (observer == -1)
    "crash": "subject crash-stopped (CTRL+C / kill -9 / scheduled fault)",
    "hb_freeze": "subject's own heartbeat counter stopped advancing "
                 "(emitted alongside crash: a dead process bumps nothing)",
    "leave": "subject broadcast LEAVE and exited voluntarily",
    "join": "subject (re)joined through the introducer",
    # -- the SWIM detection lifecycle (suspicion/)
    "suspect": "observer marked subject SUSPECT (first local staleness "
               "evidence; observer -1 = 'some observer', from the scan's "
               "any-observer carry)",
    "refute": "a pending suspicion of subject was cancelled by evidence "
              "of life (heartbeat/incarnation advance)",
    "confirm": "a detector declared subject FAILED (the lifecycle's "
               "actual failure declaration; detail.false_positive is "
               "ground truth where the engine knows it)",
    "remove": "subject dropped from a membership list; observer -1 = "
              "dropped from EVERY live observer's list (the scan's "
              "convergence carry)",
    # -- fault injection (scenarios/)
    "scenario_arm": "a FaultScenario rule table was armed",
    "scenario_clear": "the armed scenario was cleared / healed",
    "suspicion_arm": "SuspicionParams armed (suspicion/)",
    "suspicion_clear": "suspicion disarmed",
    # -- SDFS control plane
    "election": "a master election resolved (subject = the new master)",
    "replica_put": "a file version committed (detail.file / version / "
                   "replicas — the nodes that acked; the durability "
                   "audit's write record)",
    "replica_repair": "a replica re-replicated after loss "
                      "(detail.file / source / target)",
    "replica_delete": "a file's metadata + replicas dropped by a client "
                      "delete (detail.file)",
    "replica_lost": "no live replica of a file remains",
    # -- erasure plane (gossipfs_tpu/erasure/, redundancy="stripe")
    "stripe_put": "a striped file version committed (detail.file / "
                  "version / k / m / fragments — the slot-aligned holder "
                  "list, -1 where the fragment did not land; the "
                  "durability audit's stripe write record)",
    "stripe_repair": "missing fragments re-encoded from k survivors "
                     "(detail.file / version / slots / targets; observer "
                     "= the coordinating master)",
    "stripe_lost": "a stripe fell below k live fragments — "
                   "unreconstructable (the MDS data-loss line, not "
                   "total wipeout)",
    # -- traffic plane (traffic/)
    "client_op": "one SDFS client operation completed (detail.op / file / "
                 "bytes / ms / ok) — the open-loop load generator's and "
                 "bench/sdfs_ops.py's per-op latency row",
    # -- online health plane (obs/monitor.py, campaigns/)
    "invariant_violation": "the streaming monitor caught a protocol "
                           "invariant breaking (detail.invariant names "
                           "the row of obs.monitor.INVARIANTS; the "
                           "violating evidence rides detail) — emitted "
                           "INTO the stream so timeline.py and the "
                           "recorder lint maps stay the single source "
                           "of truth",
    "campaign_verdict": "one campaign run's machine-checked verdict "
                        "(tools/campaign.py ledger row: detail carries "
                        "the scenario point, the monitor estimators and "
                        "the violation list)",
    # -- operational
    "node_start": "a deploy node process came up",
}

# Kinds that constitute a subject's detection-lifecycle timeline, in
# canonical order — tools/timeline.py renders/validates against this.
# This tuple is held in exact bijection with the protocol contract's
# emit kinds (analysis/protocol_spec.py): adding a kind here without a
# contract transition/injection row — or vice versa — fails the
# spec-obs-kind-coverage rule and tests/test_protocol_spec.py.
LIFECYCLE_KINDS = (
    "crash", "hb_freeze", "leave", "join",
    "suspect", "refute", "confirm", "remove",
)


@dataclasses.dataclass(frozen=True)
class Event:
    """One schema record (see module docstring for field semantics)."""

    round: int
    observer: int
    subject: int
    kind: str
    detail: dict = dataclasses.field(default_factory=dict)

    def to_record(self) -> dict:
        rec = {"round": self.round, "observer": self.observer,
               "subject": self.subject, "kind": self.kind}
        if self.detail:
            rec["detail"] = self.detail
        return rec

    @staticmethod
    def from_record(rec: dict) -> "Event":
        # deploy node logs carry the writing node as "node" (their
        # Machine.log heritage) — it IS the observer for schema purposes
        observer = rec.get("observer", rec.get("node", -1))
        return Event(
            round=int(rec.get("round", -1)),
            observer=int(observer),
            subject=int(rec.get("subject", -1)),
            kind=rec["kind"],
            detail=rec.get("detail") or {},
        )


def header(source: str, n: int | None = None, **meta) -> dict:
    """The self-describing first row of every event stream."""
    doc = {"schema": SCHEMA, "source": source}
    if n is not None:
        doc["n"] = int(n)
    doc.update(meta)
    return doc


def is_header(rec: dict) -> bool:
    return "schema" in rec and "kind" not in rec


def dumps(rec: dict) -> str:
    return json.dumps(rec, separators=(",", ":"))


# ---------------------------------------------------------------------------
# Uniform vitals — the counter set every engine's `metrics` surface renders
# ---------------------------------------------------------------------------

# One ordered field list for the CLI `metrics` verb, the shim/deploy
# `Vitals` RPC, and the launcher's collector.  A field an engine cannot
# know (ground-truth aliveness off the sim; per-refute aliveness off the
# socket engines) is ABSENT from its document and rendered as `n/a` —
# never as a measured 0 (the round-8 status-shape convention).
VITALS_FIELDS = (
    "engine",           # "sim" | "udp" | "deploy"
    "round",            # the engine's protocol-round clock
    "n_alive",          # ground-truth live count (sim/udp only)
    "members",          # size of the reporting node's view (deploy rows)
    "detections",       # cumulative detector firings seen by the surface
    "false_positives",  # of those, subject actually alive (ground truth)
    "suspects_now",     # live SUSPECT entries (suspicion armed only)
    "suspects_entered",
    "refutations",
    "confirms",
    "fp_suppressed",    # sim-only: refutations of actually-alive subjects
    # -- online health plane (obs/monitor.py): live invariant verdicts.
    # Present only when a StreamMonitor is attached AND the engine can
    # evaluate the invariants (deploy has no ground truth, so its rows
    # omit the field and render n/a — never a fabricated clean 0)
    "invariant_violations",
    # -- traffic plane (traffic/; the CLI `traffic status` verb's set) —
    # engines without an SDFS data plane (udp, deploy today) simply omit
    # them and render n/a, per the round-8 absent-not-zero rule
    "ops_issued",       # client ops (put/get/delete) issued via this plane
    "ops_acked",        # of those, completed (quorum-acked / found / ok)
    "repairs_pending",  # under-replicated files awaiting a repair pass
    "repairs_done",     # re-replication plans executed so far
    # -- erasure plane (redundancy="stripe" only): replica-mode documents
    # OMIT both fields — they render n/a, never a fabricated clean 0,
    # and a clean stripe-mode run reports a real measured 0
    "stripes_degraded",  # stripes below full strength but >= k live
    "fragments_lost",    # missing fragments summed over placed stripes
    # -- wire plane (socket engines only; round 20 delta gossip A/B):
    # cumulative payload bytes actually handed to sendto, and the
    # full-list vs delta-frame split.  The tensor engine has no wire,
    # so its documents omit all three and render n/a
    "bytes_sent",
    "frames_full",      # full member-list frames (anti-entropy included)
    "frames_delta",     # <#DELTA#>-marked bounded frames
)


def na(value):
    """The absent-not-zero rendering, owned HERE: an unknowable counter
    renders as the string ``n/a`` — never as a fabricated clean 0.
    Every surface that prints vitals-shaped values (render_vitals below,
    the CLI ``metrics``/``traffic status``/suspicion verbs) routes
    through this helper; gossipfs-lint's na-render-ownership rule flags
    any other literal ``n/a`` in the tree."""
    return "n/a" if value is None else value


def render_vitals(doc: dict) -> str:
    """One-line uniform rendering; absent fields print as ``n/a``."""
    return " ".join(f"{f}={na(doc.get(f))}" for f in VITALS_FIELDS)


# ---------------------------------------------------------------------------
# Lint maps — how every existing metric/log site reaches this schema
# ---------------------------------------------------------------------------

# core.rounds.RoundMetrics / MetricsCarry field -> the event kind (or
# round_tick counter) the post-scan decoder exports it through.  The
# schema-lint test asserts every field of both NamedTuples appears here
# or in SCAN_UNEXPORTED.
SCAN_FIELD_MAP: dict[str, str] = {
    # RoundMetrics -> round_tick detail counters (one row per round)
    "true_detections": "round_tick",
    "false_positives": "round_tick",
    "n_alive": "round_tick",
    "suspects_entered": "round_tick",
    "refutations": "round_tick",
    "fp_suppressed": "round_tick",
    # MetricsCarry -> per-subject lifecycle events
    "first_detect": "confirm",     # confirm.round
    "first_observer": "confirm",   # confirm.observer
    "converged": "remove",         # remove.round (observer -1)
    "first_suspect": "suspect",    # suspect.round (observer -1)
}

# Scan fields deliberately NOT exported as events (none today; list them
# here WITH a reason if that ever changes, so the lint keeps passing
# honestly instead of being loosened).
SCAN_UNEXPORTED: dict[str, str] = {}

# deploy/node.py + cosim.py log-site kind -> schema kind.  NodeDaemon.log
# rewrites through this map at write time, so the per-node JSONL logs ARE
# schema streams (the structured replacement for the free-text logs) and
# tools/timeline.py ingests them directly.
LOG_KIND_MAP: dict[str, str] = {
    "detect": "confirm",
    "failure_detected": "confirm",   # cosim's EventLog kind
    "re_replicate": "replica_repair",
    "reput": "replica_repair",
    "put": "replica_put",
    "delete": "replica_delete",
    "lost": "replica_lost",
    "elected": "election",
    "new_master": "election",
    "scenario": "scenario_arm",
    "suspicion": "suspicion_arm",
    "start": "node_start",
}

# Log sites that are operational noise, not lifecycle evidence — each
# with the reason it stays out of the event stream.  The lint test
# asserts every `log("<kind>"...)` / `kind="<kind>"` site is in
# LOG_KIND_MAP, UNEXPORTED_LOG_KINDS, or already a schema kind.
UNEXPORTED_LOG_KINDS: dict[str, str] = {
    "repair_error": "per-attempt RPC failure; the retry loop re-detects "
                    "the deficit — the outcome events are replica_repair "
                    "/ replica_lost",
    "reput_miss": "a refused RemoteReput (no local copy); the master's "
                  "retry rotates sources — outcome events cover it",
    "scenario_error": "a rejected ScenarioLoad payload (bad JSON / wrong "
                      "n); nothing armed, no lifecycle state changed",
    "suspicion_error": "a rejected SuspicionLoad payload; same",
    "election_stall": "a no-majority election attempt; retried every "
                      "control tick — the outcome event is `election`",
    "control_error": "control-loop exception kept non-fatal; diagnostics, "
                     "not protocol evidence",
}
