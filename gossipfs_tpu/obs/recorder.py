"""The flight recorder: three producers, one schema, JSONL out.

Backends (all writing ``obs/schema.py`` records):

1. **Post-scan decoder** (:func:`decode_scan`): expands the tensor sim's
   EXISTING scan outputs — the stacked ``RoundMetrics`` and the
   ``MetricsCarry`` per-subject first-detection/convergence vectors —
   into events on the host, after ``run_rounds`` returns.  No new device
   work: the rr/pallas fast paths are untouched and the compiled program
   is bit-identical with or without recording (the <2% overhead bound in
   the acceptance criteria is structural, then measured).

2. **Socket-engine seam hook**: ``detector/udp.py`` ``UdpCluster`` (and
   the deploy ``_Env``) expose ``record_obs``; ``UdpNode``'s tick and
   receive paths call it at the suspect/refute/remove/confirm seams.
   :class:`FlightRecorder` is what a cluster attaches.

3. **Deploy structured logs**: ``deploy/node.py`` writes its per-node
   JSONL through ``schema.LOG_KIND_MAP``, so ``node<i>.log`` IS a schema
   stream ``tools/timeline.py`` merges directly.

This module imports numpy only — the deploy daemons (a documented
jax-free path) use it too.
"""

from __future__ import annotations

import json
import pathlib

import numpy as np

from gossipfs_tpu.obs import schema
from gossipfs_tpu.obs.schema import Event


def load_stream(path) -> tuple[dict | None, list[Event]]:
    """One JSONL stream -> (header row or None, schema events).

    THE one reader of the ``gossipfs-obs/v1`` line format — the
    post-hoc analyzer (``tools/timeline.py``) and the streaming monitor
    (``obs/monitor.py feed_jsonl``) both parse through here, so the two
    derivations the ``monitor_parity`` oracle compares can never read a
    stream differently.  Tolerates deploy node logs (no header;
    ``node`` names the observer) and skips rows carrying no schema kind
    (free-text legacy lines, campaign-ledger metadata).
    """
    header = None
    events: list[Event] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # free-text line in a legacy log
            if i == 0 and schema.is_header(rec):
                header = rec
                continue
            if rec.get("kind") in schema.EVENT_KINDS:
                events.append(Event.from_record(rec))
    return header, events


class FlightRecorder:
    """Accumulates schema events, optionally mirrored to a JSONL file.

    The header row is written on construction; events append in arrival
    order.  ``events`` is always available in memory (the parity tests
    and the timeline selfcheck read it without touching disk).
    """

    def __init__(self, path: str | pathlib.Path | None = None,
                 source: str = "sim", n: int | None = None, **meta):
        self.header = schema.header(source, n=n, **meta)
        self.events: list[Event] = []
        self._fh = None
        if path is not None:
            self._fh = open(path, "w", encoding="utf-8")
            self._fh.write(schema.dumps(self.header) + "\n")

    def emit(self, ev: Event) -> None:
        self.events.append(ev)
        if self._fh is not None:
            self._fh.write(schema.dumps(ev.to_record()) + "\n")

    def extend(self, events) -> None:
        for ev in events:
            self.emit(ev)

    def flush(self) -> None:
        if self._fh is not None:
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # convenience for tests / the analyzer
    def kinds(self, subject: int | None = None) -> list[str]:
        return [e.kind for e in self.events
                if subject is None or e.subject == subject]


def decode_scan(
    per_round,
    mcarry,
    *,
    n: int,
    start_round: int = 0,
    crash_rounds: dict[int, int] | None = None,
    alive=None,
    suspicion: bool = False,
    n_effective: int | None = None,
) -> list[Event]:
    """Expand a finished scan's outputs into schema events (host-side).

    ``per_round``: the stacked ``RoundMetrics`` over the horizon;
    ``mcarry``: the final ``MetricsCarry``; ``start_round``: the state's
    round counter when the scan began (events stamp absolute rounds);
    ``crash_rounds``: {node: round} for scheduled/tracked faults (emits
    ground-truth ``crash`` + ``hb_freeze`` rows); ``alive``: final
    ground-truth liveness [N] — when given, ``confirm`` events carry
    ``detail.false_positive`` exactly like the interactive path's
    DetectionEvents.  ``suspicion``: whether the SWIM lifecycle was
    armed (gates the per-subject ``suspect`` rows and the suspicion
    counters in ``round_tick``).  ``n_effective``: live-cohort size for
    PADDED runs (bench/frontier.py's literal-N padding) — permanently
    dead alignment pads past it "converge" at the scan's first round,
    and without the mask each would export a phantom ``remove`` row.

    Consumes arrays the scan already returned — every np.asarray below
    is a host transfer of data the caller's ``summarize`` reads anyway.
    """
    events: list[Event] = []
    tp = np.asarray(per_round.true_detections)
    fp = np.asarray(per_round.false_positives)
    na = np.asarray(per_round.n_alive)
    se = np.asarray(per_round.suspects_entered)
    rf = np.asarray(per_round.refutations)
    fs = np.asarray(per_round.fp_suppressed)
    rounds = len(tp)

    # ground-truth fault rows first (they precede everything they cause)
    for node, r0 in sorted((crash_rounds or {}).items()):
        events.append(Event(round=int(r0), observer=-1, subject=int(node),
                            kind="crash", detail={"scheduled": True}))
        events.append(Event(round=int(r0), observer=-1, subject=int(node),
                            kind="hb_freeze"))

    # one round_tick per round — the RoundMetrics row as an event.  Every
    # round is emitted (not just eventful ones): the analyzer's FPR
    # denominator needs n_alive for the whole horizon.
    for i in range(rounds):
        detail = {
            "n_alive": int(na[i]),
            "true_detections": int(tp[i]),
            "false_positives": int(fp[i]),
        }
        if suspicion:
            detail.update(suspects_entered=int(se[i]),
                          refutations=int(rf[i]),
                          fp_suppressed=int(fs[i]))
        events.append(Event(round=start_round + i, observer=-1, subject=-1,
                            kind="round_tick", detail=detail))

    first = np.asarray(mcarry.first_detect)
    obs_v = np.asarray(mcarry.first_observer)
    conv = np.asarray(mcarry.converged)
    first_sus = np.asarray(mcarry.first_suspect)
    alive_h = None if alive is None else np.asarray(alive)
    end = start_round + rounds

    n_eff = n if n_effective is None else n_effective

    def window(v: np.ndarray) -> np.ndarray:
        # subjects whose event landed in THIS scan's horizon — nonzero
        # over the vector, so a quiet N=100k trace costs O(events) python.
        # Alignment pads (subjects >= n_eff) never export: they were
        # never members, so their carries are artifacts, not lifecycle.
        in_w = (v >= start_round) & (v < end)
        in_w[n_eff:] = False
        return np.nonzero(in_w)[0]

    if suspicion:
        for j in window(first_sus):
            events.append(Event(round=int(first_sus[j]), observer=-1,
                                subject=int(j), kind="suspect"))
    for j in window(first):
        detail = {}
        if alive_h is not None:
            detail["false_positive"] = bool(alive_h[j])
        events.append(Event(round=int(first[j]), observer=int(obs_v[j]),
                            subject=int(j), kind="confirm", detail=detail))
    for j in window(conv):
        events.append(Event(round=int(conv[j]), observer=-1,
                            subject=int(j), kind="remove"))
    events.sort(key=lambda e: e.round)
    return events


def write_trace(
    path: str | pathlib.Path,
    per_round,
    mcarry,
    *,
    n: int,
    source: str,
    start_round: int = 0,
    crash_rounds: dict[int, int] | None = None,
    alive=None,
    suspicion: bool = False,
    n_effective: int | None = None,
    **meta,
) -> int:
    """One-call trace emission for the bench ``--trace PATH`` flags.

    Decodes the scan and writes header + events; returns the event
    count.  ``crash_rounds`` lands in the header too, so the analyzer
    can compute TTD without re-deriving the fault schedule, and
    ``n_effective`` both masks the pad subjects out of the decode and
    names the FPR cohort in the header.
    """
    if crash_rounds:
        meta["crash_rounds"] = {str(k): int(v)
                                for k, v in sorted(crash_rounds.items())}
    if n_effective is not None:
        meta["n_effective"] = int(n_effective)
    rec = FlightRecorder(path, source=source, n=n,
                         start_round=start_round, suspicion=suspicion,
                         **meta)
    try:
        rec.extend(decode_scan(
            per_round, mcarry, n=n, start_round=start_round,
            crash_rounds=crash_rounds, alive=alive, suspicion=suspicion,
            n_effective=n_effective,
        ))
    finally:
        rec.close()
    return len(rec.events)
