"""Opt-in ``jax.profiler`` hook around the scan — xprof for the headline.

The repo's perf evidence so far is wall-clock + stub bisection; an xprof
trace of the headline shape (open in Perfetto / TensorBoard, or reduce
with ``utils/profiling.op_breakdown``) is the missing device-level view.
This module is the small seam the benches use so the NEXT TPU session
captures one alongside the BENCH numbers::

    python bench.py --xprof /tmp/xprof_headline

Opt-in by construction: with no directory the context is a no-op and
the benches' timed loops are untouched.  Import of jax is deferred into
the armed branch so merely importing this module stays cheap.
"""

from __future__ import annotations

import contextlib
import pathlib
from typing import Iterator


@contextlib.contextmanager
def maybe_xprof(log_dir: str | pathlib.Path | None) -> Iterator[None]:
    """``with maybe_xprof(args.xprof):`` — jax.profiler.trace when a
    directory is given, a no-op otherwise."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(str(log_dir)):
        yield


def xprof_summary(log_dir: str | pathlib.Path, top: int = 10) -> list[dict]:
    """Top device ops from a captured trace (empty on parse failure —
    the bench must not die because a trace file is missing/odd)."""
    try:
        from gossipfs_tpu.utils.profiling import op_breakdown

        return op_breakdown(log_dir, top=top)
    except Exception:  # noqa: BLE001 — diagnostics only
        return []
