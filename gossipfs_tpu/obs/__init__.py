"""Observability: one event schema, a flight recorder, and profiling hooks.

* ``obs/schema.py`` — the versioned record shape + kind registry + the
  uniform vitals field set (and the lint maps tying every existing
  metric/log site to it);
* ``obs/recorder.py`` — the flight recorder's three backends: the
  post-scan decoder over the tensor sim's existing outputs (no new
  device work), the ``UdpNode`` seam hook, and the deploy daemons'
  structured JSONL logs;
* ``obs/monitor.py`` — the ONLINE health plane: a streaming invariant
  monitor (incremental TTD/FPR/durability estimators + the declarative
  invariant table) that rides any ``attach_recorder`` surface via
  ``MonitorRecorder`` and must agree with ``tools/timeline.py``'s
  post-hoc derivation exactly (the ``monitor_parity`` claim);
* ``obs/profile.py`` — the opt-in ``jax.profiler`` trace hook around
  the scan.

``tools/timeline.py`` is the consumer: it merges per-node streams,
reconstructs per-subject crash -> SUSPECT -> confirm -> REMOVE -> repair
timelines, and re-derives TTD/FPR from events alone as a standing
cross-check against ``metrics/detection.summarize``.

The recorder exports resolve LAZILY (module ``__getattr__``), the same
pattern as ``scenarios/``: the deploy daemons — a documented jax-free
path that must start in milliseconds — import ``obs.schema`` through
this package for their structured logs, and an eager recorder import
would pull numpy into every daemon at boot.
"""

from gossipfs_tpu.obs.schema import (
    EVENT_KINDS,
    SCHEMA,
    VITALS_FIELDS,
    Event,
    render_vitals,
)

_RECORDER_EXPORTS = ("FlightRecorder", "decode_scan", "load_stream",
                     "write_trace")
_MONITOR_EXPORTS = ("INVARIANTS", "MonitorParams", "MonitorRecorder",
                    "StreamMonitor", "estimator_parity", "monitor_verdict")

__all__ = [
    "EVENT_KINDS",
    "SCHEMA",
    "VITALS_FIELDS",
    "Event",
    "render_vitals",
    *_RECORDER_EXPORTS,
    *_MONITOR_EXPORTS,
]


def __getattr__(name: str):
    if name in _RECORDER_EXPORTS:
        from gossipfs_tpu.obs import recorder

        return getattr(recorder, name)
    if name in _MONITOR_EXPORTS:
        from gossipfs_tpu.obs import monitor

        return getattr(monitor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
