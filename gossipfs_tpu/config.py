"""Simulation configuration.

The reference hardcodes every protocol parameter as Go package constants
(reference: slave/slave.go:21-29, main.go:10-12).  Here they live in one typed,
hashable config so a single compiled round kernel can be reused across the five
BASELINE.json benchmark configs.

Reference constants reproduced (see BASELINE.md):
  heartbeat period 1 s  -> 1 round == 1 s of simulated time
  failure timeout 5 s   -> t_fail = 5 rounds      (slave/slave.go:24)
  fail-list cooldown 5 s-> t_cooldown = 5 rounds  (slave/slave.go:25)
  minimum group size 4  -> min_group = 4          (slave/slave.go:504,511)
  fanout 3 ring         -> topology="ring", fanout=3 (slave/slave.go:517-519)
"""

from __future__ import annotations

import dataclasses
import math
from typing import TYPE_CHECKING, Literal

if TYPE_CHECKING:  # runtime import is lazy (see __post_init__) — the
    # suspicion package must stay importable from jax-free daemons
    from gossipfs_tpu.suspicion.params import SuspicionParams

Topology = Literal["ring", "random", "random_arc"]

# The ``age`` lane is stored as int8 and saturates here: every protocol
# comparison is against a small threshold (t_fail, t_cooldown), so any age
# beyond the clamp behaves identically.  Kept at 63 (6 bits) so age and
# status (2 bits) pack into ONE byte on the resident-round kernel's wire —
# the packing that cuts the round's HBM traffic by a third
# (ops/merge_pallas.resident_round_blocked); SimConfig rejects thresholds
# that would need deeper ages.
AGE_CLAMP = 63

# Per-subject heartbeat rebasing windows for the gossip view (core/rounds.py
# ``_merge``).  Gossipable entries lag the freshest copy of a subject's
# counter by O(t_fail) rounds per hop, so the reachable lag is
# ~t_fail * graph diameter: a handful of rounds for random fanout=log N
# (diameter ~4), up to ~N/2 rounds for the 3-neighbor parity ring.  The
# window bounds the rebased values, which picks the view dtype — and view
# bytes are the round's dominant HBM traffic (the F-way row gather):
#   int16 (window 16384): covers every topology up to ring N~32k; 2 B/elem
#   int8  (window 126):   random-fanout topologies only; 1 B/elem — halves
#                         the merge's DMA traffic again (bench headline)
# REBASE_WINDOW doubles as the window for hb_dtype="int16" *storage*
# (counters kept relative to the monotone per-subject ``hb_base``, see
# core/rounds.py _merge): live lanes stay within [base, base + window], so
# 16384 leaves half the int16 range as slack below the base.
REBASE_WINDOW = 16_384
INT8_REBASE_WINDOW = 126


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static (trace-time) parameters of the gossip simulation.

    Frozen + hashable so it can be closed over by ``jax.jit`` without
    retriggering compilation when reused.
    """

    n: int = 1024                    # number of simulated nodes (fixed; churn via masks)
    fanout: int = 3                  # gossip in-degree per round
    topology: Topology = "ring"      # "ring" = reference parity; "random" = north star
    arc_align: int = 1               # "random_arc" base granularity: bases are drawn
                                     # as multiples of this, and fanout must be a
                                     # multiple of it.  At 8, the rr kernel's windowed
                                     # row-max collapses to one 8-way group reduction
                                     # riding the view build plus a pair-max over
                                     # N/8 group rows (~1 pass over the stripe
                                     # instead of ~5 shift-doubling passes).  Aligned
                                     # arcs may include the receiver itself — a
                                     # provable merge no-op (the view is built from
                                     # the same ticked state the receiver holds), so
                                     # coverage is the plain arc's minus an O(F/N)
                                     # correction; bench/curves.py measures parity
    t_fail: int = 5                  # rounds without hb advance before declaring failure
    t_cooldown: int = 5              # rounds a removed member stays on the fail list
    min_group: int = 4               # below this list size a node only refreshes timestamps
    hb_grace: int = 1                # only detect members with hb_count > hb_grace
                                     # (reference: slave/slave.go:468-469)
    remove_broadcast: bool = True    # detector broadcasts REMOVE to everyone in one round
                                     # (reference: slave/slave.go:338-363); False = pure
                                     # gossip dissemination of failures (north-star mode)
    fresh_cooldown: bool = False     # False = reference-faithful: a removed entry keeps
                                     # its stale gossip timestamp on the fail list
                                     # (slave/slave.go:276-286), so detector removals
                                     # expire ~immediately and zombie re-adds can cycle
                                     # when remove_broadcast is off.  True = stamp the
                                     # fail-list entry at removal time, giving the full
                                     # t_cooldown suppression (required for convergence
                                     # in gossip-only dissemination mode)
    introducer: int = 0              # node index playing the hardcoded introducer
                                     # (reference: slave/slave.go:22)
    merge_block_r: int = 128         # pallas merge tile: receiver rows per block
    merge_block_c: int = 8192        # pallas merge tile: subject columns per DMA —
                                     # larger units amortize DMA descriptor issue,
                                     # the kernel's limiter once the view is a
                                     # narrow dtype (view_dtype below)
    merge_slots: int = 4             # pallas merge DMA double-buffer depth
    merge_kernel: str = "xla"        # "xla" | "pallas" | "pallas_stripe":
                                     # implementation of the per-round fanout
                                     # max-merge (the hot op).  "pallas" is
                                     # the DMA-gather TPU kernel
                                     # (ops/merge_pallas.py, ~4x the XLA
                                     # gather's bandwidth); "pallas_stripe"
                                     # keeps each view column block resident
                                     # in VMEM so the view moves over HBM
                                     # once per round instead of F times
                                     # (needs merge_block_c=4096 and
                                     # N <= ~16k — see
                                     # merge_pallas.stripe_supported);
                                     # "*_interpret" variants run the same
                                     # kernels in interpreter mode (CPU
                                     # tests only — slow).
                                     # Round 11 (fast-path unification):
                                     # scenario edge filters and the
                                     # suspicion lifecycle run on EVERY
                                     # merge kernel — scenario runs
                                     # rewrite the sampled [N, F] edges
                                     # (aligned arcs: group-granular
                                     # match masks) before any gather,
                                     # and the SUSPECT/refute transitions
                                     # are fused into the pallas/rr
                                     # epilogues and the rr packed tick.
                                     # The old forced-"xla" substitution
                                     # is retired; fallback_config()
                                     # below remains for explicitly
                                     # requesting the oracle path.
    view_dtype: str = "int16"        # gossip-view storage: "int16" | "int8".
                                     # int8 halves the merge's HBM traffic but
                                     # its 126-round rebase window only covers
                                     # short-diameter (random) topologies —
                                     # rejected for the parity ring
    hb_dtype: str = "int32"          # heartbeat-lane storage: "int32" (exact
                                     # counters, reference parity) | "int16"
                                     # (counters stored relative to the
                                     # per-subject ``hb_base``, renormalized
                                     # every round by the merge — halves the
                                     # fattest lane's HBM traffic and memory;
                                     # random topologies only, same lag
                                     # argument as the view rebase) | "int8"
                                     # (storage window == the int8 view's
                                     # 126 rounds: every matrix lane is then
                                     # int8, which lets XLA pack the
                                     # ALU-bound round 4x denser AND fuse
                                     # the epilogue's outputs into one pass;
                                     # requires view_dtype="int8")
    elementwise: str = "lanes"       # implementation of the round's elementwise
                                     # compare/select/age math over int8 lanes:
                                     # "lanes" widens every int8 element to its
                                     # own i32 VPU slot (ordered compares exist
                                     # only at i32 width on v5e Mosaic — see
                                     # BASELINE.md round-5 probes); "swar" packs
                                     # 4 subjects per i32 word and runs the
                                     # compares/selects with carry-safe bitwise
                                     # arithmetic (ops/swar.py) — 4 subjects per
                                     # VPU op, same bits (pinned by the swar
                                     # parity tests + golden fuzz).  Applies to
                                     # the XLA membership-update/tick epilogues
                                     # and the resident-round pallas kernel;
                                     # requires the all-int8 state
                                     # (hb_dtype="int8")
    rr_resident: str = "auto"        # resident-lanes mode of the rr kernel:
                                     # park the raw lanes in VMEM during the
                                     # view-build read so the receiver sweep
                                     # re-reads nothing from HBM — the round
                                     # moves the 4 N^2-byte information floor.
                                     # "auto": on whenever the 3 stripes fit
                                     # VMEM (merge_pallas.
                                     # rr_resident_supported); "on": require
                                     # it (error if it cannot fit); "off":
                                     # always stream receiver blocks
    rr_rotate: str = "auto"          # rr kernel row-budget layouts (round 9):
                                     # "auto" runs the ring-rotated aligned-
                                     # arc view build (window group maxes
                                     # rotate through a fixed ring; only the
                                     # int8 W gather buffer scales with rows)
                                     # + the LANE-compacted flags block
                                     # (1 B/row vs LANE B/row) wherever the
                                     # blocking admits them — what lifts the
                                     # sharded aligned rr past ~367k rows at
                                     # merge_block_c=512.  "off" restores the
                                     # round-5 full-T/replicated layouts
                                     # (bench.py's on-chip probe fallback,
                                     # same bits either way — pinned by the
                                     # rotate A/B parity tests)
    suspicion: "SuspicionParams | None" = None
                                     # SWIM suspect/refute lifecycle
                                     # (suspicion/params.py): silent
                                     # members pass through SUSPECT for
                                     # t_suspect rounds (refutable by any
                                     # heartbeat advance) before FAILED.
                                     # None = the reference's direct
                                     # crash-on-timeout.  Requires the
                                     # gossip-only protocol mode
                                     # (remove_broadcast off + fresh
                                     # cooldown).  Round 11 fused the
                                     # lifecycle into every merge kernel
                                     # and both elementwise forms; round
                                     # 14 fused the Lifeguard stretch
                                     # too (lh_multiplier > 0: the rr
                                     # scan carries per-receiver SUSPECT
                                     # counts and the kernel applies the
                                     # stretched confirm threshold as a
                                     # per-row select on flags bit 4) —
                                     # no degradation remains
    fused_tick: str = "auto"         # "auto": rounds with no join/leave events
                                     # and remove_broadcast off fuse the
                                     # heartbeat tick (bump/detect/cooldown)
                                     # into the merge epilogue so the [N, N]
                                     # lanes are read+written once per round
                                     # (core/rounds._round_core_fused; the
                                     # TPU stripe kernel runs the whole tick
                                     # in-kernel).  "off": always use the
                                     # separate-pass round (debug/parity)

    def __post_init__(self) -> None:
        if self.n < 2:
            raise ValueError(f"n must be >= 2, got {self.n}")
        if not (0 < self.fanout < self.n):
            raise ValueError(f"fanout must be in (0, n), got {self.fanout}")
        if self.topology == "ring" and self.fanout != 3:
            raise ValueError("ring (parity) topology is defined for fanout=3")
        if self.arc_align < 1 or (self.arc_align & (self.arc_align - 1)):
            raise ValueError(
                f"arc_align must be a power of two >= 1, got {self.arc_align}"
            )
        if self.arc_align > 1:
            if self.topology != "random_arc":
                raise ValueError("arc_align > 1 requires topology='random_arc'")
            if self.fanout % self.arc_align or self.n % self.arc_align:
                raise ValueError(
                    "arc_align must divide both fanout and n "
                    f"(align={self.arc_align}, fanout={self.fanout}, "
                    f"n={self.n})"
                )
        if self.t_fail < 1 or self.t_cooldown < 0:
            raise ValueError("t_fail >= 1 and t_cooldown >= 0 required")
        if self.t_fail >= AGE_CLAMP or self.t_cooldown >= AGE_CLAMP:
            raise ValueError(
                f"t_fail and t_cooldown must be < AGE_CLAMP ({AGE_CLAMP}); "
                "the age lane saturates there"
            )
        if self.merge_kernel not in (
            "xla", "pallas", "pallas_interpret",
            "pallas_stripe", "pallas_stripe_interpret",
            "pallas_rr", "pallas_rr_interpret",
        ):
            raise ValueError(f"unknown merge_kernel: {self.merge_kernel!r}")
        if self.merge_kernel.startswith("pallas_rr"):
            # the resident-round kernel (whole round in one pallas call —
            # ops/merge_pallas.resident_round_blocked) additionally needs
            # the all-int8 state; shape constraints match the stripe kernel
            if self.hb_dtype != "int8":
                raise ValueError("merge_kernel='pallas_rr' requires "
                                 "hb_dtype='int8'")
        if self.merge_kernel.startswith(("pallas_stripe", "pallas_rr")):
            if self.topology == "ring":
                # ring stays on the 2-D path; the stripe kernel is
                # blocked-layout only
                raise ValueError(f"merge_kernel={self.merge_kernel!r} "
                                 "requires topology='random'")
            if self.view_dtype != "int8":
                # the stripe VMEM budget is counted in bytes at 1 B/elem;
                # a wider view would double the resident stripe past it
                raise ValueError(f"merge_kernel={self.merge_kernel!r} "
                                 "requires view_dtype='int8'")
            from gossipfs_tpu.ops.merge_pallas import (
                RR_ACC_STRIPES,
                RR_BLOCK_CS,
                STRIPE_BLOCK_C,
                STRIPE_MAX_BYTES,
                rr_supported,
                stripe_supported,
            )

            if self.merge_kernel.startswith("pallas_rr"):
                # the rr kernel accepts narrower resident stripes — the
                # capacity lever: N * merge_block_c bytes must fit VMEM,
                # so N=65,536 runs at merge_block_c=1024.
                #
                # Deep-stripe gate, GLOBAL count by design: what actually
                # selects the lane-compacted accumulator is the PER-SHARD
                # stripe count (nloc/merge_block_c — ops/merge_pallas.py
                # keys on n_cols), so under run_rounds_sharded a config
                # this check rejects could be legal on every shard.  The
                # config cannot know the mesh size (it is a frozen,
                # mesh-free protocol object shared by single-chip and
                # sharded callers), so it enforces the worst case — the
                # single-chip run — and stays intentionally conservative
                # for sharded ones.  The cost is nil in practice: sharded
                # capacity configs already run merge_block_r in {128, 256,
                # 512} (ANCHORS_r05.json), all multiples of 128.
                if (self.n // self.merge_block_c > RR_ACC_STRIPES
                        and self.merge_block_r % 128):
                    raise ValueError(
                        "deep-stripe rr shapes (n/merge_block_c > "
                        f"{RR_ACC_STRIPES}) use the lane-compacted count "
                        "accumulator, which needs merge_block_r % 128 == 0 "
                        f"(got {self.merge_block_r}).  The stripe count is "
                        "checked GLOBALLY (conservative for sharded runs — "
                        "see the comment above this check)"
                    )
                if not rr_supported(
                    self.n, self.fanout, self.merge_block_c,
                    arc_align=(self.arc_align
                               if self.topology == "random_arc" else 1),
                    block_r=self.merge_block_r,
                    rotate=self.rr_rotate != "off",
                ):
                    raise ValueError(
                        f"merge_kernel={self.merge_kernel!r} needs "
                        f"merge_block_c in {RR_BLOCK_CS} with "
                        f"n * merge_block_c <= {STRIPE_MAX_BYTES} B "
                        f"(n={self.n}, merge_block_c={self.merge_block_c})"
                    )
                if self.rr_resident == "on":
                    from gossipfs_tpu.ops.merge_pallas import (
                        RR_RESIDENT_MAX_BYTES,
                        rr_resident_supported,
                    )

                    if not rr_resident_supported(
                        self.n, self.fanout, self.merge_block_c,
                        arc_align=(self.arc_align
                                   if self.topology == "random_arc" else 1),
                        block_r=self.merge_block_r,
                        rotate=self.rr_rotate != "off",
                    ):
                        raise ValueError(
                            "rr_resident='on' needs 3 * n * merge_block_c "
                            f"<= {RR_RESIDENT_MAX_BYTES} B of VMEM "
                            f"(n={self.n}, "
                            f"merge_block_c={self.merge_block_c})"
                        )
            else:
                if self.merge_block_c != STRIPE_BLOCK_C:
                    raise ValueError(
                        f"merge_kernel={self.merge_kernel!r} requires "
                        f"merge_block_c={STRIPE_BLOCK_C} (the VMEM-resident "
                        f"stripe width), got {self.merge_block_c}"
                    )
                if not stripe_supported(self.n, self.fanout):
                    # reject eagerly rather than silently running the XLA
                    # path: N must be lane-aligned, a multiple of the
                    # stripe width, and small enough to fit VMEM
                    raise ValueError(
                        f"merge_kernel={self.merge_kernel!r} unsupported at "
                        f"n={self.n} (needs n % {STRIPE_BLOCK_C} == 0 and "
                        f"n * {STRIPE_BLOCK_C} <= {STRIPE_MAX_BYTES} B of VMEM)"
                    )
        if self.rr_resident not in ("auto", "on", "off"):
            raise ValueError(f"unknown rr_resident: {self.rr_resident!r}")
        if self.rr_rotate not in ("auto", "off"):
            raise ValueError(f"unknown rr_rotate: {self.rr_rotate!r}")
        if self.elementwise not in ("lanes", "swar"):
            raise ValueError(f"unknown elementwise: {self.elementwise!r}")
        if self.elementwise == "swar" and self.hb_dtype != "int8":
            # the SWAR word math packs 4 int8 subjects per i32 and relies
            # on every lane (hb, age, status, view) being one byte
            raise ValueError("elementwise='swar' requires hb_dtype='int8'")
        if self.fused_tick not in ("auto", "off"):
            raise ValueError(f"unknown fused_tick: {self.fused_tick!r}")
        if self.suspicion is not None:
            # SWIM suspect/refute lifecycle.  Round 11 removed the
            # merge_kernel="xla" / elementwise="lanes" construction gates:
            # the lifecycle is fused into every merge path (XLA lanes +
            # SWAR epilogues, the stripe/arc kernels' in-kernel epilogue,
            # and the resident-round packed tick/merge — see
            # ops/merge_pallas.py and suspicion/tensor.py's capability
            # notes).  What remains checkable at construction is the
            # dissemination mode and the age-lane clock budget.
            from gossipfs_tpu.suspicion.params import SuspicionParams
            from gossipfs_tpu.suspicion.tensor import (
                require_suspicion_config,
            )

            if not isinstance(self.suspicion, SuspicionParams):
                raise ValueError(
                    "suspicion must be a suspicion.SuspicionParams, got "
                    f"{type(self.suspicion).__name__}"
                )
            # the dissemination-mode requirements have ONE owner
            # (suspicion/tensor.py documents the why)
            require_suspicion_config(self)
            worst = self.suspicion.max_confirm_after(self.t_fail)
            if worst >= AGE_CLAMP:
                raise ValueError(
                    f"t_fail + t_suspect * (1 + lh_multiplier) = {worst} "
                    f"must be < AGE_CLAMP ({AGE_CLAMP}); the age lane — "
                    "which carries the suspicion clock — saturates there"
                )
        if self.view_dtype not in ("int16", "int8"):
            raise ValueError(f"unknown view_dtype: {self.view_dtype!r}")
        if self.hb_dtype not in ("int32", "int16", "int8"):
            raise ValueError(f"unknown hb_dtype: {self.hb_dtype!r}")
        if self.hb_dtype != "int32" and self.topology == "ring":
            # stored counters sit within a rebase window of the per-subject
            # maximum; ring lag grows ~N/2 and can cross that window
            raise ValueError(
                f"hb_dtype={self.hb_dtype!r} requires a random topology"
            )
        if self.hb_dtype == "int8" and self.view_dtype != "int8":
            # the narrow arithmetic's overflow-freedom relies on the view
            # and storage windows coinciding (shift_a <= diagonal advance)
            raise ValueError("hb_dtype='int8' requires view_dtype='int8'")
        if self.view_dtype == "int8":
            if self.topology == "ring":
                # steady-state ring lag grows with graph distance (~N/2
                # rounds), which blows through int8's 126-round rebase window
                # for any non-toy N; the parity path stays on int16
                raise ValueError("view_dtype='int8' requires topology='random'")
            # the window invariant is lag ~ t_fail per hop over the gossip
            # graph's effective diameter (~log_{fanout+1} N for per-round
            # resampled random fanout); enforce it with a 2x safety factor so
            # large t_fail or tiny fanout can't silently drop lagging entries
            # out of the gossip view (core/rounds.py ``gossiped = rel >= 0``)
            hops = math.ceil(math.log(self.n) / math.log(self.fanout + 1))
            if self.t_fail * (hops + 1) * 2 > INT8_REBASE_WINDOW:
                raise ValueError(
                    f"view_dtype='int8': t_fail={self.t_fail} x estimated "
                    f"graph diameter ({hops} hops at fanout={self.fanout}, "
                    f"n={self.n}) exceeds the {INT8_REBASE_WINDOW}-round "
                    "rebase window (with 2x margin); use int16 or raise fanout"
                )
        for name, lo in (("merge_block_r", 8), ("merge_block_c", 128)):
            v = getattr(self, name)
            # the kernel shrinks blocks by halving until they tile N, which
            # only terminates sanely for powers of two
            if v < lo or (v & (v - 1)) != 0:
                raise ValueError(f"{name} must be a power of two >= {lo}, got {v}")
        if self.merge_slots < 2:
            raise ValueError(f"merge_slots must be >= 2, got {self.merge_slots}")

    @property
    def rebase_window(self) -> int:
        """Rebase window matching ``view_dtype`` (see module constants)."""
        return INT8_REBASE_WINDOW if self.view_dtype == "int8" else REBASE_WINDOW

    @staticmethod
    def log_fanout(n: int) -> int:
        """North-star fanout = ceil(log2 N), the BASELINE.json 100k config."""
        return max(1, math.ceil(math.log2(max(n, 2))))

    @classmethod
    def suspicion_rr(cls, n: int, block_c: int = 1024, t_fail: int = 3,
                     t_suspect: int = 2, interpret: bool = False,
                     **overrides) -> "SimConfig":
        """The rr capacity profile with the SWIM lifecycle armed at the
        fast knob (SUSPECT_r08's t_fail=3 + t_suspect=2) — the round-11
        fused fast path's production config, shared by the benches and
        the fastpath-parity tests so none of them drift."""
        from gossipfs_tpu.suspicion.params import SuspicionParams

        kw = dict(
            t_fail=t_fail,
            suspicion=SuspicionParams(t_suspect=t_suspect),
        )
        kw.update(overrides)
        return cls.packed_rr(n, block_c, interpret=interpret, **kw)

    @classmethod
    def packed_rr(cls, n: int, block_c: int = 1024,
                  interpret: bool = False, **overrides) -> "SimConfig":
        """The resident-round capacity profile — ONE definition of the
        rr-kernel protocol config shared by the frontier bench, the
        ``--packed`` CLI, and PackedDetector tests (a drifted copy in any
        of them would silently change the measured protocol)."""
        kw = dict(
            n=n, topology="random", fanout=cls.log_fanout(n),
            remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
            merge_kernel="pallas_rr_interpret" if interpret else "pallas_rr",
            merge_block_c=block_c, view_dtype="int8", hb_dtype="int8",
        )
        kw.update(overrides)
        return cls(**kw)


def fallback_config(
    config: SimConfig, suspicion: "SuspicionParams | None" = None
) -> SimConfig:
    """THE oracle-path substitution (one owner — round 11).

    Returns the ``merge_kernel="xla"`` + ``elementwise="lanes"`` form of
    ``config`` (optionally arming ``suspicion``), preserving everything
    protocol-level (dtypes, thresholds, topology, dissemination mode).

    Since the fast-path unification the fast kernels run scenarios and
    suspicion natively, so nothing *requires* this substitution anymore;
    it survives for explicitly requesting the XLA oracle — parity
    baselines, A/B bisection, the deprecated
    ``scenarios.tensor.xla_fallback_config`` /
    ``suspicion.with_suspicion`` aliases.
    """
    rep: dict = {}
    if suspicion is not None:
        from gossipfs_tpu.suspicion.tensor import require_suspicion_config

        require_suspicion_config(config)
        rep["suspicion"] = suspicion
    if config.merge_kernel != "xla":
        rep["merge_kernel"] = "xla"
    if config.elementwise != "lanes":
        rep["elementwise"] = "lanes"
    return dataclasses.replace(config, **rep) if rep else config
