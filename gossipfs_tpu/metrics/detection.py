"""Detection-quality metrics: time-to-detect, convergence, false-positive rate.

The reference's entire benchmarking apparatus is one wall-clock print in
``Get`` (slave/slave.go:888-890) and grep over Machine.log (report.pdf,
"Testing").  Here the BASELINE.md curves — time-to-detect and FPR vs N —
are array reductions over the sim outputs.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from gossipfs_tpu.core.rounds import MetricsCarry, RoundMetrics


@dataclasses.dataclass
class DetectionReport:
    """Summary of one simulation run's failure-detection behavior."""

    n: int
    rounds: int
    # per tracked crash: rounds from crash to first detection / full removal
    ttd_first: dict[int, int]        # node -> rounds (or -1 if never detected)
    ttd_converged: dict[int, int]    # node -> rounds (or -1 if never converged)
    true_detections: int
    false_positives: int
    false_positive_rate: float       # FP events / (alive-observer x subject x round)
    final_alive: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def summarize(
    carry: MetricsCarry,
    per_round: RoundMetrics,
    crash_rounds: dict[int, int] | None = None,
) -> DetectionReport:
    """Reduce sim outputs to a DetectionReport.

    ``crash_rounds``: {node: round it was crashed} for scheduled faults whose
    detection latency should be reported.
    """
    first = np.asarray(carry.first_detect)
    conv = np.asarray(carry.converged)
    tp = np.asarray(per_round.true_detections)
    fp = np.asarray(per_round.false_positives)
    n_alive = np.asarray(per_round.n_alive)
    rounds = len(tp)
    n = first.shape[0]

    ttd_first, ttd_conv = {}, {}
    for node, r0 in (crash_rounds or {}).items():
        ttd_first[node] = int(first[node] - r0) if first[node] >= 0 else -1
        ttd_conv[node] = int(conv[node] - r0) if conv[node] >= 0 else -1

    # opportunities ~= sum over rounds of alive * (n - 1) observer-subject pairs
    opportunities = float(np.sum(n_alive.astype(np.int64)) * max(n - 1, 1))
    return DetectionReport(
        n=n,
        rounds=rounds,
        ttd_first=ttd_first,
        ttd_converged=ttd_conv,
        true_detections=int(tp.sum()),
        false_positives=int(fp.sum()),
        false_positive_rate=float(fp.sum()) / opportunities if opportunities else 0.0,
        final_alive=int(n_alive[-1]),
    )
