"""Detection-quality metrics: time-to-detect, convergence, false-positive rate.

The reference's entire benchmarking apparatus is one wall-clock print in
``Get`` (slave/slave.go:888-890) and grep over Machine.log (report.pdf,
"Testing").  Here the BASELINE.md curves — time-to-detect and FPR vs N —
are array reductions over the sim outputs.

Partition-aware metrics (the scenario engine's observables — see
``gossipfs_tpu/scenarios/``): :func:`partition_round_stats` reduces one
round's state against a partition-id vector on device, and
:func:`summarize_partition` turns the per-round series + detection events
into a :class:`PartitionReport` — split-brain duration, view divergence
between the sides, cross-partition heartbeat freeze, partition-local TTD,
and post-heal reconvergence rounds.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.core.rounds import MetricsCarry, RoundMetrics
from gossipfs_tpu.core.state import MEMBER, SimState


@dataclasses.dataclass
class DetectionReport:
    """Summary of one simulation run's failure-detection behavior.

    Suspicion-aware accounting (config.suspicion, suspicion/): under the
    SWIM lifecycle ``true_detections``/``false_positives`` count SUSPECT
    -> FAILED *confirmations*, and the suspicion fields below are live —
    ``fp_suppressed`` is the headline: refutations of actually-alive
    subjects, each one a false positive the plain crash-on-timeout
    detector would have fired.  All zeros/empty in the reference mode.
    """

    n: int
    rounds: int
    # per tracked crash: rounds from crash to first detection / full removal
    ttd_first: dict[int, int]        # node -> rounds (or -1 if never detected)
    ttd_converged: dict[int, int]    # node -> rounds (or -1 if never converged)
    true_detections: int
    false_positives: int
    false_positive_rate: float       # FP events / (alive-observer x subject x round)
    final_alive: int
    suspects_entered: int = 0        # entries that entered SUSPECT
    refutations: int = 0             # suspicions cancelled by a hb advance
    fp_suppressed: int = 0           # refutations of actually-alive subjects
    # per tracked crash: rounds from crash to first suspicion, and from
    # first suspicion to the confirming detection (the suspect-to-confirm
    # latency the lifecycle adds on top of t_fail)
    ttd_suspect: dict[int, int] = dataclasses.field(default_factory=dict)
    suspect_to_confirm: dict[int, int] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def summarize(
    carry: MetricsCarry,
    per_round: RoundMetrics,
    crash_rounds: dict[int, int] | None = None,
    n_effective: int | None = None,
) -> DetectionReport:
    """Reduce sim outputs to a DetectionReport.

    ``crash_rounds``: {node: round it was crashed} for scheduled faults whose
    detection latency should be reported.

    ``n_effective``: live-cohort size for PADDED runs (the literal-N
    padding in bench/frontier.py keeps permanently-dead alignment pad
    nodes past it) — FPR opportunities then count real subjects only;
    the report's ``n`` stays the effective count.
    """
    first = np.asarray(carry.first_detect)
    conv = np.asarray(carry.converged)
    first_sus = np.asarray(carry.first_suspect)
    tp = np.asarray(per_round.true_detections)
    fp = np.asarray(per_round.false_positives)
    n_alive = np.asarray(per_round.n_alive)
    rounds = len(tp)
    n = first.shape[0] if n_effective is None else n_effective

    ttd_first, ttd_conv = {}, {}
    ttd_sus, sus2conf = {}, {}
    for node, r0 in (crash_rounds or {}).items():
        ttd_first[node] = int(first[node] - r0) if first[node] >= 0 else -1
        ttd_conv[node] = int(conv[node] - r0) if conv[node] >= 0 else -1
        if first_sus[node] >= 0:
            ttd_sus[node] = int(first_sus[node] - r0)
            if first[node] >= 0:
                sus2conf[node] = int(first[node] - first_sus[node])

    # opportunities ~= sum over rounds of alive * (n - 1) observer-subject pairs
    opportunities = float(np.sum(n_alive.astype(np.int64)) * max(n - 1, 1))
    return DetectionReport(
        n=n,
        rounds=rounds,
        ttd_first=ttd_first,
        ttd_converged=ttd_conv,
        true_detections=int(tp.sum()),
        false_positives=int(fp.sum()),
        false_positive_rate=float(fp.sum()) / opportunities if opportunities else 0.0,
        final_alive=int(n_alive[-1]),
        suspects_entered=int(np.asarray(per_round.suspects_entered).sum()),
        refutations=int(np.asarray(per_round.refutations).sum()),
        fp_suppressed=int(np.asarray(per_round.fp_suppressed).sum()),
        ttd_suspect=ttd_sus,
        suspect_to_confirm=sus2conf,
    )


# ---------------------------------------------------------------------------
# Partition-aware metrics (scenario engine)
# ---------------------------------------------------------------------------


def partition_round_stats(state: SimState, pid: jnp.ndarray) -> jnp.ndarray:
    """One round's partition observables, reduced on device.

    ``pid`` int32 [N] partition ids (scenarios.FaultScenario.pid_at).
    Returns int32 [5]: ``[cross_members, cross_hb_max, cross_complete,
    views_complete, n_alive]`` —

    * ``cross_members``: MEMBER entries live observers hold for subjects
      on a DIFFERENT side — the view divergence between the sides (0 once
      both sides have fully accepted the split);
    * ``cross_hb_max``: max heartbeat counter any live observer holds for
      a cross-side subject.  The MAX, not a sum: same-side relays keep
      redistributing values that crossed before the split (laggards catch
      up to the frozen per-subject max — legitimate), but no cross entry
      can ever EXCEED the split-time max without an actual cross-partition
      message.  Any increase during a split is propagation; the committed
      artifact pins it at zero.
    * ``cross_complete``: every live observer lists every live CROSS-side
      subject — the partition-reconvergence predicate after heal (the
      global predicate below also gates on the protocol's endemic
      same-side false-positive churn, which a netsplit metric must not);
    * ``views_complete``: every live observer lists every live subject;
    * ``n_alive``: ground-truth live count.

    Pure jnp on static shapes — wrap in ``jax.jit`` for per-round drives
    (bench/curves.py's partition sweep does).
    """
    status, alive = state.status, state.alive
    cross = pid[:, None] != pid[None, :]
    live_rows = alive[:, None]
    member = status == MEMBER
    cross_members = jnp.sum(
        (member & cross & live_rows).astype(jnp.int32)
    )
    cross_hb_max = jnp.max(
        jnp.where(cross & live_rows, state.hb_true(), 0)
    )
    need = live_rows & alive[None, :]
    cross_complete = jnp.all(jnp.where(need & cross, member, True))
    complete = jnp.all(jnp.where(need, member, True))
    return jnp.stack([
        cross_members, cross_hb_max, cross_complete.astype(jnp.int32),
        complete.astype(jnp.int32),
        jnp.sum(alive, dtype=jnp.int32),
    ])


@dataclasses.dataclass
class PartitionReport:
    """Scenario-engine observables of one partition/heal cycle."""

    n: int
    split_at: int                 # first round cross messages drop
    heal_at: int                  # first round messages flow again
    split_brain_rounds: int       # rounds until both sides fully accepted
                                  # the split (cross view entries hit 0);
                                  # -1 = never during the window
    view_divergence_max: int      # max cross-side MEMBER entries held
    view_divergence_at_heal: int  # cross entries remaining when healed
    cross_hb_advances: int        # rounds where the cross heartbeat MAX
                                  # grew DURING the split (must be 0: no
                                  # cross-partition propagation)
    reconverge_rounds: int        # rounds after heal until every live view
                                  # again lists every live CROSS-side
                                  # member; -1 = not in horizon
    full_view_rounds: int         # rounds after heal until views are
                                  # complete INCLUDING same-side entries
                                  # (also gated by the protocol's endemic
                                  # background FP churn); -1 = not reached
    local_ttd: dict[int, int]     # partition-local detection: crashed node
                                  # -> rounds until a SAME-side observer
                                  # fired (-1 = never)
    cross_detections: int         # detections of other-side subjects
                                  # WHILE the split could cause them
                                  # (split_at..heal_at) — expected
    local_false_positives: int    # detections of alive subjects the split
                                  # does NOT explain (same-side any time,
                                  # cross-side outside the split window)
                                  # — real FPs, the partition-local FPR's
                                  # numerator
    local_fp_rate: float          # above / (sum_t n_alive * same-side
                                  # subjects) — the partition-local FPR

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def summarize_partition(
    series: list[dict],
    events,
    pid: np.ndarray,
    split_at: int,
    heal_at: int,
    crash_rounds: dict[int, int] | None = None,
) -> PartitionReport:
    """Reduce a per-round stats series + detection events to a report.

    ``series``: one dict per completed round, ``{"round": r,
    "cross_members", "cross_hb_max", "cross_complete", "complete",
    "n_alive"}`` with ``r`` the state's round counter AFTER that round
    ran (the round executed with counter r-1); rounds are
    scenario-relative (armed at round 0).  ``events``: DetectionEvents
    drained over the same horizon.  ``crash_rounds``: same-side tracked
    crashes for the local-TTD rows.
    """
    by_round = {row["round"]: row for row in series}
    rounds = sorted(by_round)

    # the state produced by the last pre-split round has counter split_at;
    # every state in (split_at, heal_at] saw only filtered merges
    split_states = [r for r in rounds if split_at < r <= heal_at]
    div_max = max(
        (by_round[r]["cross_members"] for r in split_states), default=0
    )
    brain = -1
    for r in split_states:
        if by_round[r]["cross_members"] == 0:
            brain = r - split_at
            break
    div_heal = by_round[heal_at]["cross_members"] if heal_at in by_round else -1

    advances = 0
    prev = None
    for r in rounds:
        if split_at < r <= heal_at:
            cur = by_round[r]["cross_hb_max"]
            if prev is not None and cur > prev:
                advances += 1
            prev = cur
        elif r == split_at:
            prev = by_round[r]["cross_hb_max"]

    reconverge = full_view = -1
    for r in rounds:
        if r > heal_at and by_round[r]["cross_complete"] and reconverge < 0:
            reconverge = r - heal_at
        if r > heal_at and by_round[r]["complete"] and full_view < 0:
            full_view = r - heal_at
        if reconverge >= 0 and full_view >= 0:
            break

    local_ttd: dict[int, int] = {}
    for node, r0 in (crash_rounds or {}).items():
        hit = [
            e.round for e in events
            if e.subject == node and pid[e.observer] == pid[node]
            and e.round >= r0
        ]
        local_ttd[node] = (min(hit) - r0) if hit else -1

    tracked = set(crash_rounds or ())
    # an event's false_positive flag IS ground-truth "subject was alive".
    # A cross-side detection is "the split working as designed" only
    # while the split could have caused it — firing from the split round
    # through heal (entries that went stale during the split are all
    # declared by then; a post-heal cycle needs a fresh t_fail of silence
    # the healed links no longer produce).  Cross-side detections OUTSIDE
    # that window, like same-side ones of alive subjects any time, are
    # real false positives.
    cross_det = local_fp = 0
    for e in events:
        if e.subject in tracked:
            continue
        cross = pid[e.observer] != pid[e.subject]
        if cross and split_at <= e.round <= heal_at:
            cross_det += 1
        elif e.false_positive:
            local_fp += 1
    n = int(pid.shape[0])
    # same-side observer-subject opportunities, approximated with the
    # mean side size (exact would track per-side liveness; at the
    # artifact's half/half splits they coincide)
    side = max(n // max(len(set(pid.tolist())), 1) - 1, 1)
    opportunities = float(sum(by_round[r]["n_alive"] for r in rounds)) * side
    return PartitionReport(
        n=n,
        split_at=split_at,
        heal_at=heal_at,
        split_brain_rounds=brain,
        view_divergence_max=int(div_max),
        view_divergence_at_heal=int(div_heal),
        cross_hb_advances=int(advances),
        reconverge_rounds=int(reconverge),
        full_view_rounds=int(full_view),
        local_ttd=local_ttd,
        cross_detections=int(cross_det),
        local_false_positives=int(local_fp),
        local_fp_rate=(local_fp / opportunities) if opportunities else 0.0,
    )
