"""Device-mesh sharding of the simulation state (the sim's "model parallelism").

The scaling axis of this framework is N, the member count (SURVEY §5): the
reference scales by adding VMs (max ~10, capped by its 1024-byte UDP buffer,
slave/slave.go:210); we scale to 100k+ by sharding the [N, N] state over a
``jax.sharding.Mesh``.

Sharding choice — **subject axis (columns)**, ``P(None, AXIS)``:

The per-round merge gathers whole *rows* of the state by sender index
(``hb[k, :]``).  With column sharding every device holds all rows for a slice
of subjects, so the row gather needs **no communication at all** — each chip
merges its slice of every node's table independently.  The only collectives
XLA inserts are cheap [N]-vector reductions over the subject axis
(member counts, detection aggregates), which ride ICI.  Row sharding, by
contrast, would turn the gather into an all-gather of the full matrix.

Everything goes through GSPMD: we annotate inputs with NamedSharding and let
``jax.jit`` partition the identical round kernel that runs single-chip.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossipfs_tpu.core.state import SimState

AXIS = "shard"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over available devices (v5e-8 -> 8-way column sharding)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def state_shardings(mesh: Mesh) -> SimState:
    """NamedShardings matching SimState's pytree structure.

    [N, N] tables shard on the subject (column) axis; the small per-node
    vectors and the round counter are replicated — they are read on every
    shard each round and cost O(N) bytes, not O(N^2).
    """
    mat = NamedSharding(mesh, P(None, AXIS))
    rep = NamedSharding(mesh, P())
    return SimState(hb=mat, age=mat, status=mat, alive=rep, round=rep)


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place an (unsharded) SimState onto the mesh with column sharding."""
    sh = state_shardings(mesh)
    return jax.tree.map(jax.device_put, state, sh)
