"""Device-mesh sharding of the simulation state (the sim's "model parallelism").

The scaling axis of this framework is N, the member count (SURVEY §5): the
reference scales by adding VMs (max ~10, capped by its 1024-byte UDP buffer,
slave/slave.go:210); we scale to 100k+ by sharding the [N, N] state over a
``jax.sharding.Mesh``.

Sharding choice — **subject axis (columns)**, ``P(None, AXIS)``:

The per-round merge gathers whole *rows* of the state by sender index
(``hb[k, :]``).  With column sharding every device holds all rows for a slice
of subjects, so the row gather needs **no communication at all** — each chip
merges its slice of every node's table independently.  The only collectives
XLA inserts are cheap [N]-vector reductions over the subject axis
(member counts, detection aggregates, and — on lh-armed rr runs since
round 14 — the per-receiver SUSPECT counts feeding the Lifeguard
local-health lane), which ride ICI.  Row sharding, by contrast, would
turn the gather into an all-gather of the full matrix.

Everything goes through GSPMD: we annotate inputs with NamedSharding and let
``jax.jit`` partition the identical round kernel that runs single-chip.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossipfs_tpu.core.state import SimState

AXIS = "shard"

# jax-version compat: shard_map moved to the jax namespace (and its
# replication-check kwarg was renamed check_rep -> check_vma) in 0.5+
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SM_NOCHECK = {"check_vma": False}
else:  # pragma: no cover - exercised only on older jax runtimes
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_NOCHECK = {"check_rep": False}


def rr_shard_admissible(n: int, shards: int, block_c: int, fanout: int,
                        arc_align: int = 8, block_r: int = 512,
                        rotate: bool = True) -> dict:
    """Row-budget admissibility of ONE shard's resident-round program.

    The sharded aligned rr runs tall-skinny [N global rows x N/shards
    local columns] shapes — exactly where the kernel's per-row VMEM
    binds.  Returns the verdict plus the budget components (window
    scratch, flags, count accumulator) so capacity planning
    (tools/shard_anchor.py --ladder) can show WHY a shape is in or out.
    Ring-rotated + LANE-compacted layouts by default (round 9); pass
    ``rotate=False`` for the round-5 full-T/replicated budget.
    """
    from gossipfs_tpu.ops import merge_pallas as mp

    nloc = n // shards
    scratch = mp.rr_align_scratch_bytes(n, fanout, block_c, arc_align,
                                        rotate=rotate)
    flags = mp.rr_flags_bytes(n, block_c, block_r=block_r,
                              arc_align=arc_align, rotate=rotate)
    acc = n * 8 if nloc // block_c > mp.RR_ACC_STRIPES else 0
    return {
        "n_global": n,
        "shards": shards,
        "local_cols": nloc,
        "merge_block_c": block_c,
        "fanout": fanout,
        "arc_align": arc_align,
        "admissible": mp.rr_supported(n, fanout, block_c, nloc,
                                      arc_align=arc_align, block_r=block_r,
                                      rotate=rotate),
        "window_scratch_bytes": scratch,
        "flags_bytes": flags,
        "count_acc_bytes": acc,
        "row_budget_bytes": scratch + flags + acc,
        "budget_limit_bytes": mp.RR_ALIGN_VMEM_BUDGET,
    }


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over available devices (v5e-8 -> 8-way column sharding)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def state_shardings(mesh: Mesh) -> SimState:
    """NamedShardings matching SimState's pytree structure.

    [N, N] tables shard on the subject (column) axis; the small per-node
    vectors and the round counter are replicated — they are read on every
    shard each round and cost O(N) bytes, not O(N^2).
    """
    mat = NamedSharding(mesh, P(None, AXIS))
    rep = NamedSharding(mesh, P())
    col = NamedSharding(mesh, P(AXIS))  # per-subject vector, column-aligned
    return SimState(hb=mat, age=mat, status=mat, alive=rep, round=rep, hb_base=col)


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place an (unsharded) SimState onto the mesh with column sharding."""
    sh = state_shardings(mesh)
    return jax.tree.map(jax.device_put, state, sh)


@functools.lru_cache(maxsize=32)
def _sharded_runner(mesh, config, crash_rate, rejoin_rate, has_churn_ok,
                    donate=False, matrix_events=True, has_scenario=False):
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from gossipfs_tpu.core import rounds
    from gossipfs_tpu.core.state import RoundEvents, SimState as SS

    n = config.n
    d = mesh.devices.size
    nloc = n // d
    mat = P(None, AXIS)
    rep = P()

    def local_run(hb, age, status, alive, rnd, hb_base, ev_crash, ev_leave,
                  ev_join, key, churn_ok, scenario):
        ctx = rounds.ShardCtx(axis=AXIS, offset=lax.axis_index(AXIS) * nloc)
        st = SS(hb=hb, age=age, status=status, alive=alive, round=rnd,
                hb_base=hb_base)
        scn = scenario if has_scenario else None
        blocked = rounds._use_blocked(config, config.fanout, n, nloc)
        if not blocked and rounds._rr_scan_eligible(
            config, n, nloc, matrix_events, ctx, scenario=scn
        ):
            # the rr scan accepts narrower per-shard stripe widths than
            # the stripe kernels _use_blocked models; it consumes the
            # blocked layout regardless (same shared-gate pattern as
            # rounds._run_rounds_impl)
            blocked = True
        if blocked:
            st = rounds._to_blocked(st, config)
        ev = RoundEvents(crash=ev_crash, leave=ev_leave, join=ev_join)
        st, mc, pr = rounds._scan_rounds(
            st, config, key, ev, crash_rate, rejoin_rate,
            churn_ok if has_churn_ok else None, ctx,
            matrix_events=matrix_events, scenario=scn,
        )
        if blocked:
            st = rounds._from_blocked(st)
        return st.hb, st.age, st.status, st.alive, st.round, st.hb_base, mc, pr

    # the scenario rule table is a small pytree of replicated rule arrays
    # (every shard filters identically); a 0-leaf placeholder rides the
    # same slot when no scenario is armed
    from gossipfs_tpu.scenarios.tensor import TensorScenario

    scn_spec = TensorScenario(*([rep] * len(TensorScenario._fields)))
    # the positional MetricsCarry/RoundMetrics specs below must track the
    # NamedTuple widths in core/rounds — a dropped/reordered spec silently
    # binds later fields to the wrong sharding (scan-carry-arity rule)
    fn = _shard_map(
        local_run,
        mesh=mesh,
        in_specs=(mat, mat, mat, rep, rep, P(AXIS), rep, rep, rep, rep, rep,
                  scn_spec),
        out_specs=(mat, mat, mat, rep, rep, P(AXIS),
                   rounds.MetricsCarry(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                   rounds.RoundMetrics(rep, rep, rep, rep, rep, rep)),
        **_SM_NOCHECK,
    )
    if donate:
        # in-place [N, N] lanes: the 100k-class runs don't fit with
        # double-buffered state (the caller's state is consumed)
        return jax.jit(fn, donate_argnums=(0, 1, 2))
    return jax.jit(fn)


@functools.lru_cache(maxsize=8)
def _placeholder_scenario(n: int):
    """Zero-rule TensorScenario riding the scenario slot on
    scenario-free calls (the runner is lru-cached per has_scenario, so
    its leaves are never read) — cached so repeated sharded launches
    don't pay the ~12 host builds + transfers per call."""
    from gossipfs_tpu.scenarios.schedule import FaultScenario
    from gossipfs_tpu.scenarios.tensor import compile_tensor

    return compile_tensor(FaultScenario(name="none", n=n))


def run_rounds_sharded(
    state: SimState,
    config,
    num_rounds: int,
    key: jax.Array,
    mesh: Mesh,
    events=None,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
    churn_ok: jax.Array | None = None,
    donate: bool = False,
    crash_only_events: bool = False,
    scenario=None,
):
    """``core.rounds.run_rounds`` over an explicit subject-axis shard_map.

    Under plain GSPMD the pallas merge kernel is an opaque custom call —
    XLA has no partitioning rule for it and inserts full-matrix all-gathers
    around each round.  shard_map instead runs the identical round program
    per shard on its local [N, N/D] column slice: the row gather is 100%
    shard-local by construction, and only the [N]-vector reductions
    (member counts, metric sums) cross shards via ``psum`` over ICI/DCN.
    This is the v5e-8 path for the BASELINE 100k-member configs.

    Requires n % n_devices == 0 and (for the pallas path) a lane-aligned
    local column count — e.g. the 100k-class config runs N=131072 on 8
    chips (16384 columns each).  Ring (parity) topology needs the full
    2-D tables per round and is not supported here; use ``run_rounds``.
    """
    import jax.numpy as jnp

    from gossipfs_tpu.core import rounds
    from gossipfs_tpu.core.state import RoundEvents

    n = config.n
    d = mesh.devices.size
    if config.topology == "ring":
        raise ValueError("ring topology derives edges from the full table; "
                         "use run_rounds (GSPMD) instead")
    if n % d:
        raise ValueError(f"n={n} must divide over {d} devices")
    # crash_only_events: the caller's static promise that scheduled events
    # carry no leave/join bits — keeps the lean event path (see
    # core.rounds._run_rounds_impl), which matters for peak memory at the
    # 100k-class capacity points.  Joins would be silently ignored, so the
    # promise is enforced while the events are still concrete.
    rounds.check_crash_only_promise(events, crash_only_events)
    matrix_events = (
        events is not None and not crash_only_events
    ) or rejoin_rate > 0.0
    if events is None:
        zeros = jnp.zeros((num_rounds, n), dtype=bool)
        events = RoundEvents(crash=zeros, leave=zeros, join=zeros)
    if churn_ok is None:
        churn_ok_arr = jnp.ones((n,), dtype=bool)  # placeholder, unused
    else:
        churn_ok_arr = churn_ok
    from gossipfs_tpu.scenarios.tensor import TensorScenario

    if scenario is not None:
        from gossipfs_tpu.scenarios.tensor import require_scenario_config

        require_scenario_config(config, scenario)
        scn_arg = scenario
    else:
        scn_arg = _placeholder_scenario(n)
    assert isinstance(scn_arg, TensorScenario)

    fn = _sharded_runner(mesh, config, crash_rate, rejoin_rate,
                         churn_ok is not None, donate=donate,
                         matrix_events=matrix_events,
                         has_scenario=scenario is not None)
    hb, age, status, alive, rnd, hb_base, mc, pr = fn(
        state.hb, state.age, state.status, state.alive, state.round,
        state.hb_base, events.crash, events.leave, events.join, key,
        churn_ok_arr, scn_arg,
    )
    return (
        SimState(hb=hb, age=age, status=status, alive=alive, round=rnd,
                 hb_base=hb_base),
        mc,
        pr,
    )
