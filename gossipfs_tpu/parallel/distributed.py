"""Multi-host (DCN) scale-out for the simulation mesh.

The reference scales by adding VMs connected over a campus LAN (~10 max,
capped by its 1024-byte gossip datagram, reference: slave/slave.go:210).  The
TPU build scales N by sharding the [N, N] state: within a host, shards ride
ICI; across hosts, XLA routes the (cheap, O(N)-vector) collectives over DCN.
Because the round kernel's row gather is 100% shard-local under column
sharding (parallel/mesh.py), the cross-host traffic per round stays tiny —
the design scales to multi-host the way the reference's UDP fabric never
could.

Usage on a multi-host TPU pod slice:

    from gossipfs_tpu.parallel import distributed
    distributed.initialize(auto=True)  # pod auto-detect (or env-driven args)
    mesh = distributed.global_mesh()   # 1-D mesh over every chip in the job
    state = shard_state(init_state(cfg), mesh)

Single-process runs (tests, the one-chip bench) fall through both calls
unchanged.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

# NOTE: deliberately no gossipfs imports at module level — callers must be
# able to ``from gossipfs_tpu.parallel import distributed`` and call
# ``initialize()`` BEFORE anything touches jax computations (several
# modules build jnp constants at import time, and jax.distributed refuses
# to initialize after the first computation).


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    auto: bool = False,
) -> bool:
    """Bring up jax.distributed when running multi-process; no-op otherwise.

    Arguments default from the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID).  On TPU pod slices, pass
    ``auto=True`` to let jax auto-detect coordinator and topology from the
    TPU runtime with no arguments — the plain no-arg call stays a no-op so
    single-host runs (tests, the one-chip bench) never try to handshake.
    Returns True when distributed mode is active.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if auto and coordinator_address is None and num_processes is None:
        jax.distributed.initialize()  # TPU-runtime auto-detection
        return True
    if coordinator_address is None and num_processes is None:
        return False  # single-process run
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh() -> Mesh:
    """1-D mesh over every device in the (possibly multi-host) job.

    jax.devices() enumerates devices across all processes after
    ``initialize()``; order groups each host's chips together, so
    neighbouring shards share ICI and only shard-boundary collectives
    cross DCN.
    """
    from gossipfs_tpu.parallel.mesh import AXIS

    return Mesh(np.array(jax.devices()), (AXIS,))
