"""Global simulation state: every node's membership table as one batched tensor.

The reference keeps, per node, a ``[]master.Member`` slice of
``{Address, HeartbeatCount, UpdateTime}`` records (reference:
master/master.go:16-20, slave/slave.go:59-118).  The TPU-native build holds all
N tables at once as a structure-of-arrays ``[N, N]`` state — row *i* is node
*i*'s view of every peer *j*:

  ``hb[i, j]``     heartbeat count *i* currently knows for *j*
                   (reference ``Member.HeartbeatCount``)
  ``age[i, j]``    rounds since the entry was last refreshed — the round-time
                   equivalent of ``now - Member.UpdateTime`` (slave.go:426,470).
                   Stored int8, saturating at ``config.AGE_CLAMP``: the
                   protocol only ever compares age against small thresholds
                   (t_fail, t_cooldown), so the clamp is invisible to the
                   semantics and quarters the lane's HBM footprint
  ``status[i, j]`` UNKNOWN (not in *i*'s list) / MEMBER (in the list) /
                   FAILED (removed, on the RecentFailList cooldown —
                   slave/slave.go:276-286, 484-497)

plus ground truth ``alive[j]`` (is the simulated process up) and the global
round counter.  Keeping N fixed and encoding churn in ``alive``/``status``
avoids shape changes that would retrigger XLA compilation.

Arrays are sharded over the **subject axis j** (columns) on the device mesh:
the gossip merge gathers whole *rows* by sender index, which is local to every
column shard — see gossipfs_tpu/parallel/mesh.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from gossipfs_tpu.config import SimConfig

# status lane values (2 bits: the resident-round kernel packs status
# beside age in one byte — ops/merge_pallas.pack_age_status)
UNKNOWN = jnp.int8(0)   # j not in i's membership list
MEMBER = jnp.int8(1)    # j in i's list (alive as far as i knows)
FAILED = jnp.int8(2)    # j removed by i, still on the RecentFailList cooldown
SUSPECT = jnp.int8(3)   # SWIM suspicion (config.suspicion, suspicion/):
                        # j is in i's list but silent past t_fail — still a
                        # member (gossiped, counted, placeable), pending
                        # either refutation (a heartbeat advance -> MEMBER)
                        # or confirmation (t_suspect more silent rounds ->
                        # FAILED).  The suspect-start timestamp is carried
                        # implicitly by the age lane (age - t_fail = rounds
                        # in SUSPECT); only reachable when suspicion is
                        # armed — the reference mode never writes it


class SimState(NamedTuple):
    """Pytree of the full simulation state (see module docstring)."""

    hb: jax.Array       # int32 [N, N] — or int16 when config.hb_dtype="int16":
                        # then the true counter is ``hb + hb_base[subject]``
                        # (core/rounds.py renormalizes the stored values to
                        # each round's base inside the merge write)
    age: jax.Array      # int8  [N, N], saturates at config.AGE_CLAMP
    status: jax.Array   # int8  [N, N]
    alive: jax.Array    # bool  [N]
    round: jax.Array    # int32 scalar
    hb_base: jax.Array  # int32 [N] per-subject heartbeat origin; all-zero
                        # (and never updated) in int32 mode.  Sharded over
                        # the subject axis like the matrix columns.

    @property
    def n(self) -> int:
        return self.hb.shape[0]

    def hb_true(self) -> jax.Array:
        """Absolute heartbeat counters, whatever the storage dtype."""
        base = self.hb_base.reshape(self.hb.shape[1:])[None]
        return self.hb.astype(jnp.int32) + base


class RoundEvents(NamedTuple):
    """Per-round external events (the sim equivalent of CTRL+C / CLI verbs).

    Reference fault model is crash-stop via CTRL+C plus voluntary ``leave``
    and ``join`` (reference: README.md:30, slave/slave.go:288-336).
    """

    crash: jax.Array    # bool [N] — die silently this round
    leave: jax.Array    # bool [N] — broadcast LEAVE, then die
    join: jax.Array     # bool [N] — (re)join through the introducer

    @staticmethod
    def none(n: int) -> "RoundEvents":
        z = jnp.zeros((n,), dtype=bool)
        return RoundEvents(crash=z, leave=z, join=z)


def init_state(config: SimConfig, member_mask: jax.Array | None = None) -> SimState:
    """Fully-joined initial cohort.

    Every node in ``member_mask`` (default: all N) starts with every other
    member in its list at heartbeat 0, freshly stamped — the state the
    reference reaches after all nodes complete the JOIN handshake
    (reference: slave/slave.go:250-274, 161-167).
    """
    n = config.n
    if member_mask is None:
        member_mask = jnp.ones((n,), dtype=bool)
    member_mask = member_mask.astype(bool)
    hb_dtype = {"int32": jnp.int32, "int16": jnp.int16, "int8": jnp.int8}[
        config.hb_dtype
    ]
    # i knows j iff both are initial members
    know = member_mask[:, None] & member_mask[None, :]
    return SimState(
        hb=jnp.zeros((n, n), dtype=hb_dtype),
        age=jnp.zeros((n, n), dtype=jnp.int8),
        status=jnp.where(know, MEMBER, UNKNOWN).astype(jnp.int8),
        alive=member_mask,
        round=jnp.int32(0),
        hb_base=jnp.zeros((n,), dtype=jnp.int32),
    )


def member_counts(state: SimState) -> jax.Array:
    """Size of each node's membership list (int32 [N])."""
    return jnp.sum((state.status == MEMBER).astype(jnp.int32), axis=1)


def swar_lanes_ok(hb: jax.Array) -> bool:
    """Whether the SWAR elementwise path can pack this state's lanes.

    The packed-word formulation (``config.elementwise="swar"``,
    ops/swar.py) runs the round's compares/selects on 4 subjects per i32
    word; it needs all-int8 storage and a minor (subject) axis divisible
    by the 4-byte word — true for every lane-aligned shape (the minor
    axis is LANE=128 blocked, or the lane-aligned column count 2-D).
    Static (trace-time) predicate: shapes and dtypes only.
    """
    return hb.dtype == jnp.int8 and hb.shape[-1] % 4 == 0
