"""The gossip round kernel: one synchronous step advances all N nodes.

This replaces the reference's per-node 1 s heartbeat goroutine
(``HeartBeat``, reference: slave/slave.go:499-544 driven by main.go:27-33) with
a single batched tensor program.  One call == one heartbeat period == 1
simulated second for every node at once.  Mapping (SURVEY.md §7.1):

  Go behaviour (cite)                          -> tensor op here
  bump own heartbeat (slave.go:443-448)        -> diagonal += alive & !small
  refresh-only when list < 4 (slave.go:504-509)-> age[i, member] = 0 for small rows
  detect hb>1 & age>5 (slave.go:460-476)       -> fail mask over [N, N]
  REMOVE broadcast to all (slave.go:338-363)   -> any-over-observers OR into columns
  RecentFailList cooldown (slave.go:484-497)   -> FAILED entries expire to UNKNOWN
  push list to fanout + max-merge + local
  timestamp (slave.go:527-542, 414-427)        -> row gather over in-edges,
                                                  elementwise max, age reset
  join via introducer push (slave.go:250-274)  -> introducer row broadcast
  leave broadcast (slave.go:310-336)           -> column mark FAILED

The Go system is asynchronous (UDP datagrams land whenever); the sim uses the
standard synchronous-rounds model: messages sent in round t are merged before
round t+1's detection pass, which is what the 1 s period effectively gives the
reference on a LAN.

Everything here is pure jnp on static shapes — safe under ``jit``,
``lax.scan``, and GSPMD sharding (see parallel/mesh.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from gossipfs_tpu.config import (
    AGE_CLAMP,
    INT8_REBASE_WINDOW,
    REBASE_WINDOW,
    SimConfig,
)
from gossipfs_tpu.core import topology
from gossipfs_tpu.core.state import (
    FAILED,
    MEMBER,
    SUSPECT,
    UNKNOWN,
    RoundEvents,
    SimState,
    swar_lanes_ok,
)
from gossipfs_tpu.ops import swar

# ---------------------------------------------------------------------------
# Blocked layout.
#
# TPU arrays are physically tiled, so the [N, N] -> [N, N/C, C/128, 128]
# reshape the pallas merge kernel needs is a real relayout pass (~1-3 ms per
# lane at N=16k — it was ~35% of round time when done per round).  The scan
# therefore keeps the whole state in the kernel's blocked layout and
# reshapes once at entry/exit.  Every round function below is shape-generic:
# axis 0 is always the receiver; all remaining axes together index the
# subject.  The helpers express the two broadcasts and the identity mask.
# ---------------------------------------------------------------------------


class ShardCtx(NamedTuple):
    """Where this program sits in a subject-axis shard_map, if any.

    The single-device run uses the module default (no axis, offset 0).
    Under ``parallel.mesh.run_rounds_sharded`` each shard holds all N
    receiver rows for a contiguous slice of subjects: ``axis`` names the
    mesh axis for the few cross-shard reductions (member counts, metric
    sums), ``offset`` is the shard's first global subject index (so the
    diagonal mask and subject-vector slices line up).
    """

    axis: str | None
    offset: jax.Array | int

    def slice_cols(self, v: jax.Array, nloc: int) -> jax.Array:
        """This shard's slice of a replicated per-subject [N] vector."""
        if self.axis is None:
            return v
        return lax.dynamic_slice_in_dim(v, self.offset, nloc)

    def psum(self, x: jax.Array) -> jax.Array:
        """Combine a subject-axis partial reduction across shards."""
        return x if self.axis is None else lax.psum(x, self.axis)


LOCAL_CTX = ShardCtx(axis=None, offset=0)


def _nsubj(shape: tuple[int, ...]) -> int:
    out = 1
    for s in shape[1:]:
        out *= s
    return out


def _rx(v: jax.Array, ndim: int) -> jax.Array:
    """Broadcast a per-receiver [N] vector over the subject axes."""
    return v.reshape(v.shape[:1] + (1,) * (ndim - 1))


def _sj(v: jax.Array, shape: tuple[int, ...], ctx: ShardCtx = LOCAL_CTX) -> jax.Array:
    """Broadcast a (global) per-subject [N] vector over the receiver axis."""
    return ctx.slice_cols(v, _nsubj(shape)).reshape(shape[1:])[None]


def _eye(n: int, shape: tuple[int, ...], ctx: ShardCtx = LOCAL_CTX) -> jax.Array:
    """bool mask of the diagonal (receiver == subject), shape/shard-generic."""
    rows = jnp.arange(n, dtype=jnp.int32)
    cols = ctx.offset + jnp.arange(_nsubj(shape), dtype=jnp.int32)
    return _rx(rows, len(shape)) == cols.reshape(shape[1:])[None]


def _subj_axes(a: jax.Array) -> tuple[int, ...]:
    return tuple(range(1, a.ndim))


def _listed(status: jax.Array, config: SimConfig) -> jax.Array:
    """bool mask of entries in the membership list.

    Under the SWIM lifecycle (config.suspicion, suspicion/) a SUSPECT
    entry is still a member — it gossips, counts toward min_group, and
    is marked by LEAVE like any member — pending refutation or
    confirmation; only the detector treats it specially.  In the
    reference mode SUSPECT is unreachable, so the extra compare is
    dropped at trace time.
    """
    if config.suspicion is None:
        return status == MEMBER
    return (status == MEMBER) | (status == SUSPECT)


def _diag(arr: jax.Array, ctx: ShardCtx = LOCAL_CTX) -> jax.Array:
    """Gather the diagonal (receiver == subject) of a 2-D or blocked lane.

    Returns a [nloc] vector; under subject-axis sharding the global row of
    local subject j is ``ctx.offset + j``.
    """
    shp = arr.shape
    nloc = _nsubj(shp)
    j = jnp.arange(nloc)
    rows = ctx.offset + j
    if arr.ndim == 2:
        return arr[rows, j]
    _, _, cs, lane = shp
    return arr[rows, j // (cs * lane), (j % (cs * lane)) // lane, j % lane]


def _use_pallas(config: SimConfig, fanout: int, n: int, n_cols: int | None = None) -> bool:
    """Whether this run executes a pallas merge kernel."""
    from gossipfs_tpu.ops import merge_pallas

    if config.merge_kernel == "xla" or not merge_pallas.supported(n, fanout, n_cols):
        return False
    if config.merge_kernel.startswith(("pallas_stripe", "pallas_rr")):
        # "pallas_rr" rides the stripe dispatch everywhere except the lean
        # crash-only scan, where _scan_rounds_rr runs the whole round in
        # one kernel (see merge_pallas.resident_round_blocked)
        if not merge_pallas.stripe_supported(n, fanout, n_cols):
            return False
        return (
            config.merge_kernel.endswith("interpret")
            or jax.default_backend() == "tpu"
        )
    if config.merge_kernel == "pallas_interpret":
        return True
    # compiled (Mosaic) path only on TPU, and only when the column blocking
    # yields int8-tileable DMA units — small N (or narrow shards) would
    # produce sub-(32, 128) blocks that fail to compile; XLA is the right
    # path at those sizes anyway
    if jax.default_backend() != "tpu":
        return False
    _, cs, lane = merge_pallas.blocked_cols(
        n if n_cols is None else n_cols, config.merge_block_c
    )
    return cs * lane >= merge_pallas.MIN_COMPILED_BLOCK_C


def _use_blocked(config: SimConfig, fanout: int, n: int, n_cols: int | None = None) -> bool:
    """Whether the scan keeps state in the kernel's blocked layout.

    Ring mode re-derives edges from the 2-D membership tables every round,
    which would re-pay the relayout the blocked layout exists to avoid —
    ring (the parity mode, never the perf mode) stays 2-D and reaches the
    pallas kernel through the reshaping wrapper instead.
    """
    return _use_pallas(config, fanout, n, n_cols) and config.topology != "ring"


def _to_blocked(state: SimState, config: SimConfig) -> SimState:
    from gossipfs_tpu.ops import merge_pallas

    rows, cols = state.hb.shape  # cols < rows under subject-axis sharding
    shp = (rows,) + merge_pallas.blocked_cols(cols, config.merge_block_c)
    return state._replace(
        hb=state.hb.reshape(shp),
        age=state.age.reshape(shp),
        status=state.status.reshape(shp),
    )


def _from_blocked(state: SimState) -> SimState:
    rows = state.n
    cols = _nsubj(state.hb.shape)
    return state._replace(
        hb=state.hb.reshape(rows, cols),
        age=state.age.reshape(rows, cols),
        status=state.status.reshape(rows, cols),
    )


class RoundMetrics(NamedTuple):
    """Per-round scalar observables (cheap enough to stack over any horizon).

    Under suspicion (config.suspicion, suspicion/) ``true_detections`` /
    ``false_positives`` count SUSPECT -> FAILED *confirmations* — the
    lifecycle's actual failure declarations — and the three suspicion
    counters are live; in the reference mode they are constant zeros
    (folded away by XLA).
    """

    true_detections: jax.Array   # detector fired on an actually-dead subject
    false_positives: jax.Array   # detector fired on a live subject
    n_alive: jax.Array
    suspects_entered: jax.Array  # entries newly marked SUSPECT this round
    refutations: jax.Array       # SUSPECT entries refuted (-> MEMBER)
    fp_suppressed: jax.Array     # refutations of actually-ALIVE subjects —
                                 # each one a false positive the plain
                                 # crash-on-timeout detector would have fired


def _round_stats(
    n_det: jax.Array, state: SimState, ctx: ShardCtx,
    sus_stats: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[RoundMetrics, jax.Array]:
    """Scalar RoundMetrics + any_fail from the per-subject detector counts."""
    nloc = n_det.shape[0]
    dead_l = ctx.slice_cols(~state.alive, nloc)
    alive_l = ctx.slice_cols(state.alive, nloc)
    if sus_stats is None:
        z = jnp.int32(0)
        sus_stats = (z, z, z)
    metrics = RoundMetrics(
        true_detections=ctx.psum(jnp.sum(jnp.where(dead_l, n_det, 0))),
        false_positives=ctx.psum(jnp.sum(jnp.where(alive_l, n_det, 0))),
        n_alive=jnp.sum(state.alive, dtype=jnp.int32),
        suspects_entered=sus_stats[0],
        refutations=sus_stats[1],
        fp_suppressed=sus_stats[2],
    )
    return metrics, n_det > 0


class MetricsCarry(NamedTuple):
    """Per-subject first-detection / convergence rounds, carried across the scan.

    ``first_detect[j]``: first round any observer's detector fired on j.
    ``first_observer[j]``: the (lowest-index) observer whose detector fired
    on j in that first round — so bulk advancement can report real
    per-observer detection events instead of an aggregate placeholder.
    ``converged[j]``: first round every live observer had dropped j from its
    list (the cluster-wide detection-complete time the BASELINE curves want).
    ``first_suspect[j]``: first round any observer held j SUSPECT
    (suspicion runs only; stays -1 in the reference mode) — the
    suspect-to-confirm latency the suspicion metrics report is
    ``first_detect - first_suspect``.
    All are -1 until the event happens; reset to -1 when j rejoins.
    """

    first_detect: jax.Array    # int32 [N]
    first_observer: jax.Array  # int32 [N]
    converged: jax.Array       # int32 [N]
    first_suspect: jax.Array   # int32 [N]

    @staticmethod
    def init(n: int) -> "MetricsCarry":
        neg = jnp.full((n,), -1, dtype=jnp.int32)
        return MetricsCarry(first_detect=neg, first_observer=neg,
                            converged=neg, first_suspect=neg)


def _apply_events(
    state: SimState,
    events: RoundEvents,
    config: SimConfig,
    ctx: ShardCtx = LOCAL_CTX,
    matrix_events: bool = True,
) -> SimState:
    """Crash / leave / join, before the heartbeat tick (see module docstring).

    ``matrix_events`` is a *static* flag: scans that provably schedule no
    leave/join events (``run_rounds`` with events=None and rejoin_rate=0 —
    the headline benchmark's crash-only fault model) drop the leave/join
    rewrites (~10 elementwise ops x N^2 per round) at trace time.  Inside
    ``lax.scan`` the per-round masks are tracers even when the stacked
    array is a constant, so XLA cannot fold them on its own.
    """
    hb, age, status, alive = state.hb, state.age, state.status, state.alive
    if not matrix_events:
        return state._replace(alive=alive & ~(events.crash | events.leave))
    n, nd, shp = state.n, hb.ndim, hb.shape
    # the stored encoding of "true heartbeat 0" (see SimState.hb_base):
    # 0 - base per subject, saturating; identically 0 in int32 mode
    basec = state.hb_base.reshape(shp[1:])[None]
    hz = jnp.clip(-basec, jnp.iinfo(hb.dtype).min, 0).astype(hb.dtype)

    # -- leave: broadcast LEAVE, receivers remove + fail-list (slave.go:310-336).
    # The entry moves onto the fail list keeping its *existing* timestamp
    # (removeMember appends the live Member struct, slave.go:276-286), so age
    # keeps running — cooldown is measured from the last gossip refresh.
    leave = events.leave & alive
    mark = _rx(alive, nd) & _listed(status, config) & _sj(leave, shp, ctx)
    status = jnp.where(mark, FAILED, status)
    if config.fresh_cooldown:
        age = jnp.where(mark, 0, age)

    # -- crash-stop: silent death (README.md:30 "CTRL+C to crash")
    alive = alive & ~(events.crash | leave)

    # -- join: introducer appends unconditionally (addNewMember, slave.go:250-274)
    #    then pushes its full list to every member; receivers merge-add unless
    #    the joiner is on their RecentFailList (slave.go:430-439).
    join = events.join & ~alive
    intro = config.introducer
    intro_alive = alive[intro]
    eff = join & intro_alive  # joins are lost if the introducer is down (SPOF kept)

    hb_base = state.hb_base
    if hb.dtype != jnp.int32:
        # join-time column rebase: the fresh incarnation's true hb 0 must be
        # representable in THIS round's writes — under a base beyond the
        # storage range the hz encoding would saturate the join writes to
        # the floor sentinel, permanently muting the node (it could neither
        # bump nor be detected).  Joined subjects' columns rebase to 0
        # here: fresh entries encode exactly; old-incarnation lanes clip at
        # the storage ceiling (outside the gossip window, aging, detectable
        # — ordinary zombies); floor sentinels stay sentinels.
        info = jnp.iinfo(hb.dtype)
        new_base = jnp.where(ctx.slice_cols(eff, _nsubj(shp)), 0, hb_base)
        renorm = _sj(eff, shp, ctx) & (basec != 0)
        true32 = hb.astype(jnp.int32) + basec
        sent = hb == info.min
        hb = jnp.where(
            renorm & ~sent,
            jnp.clip(true32, info.min, info.max).astype(hb.dtype),
            hb,
        )
        hb_base = new_base
        basec = new_base.reshape(shp[1:])[None]
        hz = jnp.clip(-basec, info.min, 0).astype(hb.dtype)

    # introducer's own row: unconditional append at hb=0
    intro_row_add = eff & (jnp.arange(n) != intro)
    intro_sel = _rx(jnp.arange(n) == intro, nd) & _sj(intro_row_add, shp, ctx)
    status = jnp.where(intro_sel, MEMBER, status)
    hb = jnp.where(intro_sel, hz, hb)
    age = jnp.where(intro_sel, 0, age)

    # everyone else merges the introducer's pushed list: add joiner if UNKNOWN
    recv_add = _rx(alive, nd) & (status == UNKNOWN) & _sj(eff, shp, ctx)
    status = jnp.where(recv_add, MEMBER, status)
    hb = jnp.where(recv_add, hz, hb)
    age = jnp.where(recv_add, 0, age)

    # the joiner's fresh table = the introducer's post-append row (it receives
    # the same full-list push); a fresh process has an empty fail list.
    joiner_status = jnp.where(status[intro] == MEMBER, MEMBER, UNKNOWN)
    joiner_hb = jnp.where(status[intro] == MEMBER, hb[intro], hz[0])
    new_row = _rx(eff, nd)
    status = jnp.where(new_row, joiner_status[None], status)
    hb = jnp.where(new_row, joiner_hb[None], hb)
    age = jnp.where(new_row, 0, age)
    # self entry always present (InitMembership, slave.go:161-167)
    self_sel = new_row & _eye(n, shp, ctx)
    status = jnp.where(self_sel, MEMBER, status)
    hb = jnp.where(self_sel, hz, hb)

    alive = alive | eff
    return state._replace(
        hb=hb, age=age, status=status, alive=alive, hb_base=hb_base
    )


def _pre_tick(
    state: SimState, config: SimConfig, ctx: ShardCtx = LOCAL_CTX
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The round's two reductions over the post-events state, in one pass.

    Returns (active [N], refresher [N], colmax_est [subject-shaped]):

    * ``active``/``refresher``: senders vs small-group timestamp-refreshers,
      from the per-receiver member counts (slave.go:504-511).  Cross-shard
      under run_rounds_sharded: each shard holds a column slice, so the
      row-sum needs a psum.
    * ``colmax_est``: per-subject upper bound on the freshest *legitimate*
      true counter after the tick's bump — the anchor for this round's
      view/storage rebase (see ``_merge``).  Anchored on the DIAGONAL:
      a subject's own self-entry is the only source of increments, so
      every current-incarnation copy anywhere satisfies
      ``copy <= hb[j, j]``, making ``diag + 1`` an exact post-bump bound —
      and, unlike a column max, one a *rejoin cannot inflate*: the join
      resets row j (diagonal included), so the fresh incarnation's hb=0
      entries are in-window immediately, while zombie copies of the old
      incarnation (now above the window top) are excluded from gossip by
      the view clamp in ``_merge`` and age out.  This supersedes the
      reference's incarnation-free max-merge ambiguity
      (slave.go:419-424) instead of inheriting it, and costs an [N]
      gather instead of an [N, N] reduction.
    """
    hb, status, alive = state.hb, state.status, state.alive
    nd, shp = hb.ndim, hb.shape
    counts = ctx.psum(
        jnp.sum(_listed(status, config).astype(jnp.int32),
                axis=_subj_axes(status))
    )
    small = counts < config.min_group
    active = alive & ~small
    refresher = alive & small

    basec = state.hb_base.reshape(shp[1:])  # subject-shaped; zero in int32 mode
    diag = _diag(hb, ctx)
    colmax_est = (diag.astype(jnp.int32) + basec.reshape(-1) + 1).reshape(shp[1:])
    return active, refresher, colmax_est


def _tick(
    state: SimState,
    config: SimConfig,
    ctx: ShardCtx = LOCAL_CTX,
    *,
    active: jax.Array,
    refresher: jax.Array,
) -> tuple[SimState, jax.Array]:
    """Per-node heartbeat pass: refresh/bump/detect/remove-broadcast/cooldown.

    Returns (state, fail_events [N,N] bool).
    """
    if config.elementwise == "swar" and swar_lanes_ok(state.hb):
        # packed-word formulation of the all-int8 tick: 4 subjects per
        # i32 op, bit-identical per byte (see _tick_swar)
        return _tick_swar(state, config, ctx, active=active,
                          refresher=refresher)
    n = state.n
    hb, age, status, alive = state.hb, state.age, state.status, state.alive
    nd, shp = hb.ndim, hb.shape
    eye = _eye(n, shp, ctx)
    sus = config.suspicion
    # post-events status, before any tick write — the suspicion branch's
    # local-health counts anchor here (refresher rewrites touch only
    # inactive rows, so active rows' counts are unaffected either way)
    status0 = status

    # small groups only refresh timestamps (slave.go:504-509).  Below
    # min_group detection is disabled, so suspicion is moot there: any
    # SUSPECT entry reverts to MEMBER with a fresh stamp
    refresh_all = _rx(refresher, nd) & _listed(status, config)
    age = jnp.where(refresh_all, 0, age)
    if sus is not None:
        status = jnp.where(refresh_all & (status == SUSPECT), MEMBER, status)

    # bump own heartbeat + stamp — only while the self entry is still in the
    # list (updateMemberList matches by address, slave.go:443-448; a node that
    # processed a REMOVE about itself stops bumping)
    bump = eye & _rx(active, nd) & (status == MEMBER)
    if hb.dtype != jnp.int32:
        # entries saturated at the storage floor hold unknown true counters
        # (the zombie-rejoin corner): a bump would move the lane off the
        # sentinel and resurrect a counter inflated by base - 32768.  Keep
        # the sentinel sticky — the entry stays excluded from gossip and
        # detection until the introducer's join push rewrites it.
        bump &= hb != jnp.iinfo(hb.dtype).min
    hb = hb + bump.astype(hb.dtype)
    age = jnp.where(bump, 0, age)

    # failure detection (slave.go:460-476): member, not self, past the hb
    # grace, and silent for more than t_fail rounds.  Removed entries keep
    # their stale timestamp on the fail list (slave.go:276-286): age runs on.
    # in int16 mode the grace compare shifts by the per-subject base
    # (true hb = stored + base); entries saturated at the storage floor
    # have unknown true counters and are excluded (the zombie-rejoin
    # corner, same class as the view-rebase clamp in _merge)
    basec = state.hb_base.reshape(shp[1:])[None]
    if hb.dtype != jnp.int32:
        # narrow compare (packed 2-4x): hb > thr  <=>  hb >= thr+1, with
        # the int32 threshold clipped into the storage dtype — a threshold
        # below the floor admits every lane, exactly like the int32 compare
        info = jnp.iinfo(hb.dtype)
        thr = jnp.clip(config.hb_grace - basec + 1, info.min, info.max).astype(
            hb.dtype
        )
        past_grace = (hb >= thr) & (hb != info.min)
    else:
        past_grace = hb > (config.hb_grace - basec)
    stale = _rx(active, nd) & ~eye & past_grace & (age > config.t_fail)
    if sus is None:
        fail = stale & (status == MEMBER)
        status = jnp.where(fail, FAILED, status)
    else:
        # SWIM lifecycle (suspicion/params.py): a silent member is
        # SUSPECTED first; confirmation to FAILED waits t_suspect more
        # rounds of silence (the age lane IS the suspicion clock —
        # age - t_fail = rounds in SUSPECT), refutable in the meantime
        # by any heartbeat advance (the merge epilogue's SUSPECT ->
        # MEMBER write).  Both masks derive from the pre-write status,
        # so an entry always spends >= 1 round SUSPECT before it can
        # confirm.  Lifeguard local health: while an anomalous fraction
        # of a receiver's own list is simultaneously SUSPECT (evidence
        # the receiver itself is degraded — a starved or cut-off node
        # suspects everyone at once), its confirmation window stretches
        # by lh_multiplier
        suspect_new = stale & (status == MEMBER)
        if sus.lh_multiplier > 0:
            cnt_sus = ctx.psum(jnp.sum(
                (status0 == SUSPECT).astype(jnp.int32),
                axis=_subj_axes(status0)))
            cnt_listed = ctx.psum(jnp.sum(
                _listed(status0, config).astype(jnp.int32),
                axis=_subj_axes(status0)))
            degraded = (cnt_sus.astype(jnp.float32)
                        > sus.lh_frac * cnt_listed.astype(jnp.float32))
            confirm_age = (config.t_fail + sus.t_suspect
                           * (1 + jnp.where(degraded, sus.lh_multiplier, 0)))
            confirm_thr = _rx(confirm_age.astype(jnp.int32), nd)
        else:
            confirm_thr = jnp.int32(config.t_fail + sus.t_suspect)
        confirm = (
            _rx(active, nd) & ~eye & (status == SUSPECT)
            & (age.astype(jnp.int32) > confirm_thr)
        )
        # contract order (analysis/protocol_spec.py, spec-transition-order):
        # confirm is computed from the pre-round SUSPECT set BEFORE the
        # MEMBER->SUSPECT write lands, and the FAILED write is last —
        # swapping these lets an entry suspect and confirm in one round
        status = jnp.where(suspect_new, SUSPECT, status)
        status = jnp.where(confirm, FAILED, status)
        fail = confirm
    if config.fresh_cooldown:
        age = jnp.where(fail, 0, age)

    # REMOVE broadcast (slave.go:338-363): one detection removes j everywhere
    # this round.  North-star mode turns this off and lets removal spread by
    # gossip omission instead.
    if config.remove_broadcast:
        removed = jnp.any(fail, axis=0)
        mark = _rx(alive, nd) & (status == MEMBER) & removed[None]
        status = jnp.where(mark, FAILED, status)
        if config.fresh_cooldown:
            age = jnp.where(mark, 0, age)

    # fail-list cooldown expiry (cleanFailList, slave.go:484-497).  Because the
    # fail-list entry keeps its last-refresh timestamp, detector-removed
    # entries (already > t_fail stale) expire the same tick; only LEAVE/REMOVE
    # entries with fresh timestamps get the full suppression window.
    expire = (status == FAILED) & (age > config.t_cooldown)
    status = jnp.where(expire, UNKNOWN, status)

    return state._replace(hb=hb, age=age, status=status, alive=alive), fail


def _eye_words(n: int, shape: tuple[int, ...], ctx: ShardCtx = LOCAL_CTX) -> jax.Array:
    """Packed-word diagonal mask: byte set (0xFF) where receiver == subject.

    The SWAR path packs 4 subjects per i32 word along the minor axis
    (ops/swar.py), so the diagonal differs per byte: byte k of word g
    covers subject ``4g + k``.  Built from 4 word-width compares — the
    same op count as ONE byte-width compare over the unpacked lanes.
    """
    nd = len(shape)
    cols = ctx.offset + jnp.arange(_nsubj(shape), dtype=jnp.int32)
    colw = cols.reshape(shape[1:-1] + (shape[-1] // 4, 4))[..., 0][None]
    rows = _rx(jnp.arange(n, dtype=jnp.int32), nd)
    out = None
    for k, bm in enumerate(swar.BYTE):
        m = jnp.where(rows == colw + k, jnp.int32(bm), jnp.int32(0))
        out = m if out is None else out | m
    return out


def _tick_swar(
    state: SimState,
    config: SimConfig,
    ctx: ShardCtx = LOCAL_CTX,
    *,
    active: jax.Array,
    refresher: jax.Array,
) -> tuple[SimState, jax.Array]:
    """SWAR formulation of :func:`_tick`'s all-int8 narrow branch.

    Identical semantics, 4 subjects per i32 word (ops/swar.py): the
    refresh/bump selects, the clipped grace compare, the t_fail/t_cooldown
    threshold compares and the FAILED/UNKNOWN status writes all run as
    carry-safe bitwise word ops.  Per-receiver masks (active/refresher/
    alive) are uniform across a word's 4 bytes, so they enter as -1/0
    whole-word masks; per-subject thresholds pack 4 to a word; only the
    diagonal (bump) mask differs per byte (:func:`_eye_words`).  The
    suspicion branch (round 11) mirrors :func:`_tick`'s SWIM lifecycle —
    SUSPECT entry, confirmation at the (possibly Lifeguard-stretched)
    per-receiver threshold, small-group revert — with the per-receiver
    confirm threshold entering as a replicated word (thresholds are < 63,
    so the byte replication cannot carry).  Pinned bit-equal to the lanes
    branch by the swar parity tests and the golden fuzz suite.
    """
    n = state.n
    hb, age, status, alive = state.hb, state.age, state.status, state.alive
    nd, shp = hb.ndim, hb.shape
    sus = config.suspicion
    MEM = swar.word(int(MEMBER))
    FLW = swar.word(int(FAILED))
    SUS = swar.word(int(SUSPECT))
    SENT = swar.word(0x80)  # the -128 floor-sentinel byte
    hbw, agew, stw = swar.pack(hb), swar.pack(age), swar.pack(status)

    def rowm(v: jax.Array) -> jax.Array:
        return swar.bool_mask(v).reshape((n,) + (1,) * (nd - 1))

    act_m, ref_m = rowm(active), rowm(refresher)
    eye_b = _eye_words(n, shp, ctx)
    stm_b = swar.to_bytes(swar.eq(stw, MEM))

    # small groups only refresh timestamps; under suspicion the refresh
    # also reverts SUSPECT -> MEMBER (detection is disabled below
    # min_group, so suspicion is moot there)
    if sus is None:
        agew = swar.sel(ref_m & stm_b, jnp.int32(0), agew)
    else:
        sus_pre_b = swar.to_bytes(swar.eq(stw, SUS))
        listed_b = swar.to_bytes(swar.ne(stw & swar.L, 0))  # status bit 0
        refresh_b = ref_m & listed_b
        agew = swar.sel(refresh_b, jnp.int32(0), agew)
        stw = swar.sel(refresh_b & sus_pre_b, MEM, stw)
    # sentinel-sticky diagonal bump + stamp
    bump_b = eye_b & act_m & stm_b & swar.to_bytes(swar.ne(hbw, SENT))
    hbw = swar.add(hbw, bump_b & swar.L)
    agew = swar.sel(bump_b, jnp.int32(0), agew)

    # detection: per-subject clipped grace threshold (i32 vector math,
    # packed once) over the post-bump lanes
    basec = state.hb_base.reshape(shp[1:])
    thr8 = jnp.clip(config.hb_grace - basec + 1, -128, 127).astype(jnp.int8)
    thrw = swar.pack(thr8)[None]
    past_h = swar.ges(hbw, thrw) & swar.ne(hbw, SENT)
    stale_b = (
        act_m & stm_b & ~eye_b
        & swar.to_bytes(past_h & swar.gts(agew, swar.word(config.t_fail)))
    )
    if sus is None:
        fail_b = stale_b
        stw = swar.sel(fail_b, FLW, stw)
    else:
        # SWIM lifecycle (mirrors _tick's lanes branch): stale MEMBER ->
        # SUSPECT (the age lane keeps running — it is the clock); SUSPECT
        # confirms to FAILED past the per-receiver threshold.  Lifeguard
        # local health anchors on the PRE-tick status counts, exactly as
        # the lanes branch's status0 anchor.
        if sus.lh_multiplier > 0:
            cnt_sus = ctx.psum(jnp.sum(
                (status == SUSPECT).astype(jnp.int32),
                axis=_subj_axes(status)))
            cnt_listed = ctx.psum(jnp.sum(
                _listed(status, config).astype(jnp.int32),
                axis=_subj_axes(status)))
            degraded = (cnt_sus.astype(jnp.float32)
                        > sus.lh_frac * cnt_listed.astype(jnp.float32))
            confirm_age = (config.t_fail + sus.t_suspect
                           * (1 + jnp.where(degraded, sus.lh_multiplier, 0)))
            # per-receiver threshold replicated into all 4 bytes of a
            # word (thr < AGE_CLAMP = 63, so the multiply cannot carry)
            thr_sus_w = (confirm_age.astype(jnp.int32)
                         * jnp.int32(0x01010101)).reshape(
                             (n,) + (1,) * (nd - 1))
        else:
            thr_sus_w = swar.word(config.t_fail + sus.t_suspect)
        confirm_b = (
            act_m & sus_pre_b & ~eye_b
            & swar.to_bytes(swar.gts(agew, thr_sus_w))
        )
        stw = swar.sel(stale_b, SUS, stw)
        stw = swar.sel(confirm_b, FLW, stw)
        fail_b = confirm_b
    if config.fresh_cooldown:
        agew = swar.sel(fail_b, jnp.int32(0), agew)

    if config.remove_broadcast:
        # one detection removes j everywhere this round: OR the full-byte
        # fail masks over receivers (word-level reduce, byte-exact)
        removed = lax.reduce(fail_b, jnp.int32(0), lax.bitwise_or, (0,))
        mark_b = rowm(alive) & swar.to_bytes(swar.eq(stw, MEM)) & removed[None]
        stw = swar.sel(mark_b, FLW, stw)
        if config.fresh_cooldown:
            agew = swar.sel(mark_b, jnp.int32(0), agew)

    expire_b = swar.to_bytes(
        swar.eq(stw, FLW) & swar.gts(agew, swar.word(config.t_cooldown))
    )
    stw = stw & ~expire_b  # UNKNOWN == 0
    fail = swar.unpack(fail_b) != 0
    return state._replace(
        hb=swar.unpack(hbw), age=swar.unpack(agew), status=swar.unpack(stw)
    ), fail


def _rebase_shifts(
    state: SimState, config: SimConfig, colmax_est: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-subject rebase vectors for this round's view build and merge write.

    Returns (shift_a, shift_b, store_base), all subject-shaped:
    ``shift_a`` maps stored -> view encoding, ``shift_b`` maps the old stored
    base to the new one (the merge write renormalizes every stored value to
    this round's base), ``store_base`` is the new per-subject base (zero in
    int32 mode).  See the anchoring argument in :func:`_pre_tick`.

    :func:`_rebase_shifts_vec` is the shape-agnostic core (the rr scan
    carries its lanes stripe-major, where ``hb.shape[1:]`` is no longer
    the subject shape).
    """
    hb = state.hb
    basec = state.hb_base.reshape(hb.shape[1:])  # all-zero in int32 mode
    return _rebase_shifts_vec(hb.dtype, basec, config, colmax_est)


def _rebase_shifts_vec(
    hb_dtype, basec: jax.Array, config: SimConfig, colmax_est: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    hb = jnp.zeros((), dtype=hb_dtype)  # dtype carrier only
    view_base = jnp.maximum(colmax_est - config.rebase_window, 0)
    if hb.dtype != jnp.int32:
        # tracks the diagonal, DOWN included: a rejoin resets the subject's
        # counter to 0 and the base follows, so the fresh incarnation's
        # entries are immediately representable.  Old-incarnation lanes
        # renormalize above the window and saturate at the storage ceiling —
        # still past the detection grace, still aging, still clamped out of
        # gossip — so they die at their holders exactly like any silent
        # peer.  (The previous monotone base instead pinned rejoins below
        # the window — the round-1 zombie-rejoin deferral this replaces.)
        store_window = (
            REBASE_WINDOW if hb.dtype == jnp.int16 else INT8_REBASE_WINDOW
        )
        store_base = jnp.maximum(colmax_est - store_window, 0)
    else:
        store_base = jnp.zeros_like(basec)
    return view_base - basec, store_base - basec, store_base


def _gossip_view(
    state: SimState, senders: jax.Array, shift_a: jax.Array, config: SimConfig
) -> jax.Array:
    """What each sender's datagram contains, as a narrow-dtype tensor.

    Entries are the sender's MEMBER rows within the rebase window, encoded
    relative to ``shift_a``; absent entries are -1 (heartbeats are never
    negative).  See the window/zombie-exclusion argument in :func:`_merge`.
    """
    hb, status = state.hb, state.status
    nd = hb.ndim
    # suspicion: SUSPECT entries keep gossiping (they are still list
    # entries carrying the last-known counter; receivers' strict
    # max-merge makes relaying a stale copy harmless) — _listed folds to
    # the plain MEMBER compare when suspicion is off
    elig = _listed(status, config) & _rx(senders, nd)
    vdtype = jnp.int8 if config.view_dtype == "int8" else jnp.int16
    if hb.dtype != jnp.int32:
        # Narrow (packed) arithmetic: int16/int8 ops run 2-4x denser than
        # int32 on the VPU and the round is ALU-bound.  Mod-2^k adds/subs
        # are exact whenever the true int32 result is in range;
        # out-of-range cases are handled by comparisons against int32
        # thresholds clipped into the storage dtype (a clipped threshold
        # admits all / none exactly like the unclipped int32 compare
        # would).  Invariants keeping true results in range: gossiped
        # lanes have rel in [0, rebase_window] (enforced by the window
        # compares — the top side excludes old-incarnation zombie lanes),
        # and shift_a <= window + slack (both bases derive from the
        # diagonal).
        info = jnp.iinfo(hb.dtype)
        sa_n = shift_a.astype(hb.dtype)
        # shift_a below the storage range => every stored value >= it
        sa_all = (shift_a < info.min)[None]
        # legit lanes are <= the post-bump diagonal (== colmax_est), which
        # maps to rel == window exactly; anything above is an
        # old-incarnation zombie (rel fits the view dtype: window is 126
        # for int8, max 127)
        hi = shift_a + config.rebase_window
        hi_n = jnp.clip(hi, info.min, info.max).astype(hb.dtype)
        # floor sentinels carry no counter and never gossip — without the
        # explicit mask a deeply negative shift_a (sa_all) would admit them
        # and emit wrapped garbage rel values
        gossiped = (
            elig
            & ((hb >= sa_n[None]) | sa_all)
            & (hb <= hi_n[None])
            & (hb != info.min)
        )
        rel = hb - sa_n[None]  # exact on gossiped lanes; masked elsewhere
        return jnp.where(gossiped, rel, jnp.asarray(-1, hb.dtype)).astype(vdtype)
    rel = hb.astype(jnp.int32) - shift_a[None]
    gossiped = elig & (rel >= 0) & (rel <= config.rebase_window)
    return jnp.where(gossiped, rel, -1).astype(vdtype)


def _membership_update(
    state: SimState,
    best_rel: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    config: SimConfig,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """MergeMemberList semantics over a precomputed merged view row.

    ``best_rel[i, :] = max_f view[edges[i, f], :]`` (view encoding, -1 =
    no sender carried the entry).  Applies max-merge advance, UNKNOWN add,
    fresh stamp, and the post-merge global age advance; returns the updated
    (hb, age, status) lanes.  Shared by the XLA merge paths and the fused
    tick round (the pallas fused kernels run the same math in-kernel).
    """
    hb, age, status, alive = state.hb, state.age, state.status, state.alive
    nd = hb.ndim
    narrow = hb.dtype != jnp.int32
    if narrow and config.elementwise == "swar" and swar_lanes_ok(hb):
        # packed-word formulation of the all-int8 epilogue (4 subjects
        # per i32 op) — complete, including the age advance
        return _membership_update_swar(state, best_rel, shift_a, shift_b,
                                       config)
    vdtype = jnp.int8 if config.view_dtype == "int8" else jnp.int16
    any_member = best_rel >= 0
    recv = _rx(alive, nd)
    sus_on = config.suspicion is not None
    add = recv & (status == UNKNOWN) & any_member          # learn new member
    if narrow:
        # narrow-arithmetic epilogue, bit-identical to the int32+clip
        # formulation below (see the mod/threshold argument in the view
        # build).  vmax = top of the view dtype; all int32 threshold
        # vectors are per-subject (cheap [N] math).  Top-side
        # exactness of ``lhs``: best <= window and shift_a <= 1 + the
        # diagonal's per-round advance (both bases derive from the
        # diagonal), so best + shift_a <= storage max for both the
        # int16 and int8 modes.
        info = jnp.iinfo(hb.dtype)
        vmax = jnp.iinfo(vdtype).max
        sb32 = shift_b
        d32 = shift_a - shift_b
        sa_n = shift_a.astype(hb.dtype)
        best_n = best_rel.astype(hb.dtype)
        # advance: best + shift_a > hb over true int32 values.  Bottom
        # side: best + shift_a < storage floor means the compare is
        # false — mask via a clipped per-subject threshold.
        cmp_deep = jnp.clip(info.min - 1 - shift_a, -2, vmax).astype(vdtype)
        lhs = best_n + sa_n[None]
        advance = (
            recv & _listed(status, config) & any_member
            & (best_rel > cmp_deep[None])
            & (lhs > hb)
        )
        upd = advance | add
        # updated value best + (shift_a - shift_b): saturates at the
        # storage floor when the true value underflows (clip semantics)
        up_deep = jnp.clip(info.min - 1 - d32, -2, vmax).astype(vdtype)
        up_sat = best_rel <= up_deep[None]
        up_val = jnp.where(
            up_sat,
            jnp.asarray(info.min, hb.dtype),
            best_n + d32.astype(hb.dtype)[None],
        )
        # kept value hb - shift_b.  shift_b can be NEGATIVE (the base
        # follows the diagonal down on rejoin), so both clip sides
        # need guards: bottom-saturate (-> the floor sentinel) when
        # hb - sb underflows; top-saturate (old-incarnation zombie
        # lanes renormalizing above the ceiling) when it overflows,
        # only reachable for sb < 0.
        keep_thr = jnp.clip(sb32 + info.min - 1, info.min, info.max).astype(hb.dtype)
        hi_thr = jnp.clip(sb32 - info.min, info.min, info.max).astype(hb.dtype)
        has_hi = (sb32 < 0)[None]
        keep_val = jnp.where(
            has_hi & (hb >= hi_thr[None]),
            jnp.asarray(info.max, hb.dtype),
            hb - sb32.astype(hb.dtype)[None],
        )
        keep_val = jnp.where(
            hb <= keep_thr[None],
            jnp.asarray(info.min, hb.dtype),
            keep_val,
        )
        hb = jnp.where(upd, up_val, keep_val)
    else:
        hb32 = hb.astype(jnp.int32)
        best32 = best_rel.astype(jnp.int32)
        # max-merge + stamp: best_true > hb_true, both sides shifted
        # into the stored encoding (best32 + view_base > hb, as ever)
        advance = (
            recv & _listed(status, config) & any_member
            & (best32 > hb32 - shift_a[None])
        )
        upd = advance | add
        new32 = jnp.where(
            upd, best32 + (shift_a - shift_b)[None], hb32 - shift_b[None]
        )
        info = jnp.iinfo(hb.dtype)
        hb = jnp.clip(new32, info.min, info.max).astype(hb.dtype)
    age = jnp.where(upd, 0, age)
    if sus_on:
        # REFUTATION: a fresher heartbeat observed while SUSPECT is
        # SWIM's alive-message — the suspicion cancels and the entry
        # rejoins the membership with a fresh stamp (the upd write above)
        status = jnp.where(add | (advance & (status == SUSPECT)),
                           MEMBER, status)
    else:
        status = jnp.where(add, MEMBER, status)
    age = jnp.minimum(age + 1, AGE_CLAMP).astype(jnp.int8)
    return hb, age, status


def _membership_update_swar(
    state: SimState,
    best_rel: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    config: SimConfig,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """SWAR formulation of :func:`_membership_update`'s all-int8 branch.

    Term-for-term mirror of the narrow (int8-stored, int8-view) epilogue
    — max-merge advance, UNKNOWN add, floor/ceiling saturation selects,
    fresh stamp, age advance — over packed words (4 subjects per i32 op,
    ops/swar.py).  The per-subject saturation thresholds are the narrow
    branch's exact clip math (i32 vector ops, packed once); byte adds and
    subs wrap mod 2^8 exactly like the narrow branch's int8 arithmetic.
    Under suspicion (round 11) the advance eligibility widens to LISTED
    (one status-bit-0 word test: MEMBER=1 | SUSPECT=3) and every update
    writes MEMBER — the advance-on-SUSPECT IS the refutation.  Pinned
    bit-equal by the swar parity tests and the golden fuzz suite.
    """
    hb, age, status, alive = state.hb, state.age, state.status, state.alive
    n, nd, shp = state.n, hb.ndim, hb.shape
    sus = config.suspicion is not None
    MEM = swar.word(int(MEMBER))
    FLOOR = swar.word(0x80)  # the int8 storage floor, -128
    sb32 = shift_b
    d32 = shift_a - shift_b

    def vecw(v8: jax.Array) -> jax.Array:
        return swar.pack(v8.reshape(shp[1:]))[None]

    sa_nw = vecw(shift_a.astype(jnp.int8))
    cmp_deepw = vecw(jnp.clip(-129 - shift_a, -2, 127).astype(jnp.int8))
    d8w = vecw(d32.astype(jnp.int8))
    up_deepw = vecw(jnp.clip(-129 - d32, -2, 127).astype(jnp.int8))
    keep_thrw = vecw(jnp.clip(sb32 - 129, -128, 127).astype(jnp.int8))
    hi_thrw = vecw(jnp.clip(sb32 + 128, -128, 127).astype(jnp.int8))
    has_hi_b = vecw(jnp.where(sb32 < 0, -1, 0).astype(jnp.int8))
    sb8w = vecw(sb32.astype(jnp.int8))

    hbw, agew, stw = swar.pack(hb), swar.pack(age), swar.pack(status)
    bestw = swar.pack(best_rel)
    recv_m = swar.bool_mask(alive).reshape((n,) + (1,) * (nd - 1))
    anym_h = ~bestw & swar.H  # best_rel >= 0: sign bit clear
    elig_h = (
        swar.ne(stw & swar.L, 0)  # listed: MEMBER | SUSPECT (bit 0)
        if sus else swar.eq(stw, MEM)
    )
    adv_b = recv_m & swar.to_bytes(
        elig_h & anym_h
        & swar.gts(bestw, cmp_deepw)
        & swar.gts(swar.add(bestw, sa_nw), hbw)  # the wrapping int8 lhs
    )
    add_b = recv_m & swar.to_bytes(swar.eq(stw, 0) & anym_h)
    upd_b = adv_b | add_b
    up_val = swar.sel(
        swar.to_bytes(swar.les(bestw, up_deepw)), FLOOR,
        swar.add(bestw, d8w),
    )
    keep_val = swar.sel(
        has_hi_b & swar.to_bytes(swar.ges(hbw, hi_thrw)),
        swar.word(127), swar.sub(hbw, sb8w),
    )
    keep_val = swar.sel(
        swar.to_bytes(swar.les(hbw, keep_thrw)), FLOOR, keep_val
    )
    hbw = swar.sel(upd_b, up_val, keep_val)
    agew = swar.sel(upd_b, jnp.int32(0), agew)
    # every update writes MEMBER: adds learn the entry, and an advance on
    # a SUSPECT entry is the refutation (suspicion off, advance lanes are
    # MEMBER already — same bits as the old add-only select)
    stw = swar.sel(upd_b, MEM, stw)
    agew = swar.mins(swar.add(agew, swar.L), swar.word(AGE_CLAMP))
    return swar.unpack(hbw), swar.unpack(agew), swar.unpack(stw)


def _merge_best(
    state: SimState, view: jax.Array, edges: jax.Array, config: SimConfig
) -> jax.Array:
    """Dispatch the merged-view-row computation (best_rel) only.

    Used by the barrier-fused round, which :func:`_fused_ok` restricts to
    the pure-XLA merge paths (any live pallas kernel takes the
    separate-pass round, whose epilogue already runs in-kernel).
    """
    from gossipfs_tpu.ops import merge_pallas

    if config.topology == "random_arc":
        return merge_pallas.arc_window_max_xla(view, edges, config.fanout)
    return merge_pallas.fanout_max_merge_xla(view, edges)


def _merge(
    state: SimState,
    edges: jax.Array,
    senders: jax.Array,
    config: SimConfig,
    colmax_est: jax.Array,
    ctx: ShardCtx = LOCAL_CTX,
    detect_stats: bool = False,
    arc_match: jax.Array | None = None,
) -> tuple[SimState, jax.Array | None, jax.Array | None, jax.Array | None]:
    """Gossip exchange: gather sender rows over in-edges, elementwise-max merge.

    Implements MergeMemberList (slave.go:414-440): shared members take the max
    heartbeat and a *local* timestamp; unknown members are added unless on the
    receiver's fail list (FAILED entries ignore gossip entirely).

    Both kernels compute ``best_hb[i,:] = max_f gossip_view[edges[i,f],:]``
    over the gossip view (hb where the entry is in a sent message, -1
    otherwise); heartbeats are always >= 0, so ``best_hb >= 0`` is exactly
    "some peer's message contained this entry".  config.merge_kernel picks
    the XLA gather loop or the pallas DMA kernel (ops/merge_pallas.py — the
    TPU fast path); shapes the kernel's tiling can't express fall back to
    XLA.  One definition of the op serves both paths, so the kernel-parity
    tests pin exactly what production runs.

    Returns (state, member_col, n_det, first_obs), the last three None
    off the stripe-kernel paths: the kernels additionally produce the
    per-subject count of live non-self observers holding the entry (feeds
    :func:`_update_carry`'s convergence test) and — when ``detect_stats``,
    i.e. the crash-only fresh-cooldown fault model where "detected this
    round" is readable off the post-tick lanes — this round's per-subject
    detector firings and lowest firing observer.  All three replace
    full-matrix major-axis reductions in XLA, measured ~6x slower than
    their in-kernel accumulation.
    """
    hb, age, status, alive = state.hb, state.age, state.status, state.alive

    from gossipfs_tpu.ops import merge_pallas

    # random_arc passes arc BASES [N]; everything else explicit edges [N, F]
    arc = config.topology == "random_arc"
    fanout = config.fanout if arc else edges.shape[1]

    # The gossip view: what a sender's datagram contains for each subject
    # (absent entries as -1 — heartbeats are never negative).  Heartbeat
    # counts are rebased per subject so the view fits a narrow dtype
    # (config.view_dtype: int16, or int8 for random topologies), shrinking
    # the HBM traffic of the F-way gather — the round's dominant cost — by
    # 2-4x over int32.  The base anchors on ``colmax_est`` — the subject's
    # own diagonal counter + 1 (see ``_pre_tick``) — so only
    # current-incarnation values are ever in-window: entries MORE than the
    # window ahead of the subject's own counter are zombie copies of an
    # older incarnation, excluded from gossip by the top clamp below (they
    # never refresh, age out at their holders, and cannot be re-added).
    # In-window entries lag the diagonal by O(t_fail) per hop, far inside
    # the window for the random topologies the narrow dtypes validate for.
    shift_a, shift_b, store_base = _rebase_shifts(state, config, colmax_est)
    # what each sender's datagram contains: its MEMBER entries within the
    # rebase window (post-tick status, actual senders this round)
    view = _gossip_view(state, senders, shift_a, config)
    # Both paths include the post-merge global age advance (everything not
    # refreshed this round ages by one, saturating at AGE_CLAMP) so the
    # fused kernel can write each [N, N] lane exactly once.
    use_pallas = _use_pallas(config, fanout, state.n, _nsubj(hb.shape))
    stripe_kernel = config.merge_kernel.startswith(("pallas_stripe", "pallas_rr"))
    suspect = int(SUSPECT) if config.suspicion is not None else None
    best_rel = None  # set on the paths that share the XLA membership update
    cnt_incl = None  # per-subject live-member count (self included)
    k_ndet = k_fobs = None  # in-kernel detection stats (detect_stats only)
    if arc and arc_match is not None:
        # scenario-filtered aligned arcs: group-granular match masks over
        # the per-group maxes (scenarios/tensor.py arc_match_edges).  The
        # arc stripe kernels fuse the UNfiltered window max, so filtered
        # rounds take the XLA group form; the rr scan has its own fused
        # edge_filter path (merge_pallas.resident_round_blocked)
        best_rel = merge_pallas.arc_group_window_max_xla(
            view, arc_match, fanout, config.arc_align
        )
    elif use_pallas and hb.ndim == 4 and arc and stripe_kernel:
        # arc topology: windowed row-max over the resident stripe (O(log F)
        # shared passes) + one vector load per receiver + the block-wide
        # epilogue, all in one kernel — each lane read and written once
        alive32 = alive.astype(jnp.int32)
        hb, age, status, cnt_incl, k_ndet, k_fobs = (
            merge_pallas.arc_merge_update_blocked(
                view, edges, hb, age, status, shift_a, shift_b, alive32,
                fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
                age_clamp=AGE_CLAMP, failed=int(FAILED),
                detect_stats=detect_stats, block_r=config.merge_block_r,
                interpret=config.merge_kernel.endswith("interpret"),
                suspect=suspect,
            )
        )
    elif use_pallas:
        kernel_kwargs = dict(
            member=int(MEMBER),
            unknown=int(UNKNOWN),
            age_clamp=AGE_CLAMP,
            block_r=config.merge_block_r,
            slots=config.merge_slots,
            interpret=config.merge_kernel.endswith("interpret"),
        )
        alive32 = alive.astype(jnp.int32)
        if arc:
            # the fused gather kernels take explicit edges
            edges = topology.arc_edges(edges, fanout)
        if hb.ndim == 4 and stripe_kernel:
            # VMEM-resident column stripes: the view crosses HBM once per
            # round instead of F times (see stripe_merge_update_blocked)
            stripe_kwargs = dict(kernel_kwargs)
            del stripe_kwargs["slots"]
            hb, age, status, cnt_incl, k_ndet, k_fobs = (
                merge_pallas.stripe_merge_update_blocked(
                    view, edges, hb, age, status, shift_a, shift_b, alive32,
                    failed=int(FAILED), detect_stats=detect_stats,
                    suspect=suspect, **stripe_kwargs
                )
            )
        elif hb.ndim == 4:
            # blocked layout (see module header): view/hb/age/status arrive
            # in the kernel-native 4-D shape, so the fused kernel runs with
            # no relayout at all
            hb, age, status, cnt_incl, k_ndet, k_fobs = (
                merge_pallas.fused_merge_update_blocked(
                    view, edges, hb, age, status, shift_a, shift_b, alive32,
                    failed=int(FAILED), detect_stats=detect_stats,
                    suspect=suspect, **kernel_kwargs
                )
            )
        else:
            # ring mode stays 2-D (see _use_blocked) and pays the wrapper's
            # per-round reshapes — acceptable for the parity mode
            hb, age, status = merge_pallas.fused_merge_update(
                view, edges, hb, age, status, shift_a, shift_b, alive32,
                block_c=config.merge_block_c, suspect=suspect,
                **kernel_kwargs
            )
    elif arc:
        # XLA arc formulation: windowed row-max + one gather, F-independent
        # traffic — same results as the F-way gather over expanded edges
        best_rel = merge_pallas.arc_window_max_xla(view, edges, fanout)
    else:
        # XLA gather path: also the fallback for unsupported shapes/backends
        best_rel = merge_pallas.fanout_max_merge_xla(view, edges)
    if best_rel is not None:
        # shared XLA membership update (MergeMemberList semantics)
        hb, age, status = _membership_update(
            state, best_rel, shift_a, shift_b, config
        )
    member_col = None
    if cnt_incl is not None:
        # the kernels count live holders INCLUDING the subject's own row;
        # _update_carry wants non-self observers — subtract the diagonal
        # ([N] gather over the fresh status, vector math)
        nloc = _nsubj(status.shape)
        self_member = ctx.slice_cols(alive, nloc) & (_diag(status, ctx) == MEMBER)
        member_col = cnt_incl.reshape(nloc) - self_member.astype(jnp.int32)
    if not detect_stats:
        k_ndet = k_fobs = None
    return state._replace(
        hb=hb, age=age, status=status, alive=alive,
        hb_base=store_base.reshape(-1),
    ), member_col, k_ndet, k_fobs


def _round_core(
    state: SimState,
    events: RoundEvents,
    edges: jax.Array | None,
    config: SimConfig,
    ctx: ShardCtx = LOCAL_CTX,
    matrix_events: bool = True,
    edge_filter=None,
    sends: jax.Array | None = None,
    arc_match: jax.Array | None = None,
) -> tuple[SimState, RoundMetrics, jax.Array, jax.Array, jax.Array,
           jax.Array | None, jax.Array | None]:
    """One round, layout- and shard-generic (state may be 2-D or blocked,
    square or a subject-axis shard).

    ``edge_filter``: optional scenario-engine edge rewrite (a dropped
    message's edge becomes the receiver itself — a no-op merge; see
    scenarios/tensor.py).  Only passed on paths whose edges are the
    explicit [N, F] form and were not already filtered by the caller
    (the ring mode, whose edges derive from the post-tick tables here).
    ``sends``/``arc_match``: the aligned-arc scenario form — sender mute
    mask (a muted sender's view row encodes absent) and the [N, 2]
    (base, group-match bitmask) pairs for the group-granular partition
    filter (scenarios.tensor.sends_mask / arc_match_edges).

    Returns (state, metrics, fail, any_fail [nloc], first_obs [nloc],
    member_col [nloc] | None — see :func:`_merge`, any_suspect [nloc] |
    None — suspicion runs only, feeds the ``first_suspect`` carry)."""
    n = state.n
    sus_on = config.suspicion is not None
    state = _apply_events(state, events, config, ctx, matrix_events=matrix_events)
    active, refresher, colmax_est = _pre_tick(state, config, ctx)
    pre_status = state.status if sus_on else None
    state, fail = _tick(state, config, ctx, active=active, refresher=refresher)
    tick_status = state.status if sus_on else None
    if config.topology == "ring":
        edges = topology.ring_edges_from_status(
            state.status.reshape(n, n),
            include_suspects=config.suspicion is not None,
        )
    assert edges is not None
    if edge_filter is not None:
        edges = edge_filter(edges)
    # crash-only + fresh-cooldown + no-remove-broadcast: this round's
    # detector firings are readable off the post-tick lanes the merge
    # kernel loads anyway (status == FAILED and age == 0), so the kernels
    # accumulate the detection stats and the fail matrix never leaves the
    # tick fusion (its XLA reductions measured ~3 ms/round at N=16k)
    det_ok = (
        not matrix_events
        and config.fresh_cooldown
        and not config.remove_broadcast
    )
    # _merge also advances age for every entry not refreshed this round
    # (refreshes wrote 0, then everything ages by one, saturating at
    # AGE_CLAMP — beyond every protocol threshold, config.py)
    senders = active if sends is None else active & sends
    state, member_col, k_ndet, k_fobs = _merge(
        state, edges, senders, config, colmax_est, ctx, detect_stats=det_ok,
        arc_match=arc_match,
    )
    state = state._replace(round=state.round + 1)

    sus_stats = None
    any_sus = None
    if sus_on:
        # Suspicion observables, all off the three status snapshots the
        # round already produced (pre-tick, post-tick, post-merge).
        # Round 11: suspicion runs on the stripe/arc pallas kernels
        # through this function too, so these full-matrix reductions DO
        # run alongside those kernels; only the rr scan avoids them (its
        # counters are in-kernel sums, _scan_rounds_rr_packed).
        status_f, alive_f = state.status, state.alive
        shp_f = status_f.shape
        entered = (tick_status == SUSPECT) & (pre_status != SUSPECT)
        # a refutation is evidence of life: a merge advance flipping a
        # post-tick SUSPECT back to MEMBER.  Anchoring on tick_status
        # (not pre_status) excludes the below-min_group refresher revert,
        # which clears suspicion without any evidence — detection is
        # disabled there in both modes, so nothing was "suppressed"
        refuted = (tick_status == SUSPECT) & (status_f == MEMBER)
        alive_col = _sj(alive_f, shp_f, ctx)
        sus_stats = (
            ctx.psum(jnp.sum(entered, dtype=jnp.int32)),
            ctx.psum(jnp.sum(refuted, dtype=jnp.int32)),
            ctx.psum(jnp.sum(refuted & alive_col, dtype=jnp.int32)),
        )
        any_sus = jnp.any(status_f == SUSPECT, axis=0).reshape(
            _nsubj(shp_f))
        if member_col is None:
            # convergence must not count a SUSPECT holder as "dropped":
            # the entry is still in the list pending refute/confirm
            held = (
                _listed(status_f, config)
                & _rx(alive_f, status_f.ndim)
                & ~_eye(n, shp_f, ctx)
            )
            member_col = jnp.sum(held.astype(jnp.int32), axis=0).reshape(
                _nsubj(shp_f))

    # every fail-matrix statistic reduces over the SAME axis (receivers),
    # so XLA runs one column-reduce pass instead of several full-matrix
    # ones: per-subject detector counts + lowest firing observer, then
    # vector math for the scalar metrics
    nloc = _nsubj(fail.shape)
    if k_ndet is not None:
        n_det = k_ndet.reshape(nloc)
        # kernel stats carry n where no observer fired; _update_carry only
        # reads first_obs where a detection happened, so the disagreement
        # with argmax's 0-on-empty is unobservable
        first_obs_now = k_fobs.reshape(nloc)
    else:
        n_det = jnp.sum(fail, axis=0, dtype=jnp.int32).reshape(nloc)
        first_obs_now = jnp.argmax(fail, axis=0).astype(jnp.int32).reshape(nloc)
    metrics, any_fail = _round_stats(n_det, state, ctx, sus_stats=sus_stats)
    return state, metrics, fail, any_fail, first_obs_now, member_col, any_sus


def _fused_ok(config: SimConfig, matrix_events: bool, n: int, nloc: int) -> bool:
    """Whether the barrier-fused (recomputed-tick) round applies to this scan.

    The fused round recomputes the elementwise heartbeat tick inside the
    post-merge update fusion instead of materializing a post-tick state
    across the merge kernel.  It requires purely elementwise per-round
    state rewrites: join/leave events (cross-row introducer pushes) and the
    REMOVE broadcast (a cross-receiver reduction feeding the same round's
    view) force the separate-pass round.  Ring mode re-derives edges from
    2-D tables and stays on the parity path.  Any live pallas kernel means
    the separate-pass round instead: its epilogue (and the per-subject
    reductions) already run in-kernel, and moving the elementwise tick
    into Mosaic measured ~3x slower than XLA's elementwise engine (three
    fused-tick kernel variants were built and rejected on the v5e — see
    BASELINE.md's round-profile notes).
    """
    if (
        config.fused_tick != "auto"
        or matrix_events
        or config.remove_broadcast
        or config.topology == "ring"
    ):
        return False
    # Round 11: suspicion runs take the fused round too — the lifecycle's
    # observables (suspects entered / refuted, the first-suspect carry)
    # are column reductions over the recomputed tick, the same consumer-
    # fusion pattern the fail reductions already use, so the post-tick
    # lanes still never materialize.
    return not _use_pallas(config, config.fanout, n, nloc)


def _round_core_fused(
    state: SimState,
    crash: jax.Array,
    edges: jax.Array,
    config: SimConfig,
    ctx: ShardCtx = LOCAL_CTX,
    sends: jax.Array | None = None,
    arc_match: jax.Array | None = None,
) -> tuple[SimState, RoundMetrics, jax.Array, jax.Array, jax.Array,
           jax.Array | None]:
    """One crash-only round with the tick recomputed around the merge kernel.

    Semantically identical to :func:`_round_core` under
    ``matrix_events=False`` and ``remove_broadcast=False`` (pinned by
    tests/test_fused_round.py), but the post-tick state never materializes:
    the tick (bump / detect / cooldown, :func:`_tick`) is recomputed
    elementwise inside both consumers — the gossip-view build and the
    post-kernel membership update — and the fail matrix never
    materializes, only its column reductions.  Serves the XLA merge paths
    (CPU, shards, shapes without a stripe kernel); stripe-kernel shapes use
    the separate-pass round, whose in-kernel epilogue already writes each
    lane once (see :func:`_fused_ok`).  Suspicion runs (round 11) fuse
    here too: the lifecycle's transitions live in :func:`_tick` /
    :func:`_membership_update`, and its observables are column reductions
    over the recomputed tick — more consumers, no new materialization.
    ``sends``/``arc_match``: the aligned-arc scenario form, as in
    :func:`_round_core`.

    Returns (state, metrics, member_col, any_fail, first_obs, any_suspect).
    """
    n = state.n
    sus_on = config.suspicion is not None
    state = state._replace(alive=state.alive & ~crash)
    active, refresher, colmax_est = _pre_tick(state, config, ctx)
    shift_a, shift_b, store_base = _rebase_shifts(state, config, colmax_est)
    # one traced tick: XLA fuses it into the view build and the fail
    # reductions below (the arrays of st2 that feed neither are dead code)
    st2, fail = _tick(state, config, ctx, active=active, refresher=refresher)
    senders = active if sends is None else active & sends
    view = _gossip_view(st2, senders, shift_a, config)

    if arc_match is not None and config.topology == "random_arc":
        from gossipfs_tpu.ops import merge_pallas

        best_rel = merge_pallas.arc_group_window_max_xla(
            view, arc_match, config.fanout, config.arc_align
        )
    else:
        best_rel = _merge_best(st2, view, edges, config)
    # The tick feeds consumers on BOTH sides of the opaque merge kernel:
    # the view build above and the membership update below.  Left alone,
    # XLA CSEs the two into one tick whose post-tick lanes then
    # materialize across the kernel (a full [N, N] x 3 write + read).
    # The barrier gives the second tick distinct operands, so each
    # consumer fusion recomputes the elementwise tick from the carry
    # lanes instead — duplicated ALU, one less round trip to HBM.
    hb_b, age_b, status_b = lax.optimization_barrier(
        (state.hb, state.age, state.status)
    )
    st2b, _ = _tick(
        state._replace(hb=hb_b, age=age_b, status=status_b),
        config, ctx, active=active, refresher=refresher,
    )
    hb, age, status = _membership_update(
        st2b, best_rel, shift_a, shift_b, config
    )
    new_state = st2b._replace(
        hb=hb, age=age, status=status, hb_base=store_base.reshape(-1)
    )
    # per-subject live-observer count off the fresh status (fuses as a
    # consumer of the update pass; replaces _update_carry's full-matrix
    # all_dropped reduction).  Listed = MEMBER | SUSPECT under suspicion:
    # a SUSPECT holder has not dropped the entry
    member_col = jnp.sum(
        (
            _listed(status, config)
            & _rx(new_state.alive, status.ndim)
            & ~_eye(n, status.shape, ctx)
        ).astype(jnp.int32),
        axis=0,
    ).reshape(_nsubj(status.shape))
    new_state = new_state._replace(round=state.round + 1)

    sus_stats = None
    any_sus = None
    if sus_on:
        # suspicion observables — the same three snapshots _round_core
        # anchors on (post-events pre-tick, post-tick, post-merge), all
        # available here as fusion consumers of the recomputed tick
        shp_f = status.shape
        entered = (st2.status == SUSPECT) & (state.status != SUSPECT)
        refuted = (st2.status == SUSPECT) & (status == MEMBER)
        alive_col = _sj(new_state.alive, shp_f, ctx)
        sus_stats = (
            ctx.psum(jnp.sum(entered, dtype=jnp.int32)),
            ctx.psum(jnp.sum(refuted, dtype=jnp.int32)),
            ctx.psum(jnp.sum(refuted & alive_col, dtype=jnp.int32)),
        )
        any_sus = jnp.any(status == SUSPECT, axis=0).reshape(_nsubj(shp_f))

    nloc = _nsubj(fail.shape)
    n_det = jnp.sum(fail, axis=0, dtype=jnp.int32).reshape(nloc)
    first_obs_now = jnp.argmax(fail, axis=0).astype(jnp.int32).reshape(nloc)
    metrics, any_fail = _round_stats(n_det, new_state, ctx,
                                     sus_stats=sus_stats)
    return new_state, metrics, member_col, any_fail, first_obs_now, any_sus


def _gossip_round_impl(
    state: SimState,
    events: RoundEvents,
    edges: jax.Array | None,
    config: SimConfig,
) -> tuple[SimState, RoundMetrics, jax.Array, jax.Array]:
    """Advance the whole cluster by one heartbeat period.

    ``edges`` is the random-topology in-edge array; pass None for ring mode,
    where edges are derived from the post-tick membership tables (the
    reference computes push targets after updateMemberList, slave.go:510-524).
    Returns (next_state, per-round metrics, any_fail [N], first_obs [N]):
    the per-subject detection vectors, NOT the [N, N] fail matrix — the
    interactive driver (detector/sim.py ``advance``) reads them to the host
    every eventful round, so the transfer is O(N) instead of O(N^2)
    (``first_obs[j]`` is the lowest-index observer whose detector fired on
    j this round; meaningful only where ``any_fail``).

    Single-round calls pay the blocked-layout relayout on the pallas path;
    the scan in :func:`run_rounds` converts once for the whole horizon.
    """
    n = state.n
    blocked = _use_blocked(config, config.fanout, n)
    if blocked:
        state = _to_blocked(state, config)
    state, metrics, _fail, any_fail, first_obs, _, _ = _round_core(
        state, events, edges, config
    )
    if blocked:
        state = _from_blocked(state)
    return state, metrics, any_fail, first_obs


gossip_round = partial(jax.jit, static_argnames=("config",))(
    _gossip_round_impl
)
# donated variant for exclusive-owner drivers (detector/sim.py with
# donate=True): the input state's buffers are consumed, which is what fits
# the interactive single-round path at the N=49,152 capacity point — the
# non-donated call's doubled lanes + relayout copies exceed HBM there
gossip_round_donate = partial(
    jax.jit, static_argnames=("config",), donate_argnums=(0,)
)(_gossip_round_impl)


def _gossip_round_scenario_impl(
    state: SimState,
    events: RoundEvents,
    edges: jax.Array | None,
    config: SimConfig,
    tsc,
    key: jax.Array,
) -> tuple[SimState, RoundMetrics, jax.Array, jax.Array]:
    """One interactive round under an armed fault scenario.

    Same contract as :func:`_gossip_round_impl`, plus ``tsc`` (a
    scenarios.tensor.TensorScenario) and a per-round ``key`` for the
    Bernoulli loss draws.  The interactive evaluation lane runs the
    XLA-oracle config (detector.sim substitutes config.fallback_config),
    so the state stays 2-D and no blocked relayout happens; aligned-arc
    configs take the group-granular filter form (the per-edge rewrite
    has no arc shape — scenarios/tensor.py).
    """
    from gossipfs_tpu.scenarios.tensor import (
        arc_match_edges,
        filter_edges,
        sends_mask,
    )

    if config.topology == "random_arc":
        sends = sends_mask(tsc, state.n, state.round)
        arc_match = arc_match_edges(tsc, edges, state.round,
                                    config.fanout, config.arc_align)
        state, metrics, _fail, any_fail, first_obs, _, _ = _round_core(
            state, events, edges, config, sends=sends, arc_match=arc_match
        )
        return state, metrics, any_fail, first_obs
    ef = lambda e: filter_edges(tsc, e, state.round, key)  # noqa: E731
    state, metrics, _fail, any_fail, first_obs, _, _ = _round_core(
        state, events, edges, config, edge_filter=ef
    )
    return state, metrics, any_fail, first_obs


gossip_round_scenario = partial(jax.jit, static_argnames=("config",))(
    _gossip_round_scenario_impl
)


def _update_carry(
    carry: MetricsCarry,
    state: SimState,
    rejoined: jax.Array,
    any_fail: jax.Array,
    first_obs_now: jax.Array,
    round_idx: jax.Array,
    ctx: ShardCtx = LOCAL_CTX,
    member_col: jax.Array | None = None,
    any_suspect: jax.Array | None = None,
) -> MetricsCarry:
    n = state.n
    # nloc from the per-subject vector, NOT the lane shape — the rr scan
    # carries its lanes in the stripe-major layout where shape[1:] is no
    # longer the subject count
    nloc = any_fail.shape[0]
    # [nloc] — shard's slice
    first_detect, first_observer, converged, first_suspect = carry
    # rejoined = joins that actually took effect: new incarnation, new clock
    rejoined_l = ctx.slice_cols(rejoined, nloc)
    first_detect = jnp.where(rejoined_l, -1, first_detect)
    first_observer = jnp.where(rejoined_l, -1, first_observer)
    converged = jnp.where(rejoined_l, -1, converged)
    first_suspect = jnp.where(rejoined_l, -1, first_suspect)

    fresh = (first_detect < 0) & any_fail
    first_observer = jnp.where(fresh, first_obs_now, first_observer)
    first_detect = jnp.where(fresh, round_idx, first_detect)
    if any_suspect is not None:
        # EPISODE semantics: once every observer's suspicion of j has
        # cleared without a confirm (all refuted), the episode is over
        # and the clock resets — otherwise a refuted pre-crash suspicion
        # would make ttd_suspect negative and silently inflate the
        # suspect-to-confirm latency with the healthy interval between
        # episodes.  After a confirm (first_detect just set above, which
        # is why this block runs after it) the stamp freezes: it names
        # the episode that led to the detection.
        first_suspect = jnp.where(
            (first_detect < 0) & ~any_suspect, -1, first_suspect
        )
        first_suspect = jnp.where(
            (first_suspect < 0) & any_suspect, round_idx, first_suspect
        )

    alive_l = ctx.slice_cols(state.alive, nloc)
    if member_col is not None:
        # per-subject count of live non-self observers still holding the
        # entry, computed on the side by the fused stripe kernel — spares
        # the full-matrix reduction below
        all_dropped = (member_col.reshape(nloc) == 0) & ~alive_l
    else:
        nd, shp = state.status.ndim, state.status.shape
        dropped = (
            ~_rx(state.alive, nd) | _eye(n, shp, ctx) | (state.status != MEMBER)
        )
        all_dropped = jnp.all(dropped, axis=0).reshape(nloc) & ~alive_l
    converged = jnp.where((converged < 0) & all_dropped, round_idx, converged)
    return MetricsCarry(
        first_detect=first_detect, first_observer=first_observer,
        converged=converged, first_suspect=first_suspect,
    )


def _use_rr(config: SimConfig, n: int, nloc: int) -> bool:
    """Whether the lean crash-only scan runs the resident-round kernel.

    The rr kernel (merge_pallas.resident_round_blocked) folds the tick,
    the gossip-view build, the merge epilogue and every per-round
    reduction into ONE pallas call — the [N, N] view never exists in HBM
    and the per-receiver member counts are carried round-to-round instead
    of recomputed (round-4 redesign; see the kernel's module comment for
    the traffic arithmetic).  Requirements beyond the stripe kernel's:
    the lean fault model (callers: matrix_events == False), fresh
    cooldown, gossip-only dissemination, a random topology (explicit
    edges, or arc bases — the kernel then window-maxes the view stripe),
    and all-int8 lanes.
    """
    from gossipfs_tpu.ops import merge_pallas

    if not config.merge_kernel.startswith("pallas_rr"):
        return False
    if (
        config.remove_broadcast
        or not config.fresh_cooldown
        or config.topology not in ("random", "random_arc")
        or config.hb_dtype != "int8"
        # honor the debug knob: 'off' means the separate-pass round
        or config.fused_tick != "auto"
    ):
        return False
    # Round 14: the Lifeguard local-health stretch (lh_multiplier > 0)
    # is fused too — the scan carries the per-receiver SUSPECT counts
    # (a kernel output, like the member counts), derives the degraded
    # mask outside the kernel, and the kernel applies the stretched
    # confirmation threshold as a per-row select on flags bit 4.  The
    # old stripe/XLA degradation is gone.
    if config.topology == "random_arc" and (
        config.n % merge_pallas.ARC_CHUNK
        or not 1 < config.fanout <= merge_pallas.ARC_CHUNK
    ):
        return False
    if not merge_pallas.rr_supported(
            n, config.fanout, config.merge_block_c, nloc,
            config.arc_align if config.topology == "random_arc" else 1,
            block_r=config.merge_block_r,
            rotate=config.rr_rotate != "off"):
        return False
    return (
        config.merge_kernel.endswith("interpret")
        or jax.default_backend() == "tpu"
    )


def _rr_scan_eligible(config: SimConfig, n: int, nloc: int,
                      matrix_events: bool, ctx: ShardCtx,
                      scenario=None) -> bool:
    """Single rr-scan gate, shared by the dispatch in :func:`_scan_rounds`
    and the layout decision in :func:`_run_rounds_impl` — two separately
    maintained copies would let the relayout and the dispatch drift (a
    2-D state reaching the rr scan crashes its stripe-major transpose).

    Round 5: a subject-axis shard_map ctx is eligible too — the rr scan
    core is ctx-aware (shard-local row gather, psum'd counts/metrics), so
    ``run_rounds_sharded`` executes the same resident-round program the
    v5e-8 projection models.  ``nloc`` (the shard's columns) carries the
    per-shard stripe-width divisibility through ``_use_rr``.

    Round 11: an armed scenario is eligible too — explicit-edge runs
    rewrite the sampled [N, F] edges before the in-kernel gather, and
    aligned arcs run the kernel's ``edge_filter`` masked-gather form
    (group-match mask packed in an int32 — hence the nw bound; the rule
    compatibility itself was validated at the run entry,
    scenarios.tensor.require_scenario_config).
    """
    if matrix_events or not _use_rr(config, n, nloc):
        return False
    if scenario is not None and config.topology == "random_arc":
        from gossipfs_tpu.ops.merge_pallas import ARC_MATCH_MAX_GROUPS

        return (config.arc_align > 1
                and config.fanout // config.arc_align
                <= ARC_MATCH_MAX_GROUPS)
    return True


def _scan_rounds_rr(
    state: SimState,
    config: SimConfig,
    key: jax.Array,
    events: RoundEvents,
    crash_rate: float,
    churn_ok: jax.Array | None,
    mcarry0: MetricsCarry | None = None,
    ctx: ShardCtx = LOCAL_CTX,
    scenario=None,
) -> tuple[SimState, MetricsCarry, RoundMetrics]:
    """The lean crash-only scan over the resident-round kernel.

    Semantically identical to :func:`_scan_rounds` under
    ``matrix_events=False`` (pinned by tests/test_merge_pallas.py's rr
    parity tests): scheduled leave bits mean silent death, join bits are
    ignored, and the per-receiver member counts feeding the small-group
    split are carried across rounds (post-merge status is next round's
    post-events status on this path, so the carried count is exact).

    Under a subject-axis shard_map (``ctx.axis`` set) the lanes are this
    shard's stripes; rows stay global, so the kernel's row gather remains
    shard-local and only the [N]-vector member counts and metric sums
    cross chips (ctx.psum).
    """
    from gossipfs_tpu.ops import merge_pallas

    # stripe-major lane layout [nc, N, cs, LANE] for the whole scan: each
    # stripe's rows become one contiguous region, so every kernel DMA is a
    # single contiguous transfer (one transpose each way per scan).  The
    # age and status lanes travel PACKED into one byte
    # (merge_pallas.pack_age_status) — the kernel's HBM wire is 2 B/entry,
    # a third less traffic than the 3-lane form on a bandwidth-bound round.
    tr = lambda a: a.transpose(1, 0, 2, 3)  # noqa: E731
    hb4 = tr(state.hb)
    as4 = merge_pallas.pack_age_status(tr(state.age), tr(state.status))
    hb4, as4, alive, hb_base, rnd, _, _, mcarry, per_round = (
        _scan_rounds_rr_packed(
            hb4, as4, state.alive, state.hb_base, state.round,
            config, key, events, crash_rate, churn_ok, mcarry0,
            ctx=ctx, scenario=scenario,
        )
    )
    age_w, st_w = merge_pallas.unpack_age_status(as4)
    state = state._replace(
        hb=tr(hb4), age=tr(age_w.astype(jnp.int8)),
        status=tr(st_w.astype(jnp.int8)), alive=alive, hb_base=hb_base,
        round=rnd,
    )
    return state, mcarry, per_round


def rr_packed_init(config: SimConfig, member_mask=None) -> tuple:
    """Fully-joined packed stripe-major initial state for the rr core.

    Device arrays built directly in the scan's own layout — the frontier
    entry points (bench/frontier.py, detector.sim.PackedDetector) call
    this instead of init_state because three [N, N] SimState lanes plus
    blocked copies exceed HBM at N=65,536 before the scan starts.
    Returns (hb4, as4, alive, hb_base, round, counts).

    ``member_mask`` bool [N]: nodes outside it start permanently dead
    and UNKNOWN everywhere — the literal-N padding support
    (bench/frontier.py pads e.g. 100,000 up to the next stripe-aligned
    size with dead pad nodes; zero kernel changes).  Pads never bump
    (dead), are never MEMBER in any row (so they are invisible to
    detection, convergence and SDFS placement), and stay dead as long
    as the caller excludes them from churn/joins (churn_ok).
    """
    from gossipfs_tpu.ops import merge_pallas

    n = config.n
    lane = merge_pallas.LANE
    nc = n // config.merge_block_c
    cs = config.merge_block_c // lane
    # pack_age_status(age=0, MEMBER) / (age=0, UNKNOWN) as Python
    # constants — computing them through jnp breaks callers that jit
    # around this initializer
    joined = int(MEMBER) - 128
    unknown = int(UNKNOWN) - 128

    @jax.jit
    def init():
        return (
            jnp.zeros((nc, n, cs, lane), jnp.int8),
            jnp.full((nc, n, cs, lane), joined, jnp.int8),
            jnp.ones((n,), bool),
            jnp.zeros((n,), jnp.int32),
            jnp.int32(0),
            jnp.full((n,), n, jnp.int32),
        )

    if member_mask is None:
        return init()

    @jax.jit
    def init_masked(mask):
        mask = mask.astype(bool)
        # stripe-major subject axes: subject j sits at
        # [j // c_blk, :, (j % c_blk) // lane, j % lane]
        colm = mask.reshape(nc, 1, cs, lane)
        rowm = mask.reshape(1, n, 1, 1)
        as4 = jnp.where(rowm & colm, jnp.int8(joined), jnp.int8(unknown))
        n_live = jnp.sum(mask, dtype=jnp.int32)
        counts = jnp.where(mask, n_live, 0)
        return (
            jnp.zeros((nc, n, cs, lane), jnp.int8),
            as4,
            mask,
            jnp.zeros((n,), jnp.int32),
            jnp.int32(0),
            counts,
        )

    return init_masked(jnp.asarray(member_mask))


def _scan_rounds_rr_packed(
    hb4: jax.Array,
    as4: jax.Array,
    alive0: jax.Array,
    hb_base0: jax.Array,
    round0: jax.Array,
    config: SimConfig,
    key: jax.Array,
    events: RoundEvents,
    crash_rate: float,
    churn_ok: jax.Array | None,
    mcarry0: MetricsCarry | None = None,
    counts0: jax.Array | None = None,
    sus_counts0: jax.Array | None = None,
    ctx: ShardCtx = LOCAL_CTX,
    scenario=None,
) -> tuple:
    """The rr scan core over stripe-major PACKED lanes.

    ``hb4`` int8 and ``as4`` (merge_pallas.pack_age_status) in the
    [nc, N, cs, LANE] stripe-major layout.  Split out from
    :func:`_scan_rounds_rr` so capacity-frontier callers
    (bench/frontier.py) can build the packed lanes directly — at N=65,536
    the three separate [N, N] int8 lanes of a SimState plus their blocked
    copies exceed the chip's HBM before the scan even starts, while the
    packed pair (2 B/entry, built in place by a jitted initializer) fits
    with room for the scan.

    Sharded form (``ctx.axis`` set): ``hb4``/``as4`` hold this shard's
    stripes ([nc_local, N, cs, LANE] — rows global, columns local),
    ``hb_base0``/``mcarry0`` are the shard's per-subject slices, and
    ``alive``/``counts``/events stay replicated.  The kernel gets the
    shard's global column offset for its diagonal mask; the only
    cross-shard traffic is the [N]-vector member-count psum (joined by
    the [N]-vector suspect-count psum on lh-armed runs — round 14's
    local-health lane) and the scalar metric psums — the row gather
    never leaves the chip.

    ``sus_counts0``: the carried per-receiver SUSPECT counts (the
    local-health lane, ``config.suspicion.lh_multiplier > 0`` only);
    None computes them from the packed lanes, exactly like ``counts0``.
    The degraded mask anchors on the pre-tick status — on this path the
    previous round's post-merge status, which the kernel counts on the
    side (``suspect_cnt``) — matching the XLA ``_tick``'s ``status0``
    anchor bit for bit.
    """
    from gossipfs_tpu.ops import merge_pallas

    if scenario is not None:
        from gossipfs_tpu.scenarios.tensor import (
            arc_match_edges as scn_arc_match,
            filter_edges as scn_filter_edges,
            sends_mask as scn_sends_mask,
        )
    sus = config.suspicion
    arc_topo = config.topology == "random_arc"
    interp = config.merge_kernel.endswith("interpret")
    lane = merge_pallas.LANE
    nc, n, cs, _ = hb4.shape
    subj_shape = (nc, cs, lane)
    c_blk = cs * lane
    nloc = nc * c_blk
    # floor-traffic resident lanes whenever the three stripes fit VMEM
    # (the headline shape and the N=32,768 frontier; wider/larger shapes
    # stream receiver blocks as before)
    resident = config.rr_resident != "off" and (
        merge_pallas.rr_resident_supported(
            n, config.fanout, c_blk, nloc,
            arc_align=(config.arc_align
                       if config.topology == "random_arc" else 1),
            block_r=config.merge_block_r,
            rotate=config.rr_rotate != "off",
        )
    )

    def diag(arr4):  # subject j's own row entry, stripe-major layout
        jl = jnp.arange(nloc)          # local column index
        rows = jl + ctx.offset         # the diagonal sits at global row j
        return arr4[jl // c_blk, rows, (jl % c_blk) // lane, jl % lane]

    lh = sus is not None and sus.lh_multiplier > 0
    if counts0 is None or (lh and sus_counts0 is None):
        # a full pass over the packed lane; per-round drivers
        # (detector.sim.PackedDetector) thread the carried counts back in
        # instead of paying it every advance.  Listed = MEMBER | SUSPECT
        # under suspicion (a suspect still counts toward min_group) —
        # status bit 0 is the listed bit in the core/state.py encoding
        st0 = merge_pallas.unpack_age_status(as4)[1]
        if counts0 is None:
            listed0 = (st0 & 1) == 1 if sus is not None else st0 == MEMBER
            counts0 = ctx.psum(jnp.sum(
                listed0.astype(jnp.int32),
                axis=(0, 2, 3),
            ))
        if lh and sus_counts0 is None:
            # the local-health lane's initial per-receiver suspect counts
            sus_counts0 = ctx.psum(jnp.sum(
                (st0 == SUSPECT).astype(jnp.int32),
                axis=(0, 2, 3),
            ))

    class _Cols(NamedTuple):  # what _round_stats/_update_carry consume
        alive: jax.Array
        n: int

    def step(carry, ev: RoundEvents):
        if lh:
            hb4, as4, alive0, hb_base, rnd, mc, counts, sus_counts = carry
        else:
            hb4, as4, alive0, hb_base, rnd, mc, counts = carry
            sus_counts = None
        k = jax.random.fold_in(key, rnd)
        k_edge, k_churn = jax.random.split(k)
        crash = ev.crash | ev.leave
        if crash_rate > 0.0:
            c2, _ = topology.churn_masks(k_churn, alive0, crash_rate, 0.0)
            if churn_ok is not None:
                c2 = c2 & churn_ok
            crash = crash | c2
        alive = alive0 & ~crash
        small = counts < config.min_group
        active = alive & ~small
        refresher = alive & small
        # per-subject rebase vectors (_pre_tick's diagonal anchor + the
        # shared rebase policy; int8 mode: view and storage windows
        # coincide, so sa == sb)
        basec = hb_base
        colmax_est = diag(hb4).astype(jnp.int32) + basec + 1
        sa, sb, store_base = _rebase_shifts_vec(
            hb4.dtype, basec, config, colmax_est
        )
        g = config.hb_grace - basec
        muted = None
        if scenario is not None and arc_topo:
            # aligned-arc slow-sender mute rides the flags (bit 3): the
            # kernel's view encode drops the whole row — the sender-side
            # equivalent of rewriting all its out-edges (the per-edge
            # form aligned arcs don't have)
            muted = ~scn_sends_mask(scenario, n, rnd)
        lh_deg = None
        if lh:
            # Lifeguard degraded mask — the SAME float32 compare as the
            # XLA _tick's status0-anchored count branch (and runtime.py's
            # ``degraded``, given lh_frac as an exact binary fraction):
            # an anomalous fraction of this receiver's listed entries
            # simultaneously SUSPECT.  The carried counts ARE the
            # pre-tick counts on this path (post-merge status of round
            # t-1 == pre-tick status of round t under the lean model).
            lh_deg = (sus_counts.astype(jnp.float32)
                      > sus.lh_frac * counts.astype(jnp.float32))
        flags = (
            active.astype(jnp.int32)
            + refresher.astype(jnp.int32) * 2
            + alive.astype(jnp.int32) * 4
            + (muted.astype(jnp.int32) * 8 if muted is not None else 0)
            + (lh_deg.astype(jnp.int32) * 16 if lh_deg is not None else 0)
        ).astype(jnp.int8)
        # LANE-compacted flags layout ([N/LANE, LANE] row-major, 1 B/row
        # of kernel VMEM instead of the lane-replicated LANE B/row); the
        # kernel wrapper expands it back only when its blocking cannot
        # take the compact form (merge_pallas.rr_flags_compact_ok)
        if n % lane == 0:
            flags = flags.reshape(n // lane, lane)
        else:  # pragma: no cover - rr requires lane-aligned N
            flags = jnp.broadcast_to(flags[:, None], (n, lane))
        edges = topology.in_edges(config, k_edge, None)
        arc_fanout = config.fanout if arc_topo else None
        edge_filter = False
        if scenario is not None:
            # same per-round key derivation as the non-rr scan, so a
            # horizon is bit-identical across dispatches
            k_scn = jax.random.fold_in(k, 0x5CE)
            if arc_topo:
                # group-granular partition filter: (base, match-mask)
                # pairs drive the kernel's masked gather
                edges = scn_arc_match(scenario, edges, rnd,
                                      config.fanout, config.arc_align)
                edge_filter = True
            else:
                # explicit-edge rewrite: a dropped message's edge points
                # at the receiver — the kernel gathers the receiver's own
                # view row, a no-op merge (scenarios/tensor.py)
                edges = scn_filter_edges(scenario, edges, rnd, k_scn)
        (hb2, as2, cnt_incl, ndet, fobs, rcnt, nsus, nref, suscnt,
         *lh_out) = (
            merge_pallas.resident_round_blocked(
                edges, hb4, as4, flags,
                sa.reshape(subj_shape), sb.reshape(subj_shape),
                g.reshape(subj_shape), fanout=arc_fanout,
                member=int(MEMBER), unknown=int(UNKNOWN), failed=int(FAILED),
                age_clamp=AGE_CLAMP, window=config.rebase_window,
                t_fail=config.t_fail, t_cooldown=config.t_cooldown,
                block_r=config.merge_block_r, interpret=interp,
                resident=resident, col_offset=ctx.offset,
                arc_align=config.arc_align,
                elementwise=config.elementwise,
                rotate=config.rr_rotate != "off",
                suspect=int(SUSPECT) if sus is not None else None,
                t_suspect=sus.t_suspect if sus is not None else 0,
                lh_multiplier=sus.lh_multiplier if lh else 0,
                edge_filter=edge_filter,
            )
        )
        # two count forms (merge_pallas.resident_round_blocked): the
        # LANE-COMPACTED [N/LANE, LANE] block (deep-stripe shapes) IS the
        # count vector; the lane-replicated per-stripe [N, nc*LANE] form
        # reduces by summing ALL lanes and dividing by LANE — a
        # contiguous reduce (the [:, :, 0] slice formulation was a
        # strided gather, ~7x slower over the 33 MB buffer).  Sharded:
        # each shard's rcnt covers its own stripes — the psum completes
        # the per-receiver count (the scan's one [N]-vector collective)
        def recv_count_vec(cnt):
            if cnt.size == n:
                return cnt.reshape(n).astype(jnp.int32)
            return jnp.sum(cnt.reshape(n, -1), axis=1, dtype=jnp.int32) // lane

        counts_next = ctx.psum(recv_count_vec(rcnt))
        sus_counts_next = None
        if lh:
            # the local-health lane: the kernel's per-receiver suspect
            # counts (same two forms as rcnt) become next round's
            # degraded-mask input; psum completes them across shards
            sus_counts_next = ctx.psum(recv_count_vec(lh_out[0]))
        cols = _Cols(alive=alive, n=n)
        n_det = ndet.reshape(nloc)
        first_obs = fobs.reshape(nloc)
        sus_stats = None
        any_sus = None
        if sus is not None:
            # suspicion observables off the kernel's per-subject
            # reductions — the XLA path's full-matrix snapshot reductions
            # never happen on the fused fast path
            nsus_v = nsus.reshape(nloc)
            nref_v = nref.reshape(nloc)
            alive_l = ctx.slice_cols(alive, nloc)
            sus_stats = (
                ctx.psum(jnp.sum(nsus_v)),
                ctx.psum(jnp.sum(nref_v)),
                ctx.psum(jnp.sum(jnp.where(alive_l, nref_v, 0))),
            )
            any_sus = suscnt.reshape(nloc) > 0
        metrics, any_fail = _round_stats(n_det, cols, ctx,
                                         sus_stats=sus_stats)
        # the diagonal is never SUSPECT (self-suspicion needs staleness,
        # which excludes self), so the MEMBER test is the listed test
        self_member = ctx.slice_cols(alive, nloc) & (
            merge_pallas.unpack_age_status(diag(as2))[1] == MEMBER
        )
        member_col = cnt_incl.reshape(nloc) - self_member.astype(jnp.int32)
        rejoined = jnp.zeros_like(alive)  # constant: resets fold away
        mc = _update_carry(mc, cols, rejoined, any_fail, first_obs, rnd,
                           ctx, member_col=member_col, any_suspect=any_sus)
        out_carry = (hb2, as2, alive, store_base, rnd + 1, mc, counts_next)
        if lh:
            out_carry = out_carry + (sus_counts_next,)
        return out_carry, metrics

    if mcarry0 is None:
        mcarry0 = MetricsCarry.init(nloc)
    carry0 = (hb4, as4, alive0, hb_base0, round0, mcarry0, counts0)
    if lh:
        carry0 = carry0 + (sus_counts0,)
    final, per_round = lax.scan(step, carry0, events)
    (hb4, as4, alive, hb_base, rnd, mcarry, counts, *lh_tail) = final
    sus_counts = lh_tail[0] if lh else None
    return (hb4, as4, alive, hb_base, rnd, counts, sus_counts, mcarry,
            per_round)


def _scan_rounds(
    state: SimState,
    config: SimConfig,
    key: jax.Array,
    events: RoundEvents,
    crash_rate: float,
    rejoin_rate: float,
    churn_ok: jax.Array | None,
    ctx: ShardCtx,
    mcarry0: MetricsCarry | None = None,
    matrix_events: bool = True,
    scenario=None,
) -> tuple[SimState, MetricsCarry, RoundMetrics]:
    """The shared scan over rounds (state in its final layout already).

    Called by :func:`run_rounds` (single program, possibly GSPMD-sharded on
    the XLA path) and by ``parallel.mesh.run_rounds_sharded`` (explicit
    shard_map, per-shard state).  Churn masks and edges derive from
    replicated inputs (alive, key), so every shard computes identical
    events — no cross-shard communication beyond ``ctx.psum``.

    ``mcarry0`` seeds the metrics carry, so a horizon split into several
    scans (e.g. the detector's chunked bulk advancement, which reads a
    small membership view between chunks) accumulates first-detection /
    convergence rounds exactly as one long scan would.

    ``scenario``: optional compiled fault-injection rule table
    (scenarios.tensor.TensorScenario) — per-round edge filters drop
    cross-partition / lossy / lagging messages.  Scenario scans run the
    XLA merge path (enforced upstream), so the rr dispatch below never
    fires for them.
    """
    if scenario is not None:
        from gossipfs_tpu.scenarios.tensor import (
            arc_match_edges as scn_arc_match,
            filter_edges as scn_filter_edges,
            sends_mask as scn_sends_mask,
        )
    if _rr_scan_eligible(config, state.n, _nsubj(state.hb.shape),
                         matrix_events, ctx, scenario=scenario):
        # whole round in one kernel; rejoin_rate is 0 here (a nonzero rate
        # forces matrix_events at the caller)
        return _scan_rounds_rr(
            state, config, key, events, crash_rate, churn_ok, mcarry0,
            ctx=ctx, scenario=scenario,
        )
    fused = _fused_ok(config, matrix_events, state.n, _nsubj(state.hb.shape))

    def step(carry, ev: RoundEvents):
        st, mc = carry
        k = jax.random.fold_in(key, st.round)
        k_edge, k_churn = jax.random.split(k)
        if crash_rate > 0.0 or rejoin_rate > 0.0:
            crash, join = topology.churn_masks(k_churn, st.alive, crash_rate, rejoin_rate)
            if churn_ok is not None:
                crash, join = crash & churn_ok, join & churn_ok
            # rejoin_rate is static: with no random rejoins, keep ev.join
            # as-is instead of OR-ing in a dynamically-false mask — if the
            # scheduled joins are trace-time-constant zeros (crash-only
            # runs), XLA then folds the whole join chain out of the round
            if rejoin_rate > 0.0:
                ev = RoundEvents(crash=ev.crash | crash, leave=ev.leave,
                                 join=ev.join | join)
            else:
                ev = RoundEvents(crash=ev.crash | crash, leave=ev.leave,
                                 join=ev.join)
        ef = None
        if scenario is not None:
            k_scn = jax.random.fold_in(k, 0x5CE)
            ef = lambda e: scn_filter_edges(scenario, e, st.round, k_scn)  # noqa: E731
        sends = arc_match = None
        if config.topology == "ring":
            edges = None  # derived per-round from the membership tables
            ring_filter = ef  # applied inside _round_core, post-derivation
        elif config.topology == "random_arc":
            edges = topology.in_edges(config, k_edge, None)  # arc bases
            ring_filter = None
            if scenario is not None:
                # aligned-arc scenario form: group-granular partition
                # match masks + sender mute (scenarios/tensor.py) — the
                # per-edge rewrite has no arc form, the group form is
                # exactly equivalent for align-closed sides
                sends = scn_sends_mask(scenario, st.n, st.round)
                arc_match = scn_arc_match(scenario, edges, st.round,
                                          config.fanout, config.arc_align)
        else:
            edges = topology.in_edges(config, k_edge, None)
            if ef is not None:
                edges = ef(edges)
            ring_filter = None
        round_idx = st.round
        alive_before = st.alive
        if fused:
            # matrix_events is False here, so scheduled leaves (if any) can
            # only mean silent death — same liveness effect as a crash
            # (non-ring only, so any scenario filter already ran above)
            (st, metrics, member_col, any_fail, first_obs,
             any_sus) = _round_core_fused(
                st, ev.crash | ev.leave, edges, config, ctx,
                sends=sends, arc_match=arc_match,
            )
        else:
            (st, metrics, _fail, any_fail, first_obs, member_col,
             any_sus) = _round_core(
                st, ev, edges, config, ctx, matrix_events=matrix_events,
                edge_filter=ring_filter, sends=sends, arc_match=arc_match,
            )
        # joins lost to a dead introducer don't reset metrics (slave.go:22 SPOF)
        if matrix_events:
            rejoined = ev.join & ~alive_before & st.alive
        else:
            rejoined = jnp.zeros_like(st.alive)  # constant: resets fold away
        mc = _update_carry(mc, st, rejoined, any_fail, first_obs, round_idx, ctx,
                           member_col=member_col, any_suspect=any_sus)
        return (st, mc), metrics

    if mcarry0 is None:
        mcarry0 = MetricsCarry.init(_nsubj(state.hb.shape))
    (state, mcarry), per_round = lax.scan(step, (state, mcarry0), events)
    return state, mcarry, per_round


def _run_rounds_impl(
    state: SimState,
    config: SimConfig,
    num_rounds: int,
    key: jax.Array,
    events: RoundEvents | None = None,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
    churn_ok: jax.Array | None = None,
    mcarry0: MetricsCarry | None = None,
    crash_only_events: bool = False,
    scenario=None,
) -> tuple[SimState, MetricsCarry, RoundMetrics]:
    """Scan ``num_rounds`` gossip rounds.

    ``events``: optional pre-scheduled RoundEvents stacked to [num_rounds, N]
    (deterministic fault injection — the sim's CTRL+C).  ``crash_rate`` /
    ``rejoin_rate`` add per-round random churn on top (BASELINE configs 3/4).
    ``churn_ok``: optional bool [N] mask of nodes eligible for *random* churn
    — benchmark runs exclude their tracked crash victims so a random rejoin
    can't reset the tracked detection/convergence rounds mid-measurement.
    ``mcarry0``: optional carry from a previous scan, making a chunked
    horizon bit-identical to one long scan (SURVEY §7.4's async boundary
    is served by reading small views between chunks — see
    ``detector.sim.SimDetector.advance_bulk`` — instead of in-scan host
    callbacks, which cannot cross a remote-PJRT TPU tunnel).
    Returns final state, per-subject detection/convergence rounds, and
    per-round metrics stacked over the horizon.

    For multi-device runs on the pallas path use
    ``parallel.mesh.run_rounds_sharded`` — under plain GSPMD the pallas
    custom call has no partitioning rule and XLA all-gathers the full state
    around it; the XLA merge path partitions cleanly either way.
    """
    n = config.n
    # static: no scheduled events + no random rejoins => the leave/join
    # matrix rewrites drop out of the compiled round entirely.
    # ``crash_only_events`` is the caller's static promise that scheduled
    # events carry no leave/join bits (e.g. bench.tracked_crash_events),
    # which keeps the lean event path — and, with it, the in-kernel
    # detection stats and the fail matrix never materializing — even with
    # a tracked-crash schedule.  Leave bits are still honored as silent
    # death (same liveness effect), join bits would be IGNORED.
    matrix_events = (
        events is not None and not crash_only_events
    ) or rejoin_rate > 0.0
    if events is None:
        zeros = jnp.zeros((num_rounds, n), dtype=bool)
        events = RoundEvents(crash=zeros, leave=zeros, join=zeros)

    blocked = _use_blocked(config, config.fanout, n)
    if not blocked and _rr_scan_eligible(config, n, n, matrix_events,
                                         LOCAL_CTX, scenario=scenario):
        # the rr scan accepts narrower stripe widths than the stripe
        # kernels _use_blocked models (rr_supported vs stripe_supported);
        # it consumes the blocked layout regardless
        blocked = True
    if blocked:
        # one relayout for the whole horizon (see module header)
        state = _to_blocked(state, config)
    state, mcarry, per_round = _scan_rounds(
        state, config, key, events, crash_rate, rejoin_rate, churn_ok, LOCAL_CTX,
        mcarry0=mcarry0, matrix_events=matrix_events, scenario=scenario,
    )
    if blocked:
        state = _from_blocked(state)
    return state, mcarry, per_round


_RUN_ROUNDS_STATIC = (
    "config", "num_rounds", "crash_rate", "rejoin_rate", "crash_only_events"
)
_run_rounds_jit = partial(jax.jit, static_argnames=_RUN_ROUNDS_STATIC)(
    _run_rounds_impl
)
_run_rounds_donate_jit = partial(
    jax.jit, static_argnames=_RUN_ROUNDS_STATIC, donate_argnums=(0,)
)(_run_rounds_impl)


def check_crash_only_promise(
    events: RoundEvents | None, crash_only_events: bool
) -> None:
    """Fail loudly when a join-carrying schedule meets crash_only_events.

    ``crash_only_events=True`` is the caller's static promise that the
    schedule carries no join bits (leave bits are honored as silent death;
    join bits would be silently IGNORED on the lean path) — enforced while
    the events are still concrete, so a schedule that breaks the promise
    fails instead of simulating the wrong dynamics.  Shared by every entry
    that takes the flag (run_rounds, run_rounds_donate,
    parallel.mesh.run_rounds_sharded).
    """
    if crash_only_events and events is not None and not isinstance(
        events.join, jax.core.Tracer
    ):
        if bool(jnp.any(events.join)):
            raise ValueError(
                "crash_only_events=True ignores events.join, but the "
                "schedule contains join bits — drop the flag or the joins"
            )


def run_rounds(
    state: SimState,
    config: SimConfig,
    num_rounds: int,
    key: jax.Array,
    events: RoundEvents | None = None,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
    churn_ok: jax.Array | None = None,
    mcarry0: MetricsCarry | None = None,
    crash_only_events: bool = False,
    scenario=None,
) -> tuple[SimState, MetricsCarry, RoundMetrics]:
    """Jitted entry for :func:`_run_rounds_impl` (same signature/docs).

    ``scenario``: a compiled scenarios.tensor.TensorScenario (or None).
    Round 11: scenario runs keep the CONFIGURED merge kernel — the rr
    scan rewrites the sampled edges (or runs the aligned-arc masked
    gather) and the XLA/stripe paths consume filtered edges natively;
    only the per-scenario capability matrix is validated here
    (scenarios.tensor.require_scenario_config).
    """
    check_crash_only_promise(events, crash_only_events)
    if scenario is not None:
        from gossipfs_tpu.scenarios.tensor import require_scenario_config

        require_scenario_config(config, scenario)
    return _run_rounds_jit(
        state, config, num_rounds, key, events, crash_rate, rejoin_rate,
        churn_ok, mcarry0, crash_only_events, scenario,
    )


def run_rounds_donate(
    state: SimState,
    config: SimConfig,
    num_rounds: int,
    key: jax.Array,
    events: RoundEvents | None = None,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
    churn_ok: jax.Array | None = None,
    mcarry0: MetricsCarry | None = None,
    crash_only_events: bool = False,
    scenario=None,
) -> tuple[SimState, MetricsCarry, RoundMetrics]:
    """In-place variant: XLA reuses the input state's HBM for the output
    (the caller's ``state`` is consumed).  At N=32k the scan needs ~13 GiB
    without aliasing — past a v5e chip's headroom — and ~9 GiB with it.
    """
    check_crash_only_promise(events, crash_only_events)
    if scenario is not None:
        from gossipfs_tpu.scenarios.tensor import require_scenario_config

        require_scenario_config(config, scenario)
    return _run_rounds_donate_jit(
        state, config, num_rounds, key, events, crash_rate, rejoin_rate,
        churn_ok, mcarry0, crash_only_events, scenario,
    )


# the guard wrappers keep the jitted functions' introspection surface:
# callers (and tests) use lower()/AOT, cache-size assertions, and explicit
# cache clears on these names
for _wrapper, _jitted in ((run_rounds, _run_rounds_jit),
                          (run_rounds_donate, _run_rounds_donate_jit)):
    _wrapper._cache_size = _jitted._cache_size
    _wrapper.clear_cache = _jitted.clear_cache
    _wrapper.lower = _jitted.lower
