"""The gossip round kernel: one synchronous step advances all N nodes.

This replaces the reference's per-node 1 s heartbeat goroutine
(``HeartBeat``, reference: slave/slave.go:499-544 driven by main.go:27-33) with
a single batched tensor program.  One call == one heartbeat period == 1
simulated second for every node at once.  Mapping (SURVEY.md §7.1):

  Go behaviour (cite)                          -> tensor op here
  bump own heartbeat (slave.go:443-448)        -> diagonal += alive & !small
  refresh-only when list < 4 (slave.go:504-509)-> age[i, member] = 0 for small rows
  detect hb>1 & age>5 (slave.go:460-476)       -> fail mask over [N, N]
  REMOVE broadcast to all (slave.go:338-363)   -> any-over-observers OR into columns
  RecentFailList cooldown (slave.go:484-497)   -> FAILED entries expire to UNKNOWN
  push list to fanout + max-merge + local
  timestamp (slave.go:527-542, 414-427)        -> row gather over in-edges,
                                                  elementwise max, age reset
  join via introducer push (slave.go:250-274)  -> introducer row broadcast
  leave broadcast (slave.go:310-336)           -> column mark FAILED

The Go system is asynchronous (UDP datagrams land whenever); the sim uses the
standard synchronous-rounds model: messages sent in round t are merged before
round t+1's detection pass, which is what the 1 s period effectively gives the
reference on a LAN.

Everything here is pure jnp on static shapes — safe under ``jit``,
``lax.scan``, and GSPMD sharding (see parallel/mesh.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from gossipfs_tpu.config import AGE_CLAMP, SimConfig
from gossipfs_tpu.core import topology
from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN, RoundEvents, SimState


class RoundMetrics(NamedTuple):
    """Per-round scalar observables (cheap enough to stack over any horizon)."""

    true_detections: jax.Array   # detector fired on an actually-dead subject
    false_positives: jax.Array   # detector fired on a live subject
    n_alive: jax.Array


class MetricsCarry(NamedTuple):
    """Per-subject first-detection / convergence rounds, carried across the scan.

    ``first_detect[j]``: first round any observer's detector fired on j.
    ``converged[j]``: first round every live observer had dropped j from its
    list (the cluster-wide detection-complete time the BASELINE curves want).
    Both are -1 until the event happens; reset to -1 when j rejoins.
    """

    first_detect: jax.Array  # int32 [N]
    converged: jax.Array     # int32 [N]

    @staticmethod
    def init(n: int) -> "MetricsCarry":
        neg = jnp.full((n,), -1, dtype=jnp.int32)
        return MetricsCarry(first_detect=neg, converged=neg)


def _apply_events(state: SimState, events: RoundEvents, config: SimConfig) -> SimState:
    """Crash / leave / join, before the heartbeat tick (see module docstring).

    All-false event masks flow through as plain masked passes: XLA fuses
    them into the neighbouring elementwise chains nearly for free, and
    measuring ``lax.cond``-guarded variants showed the branch overhead +
    lost fusion costs ~8% of round time at N=16k — skip-if-empty does not
    pay here.
    """
    hb, age, status, alive = state.hb, state.age, state.status, state.alive

    # -- leave: broadcast LEAVE, receivers remove + fail-list (slave.go:310-336).
    # The entry moves onto the fail list keeping its *existing* timestamp
    # (removeMember appends the live Member struct, slave.go:276-286), so age
    # keeps running — cooldown is measured from the last gossip refresh.
    leave = events.leave & alive
    mark = alive[:, None] & (status == MEMBER) & leave[None, :]
    status = jnp.where(mark, FAILED, status)
    if config.fresh_cooldown:
        age = jnp.where(mark, 0, age)

    # -- crash-stop: silent death (README.md:30 "CTRL+C to crash")
    alive = alive & ~(events.crash | leave)

    # -- join: introducer appends unconditionally (addNewMember, slave.go:250-274)
    #    then pushes its full list to every member; receivers merge-add unless
    #    the joiner is on their RecentFailList (slave.go:430-439).
    join = events.join & ~alive
    intro = config.introducer
    intro_alive = alive[intro]
    eff = join & intro_alive  # joins are lost if the introducer is down (SPOF kept)

    # introducer's own row: unconditional append at hb=0
    intro_row_add = eff & (jnp.arange(state.n) != intro)
    intro_sel = (jnp.arange(state.n) == intro)[:, None] & intro_row_add[None, :]
    status = jnp.where(intro_sel, MEMBER, status)
    hb = jnp.where(intro_sel, 0, hb)
    age = jnp.where(intro_sel, 0, age)

    # everyone else merges the introducer's pushed list: add joiner if UNKNOWN
    recv_add = alive[:, None] & (status == UNKNOWN) & eff[None, :]
    status = jnp.where(recv_add, MEMBER, status)
    hb = jnp.where(recv_add, 0, hb)
    age = jnp.where(recv_add, 0, age)

    # the joiner's fresh table = the introducer's post-append row (it receives
    # the same full-list push); a fresh process has an empty fail list.
    joiner_status = jnp.where(status[intro] == MEMBER, MEMBER, UNKNOWN)
    joiner_hb = jnp.where(status[intro] == MEMBER, hb[intro], 0)
    new_row = eff[:, None]
    status = jnp.where(new_row, joiner_status[None, :], status)
    hb = jnp.where(new_row, joiner_hb[None, :], hb)
    age = jnp.where(new_row, 0, age)
    # self entry always present (InitMembership, slave.go:161-167)
    self_sel = new_row & (jnp.arange(state.n)[None, :] == jnp.arange(state.n)[:, None])
    status = jnp.where(self_sel, MEMBER, status)
    hb = jnp.where(self_sel, 0, hb)

    alive = alive | eff
    return SimState(hb=hb, age=age, status=status, alive=alive, round=state.round)


def _tick(
    state: SimState, config: SimConfig
) -> tuple[SimState, jax.Array, jax.Array]:
    """Per-node heartbeat pass: refresh/bump/detect/remove-broadcast/cooldown.

    Returns (state, fail_events [N,N] bool, active [N] bool senders).
    """
    n = state.n
    hb, age, status, alive = state.hb, state.age, state.status, state.alive
    eye = jnp.eye(n, dtype=bool)

    counts = jnp.sum((status == MEMBER).astype(jnp.int32), axis=1)
    small = counts < config.min_group
    active = alive & ~small
    refresher = alive & small

    # small groups only refresh timestamps (slave.go:504-509)
    refresh_all = refresher[:, None] & (status == MEMBER)
    age = jnp.where(refresh_all, 0, age)

    # bump own heartbeat + stamp — only while the self entry is still in the
    # list (updateMemberList matches by address, slave.go:443-448; a node that
    # processed a REMOVE about itself stops bumping)
    bump = eye & active[:, None] & (status == MEMBER)
    hb = hb + bump.astype(jnp.int32)
    age = jnp.where(bump, 0, age)

    # failure detection (slave.go:460-476): member, not self, past the hb
    # grace, and silent for more than t_fail rounds.  Removed entries keep
    # their stale timestamp on the fail list (slave.go:276-286): age runs on.
    fail = (
        active[:, None]
        & (status == MEMBER)
        & ~eye
        & (hb > config.hb_grace)
        & (age > config.t_fail)
    )
    status = jnp.where(fail, FAILED, status)
    if config.fresh_cooldown:
        age = jnp.where(fail, 0, age)

    # REMOVE broadcast (slave.go:338-363): one detection removes j everywhere
    # this round.  North-star mode turns this off and lets removal spread by
    # gossip omission instead.
    if config.remove_broadcast:
        removed = jnp.any(fail, axis=0)
        mark = alive[:, None] & (status == MEMBER) & removed[None, :]
        status = jnp.where(mark, FAILED, status)
        if config.fresh_cooldown:
            age = jnp.where(mark, 0, age)

    # fail-list cooldown expiry (cleanFailList, slave.go:484-497).  Because the
    # fail-list entry keeps its last-refresh timestamp, detector-removed
    # entries (already > t_fail stale) expire the same tick; only LEAVE/REMOVE
    # entries with fresh timestamps get the full suppression window.
    expire = (status == FAILED) & (age > config.t_cooldown)
    status = jnp.where(expire, UNKNOWN, status)

    return (
        SimState(hb=hb, age=age, status=status, alive=alive, round=state.round),
        fail,
        active,
    )


def _merge(
    state: SimState, edges: jax.Array, senders: jax.Array, config: SimConfig
) -> SimState:
    """Gossip exchange: gather sender rows over in-edges, elementwise-max merge.

    Implements MergeMemberList (slave.go:414-440): shared members take the max
    heartbeat and a *local* timestamp; unknown members are added unless on the
    receiver's fail list (FAILED entries ignore gossip entirely).

    Both kernels compute ``best_hb[i,:] = max_f gossip_view[edges[i,f],:]``
    over the gossip view (hb where the entry is in a sent message, -1
    otherwise); heartbeats are always >= 0, so ``best_hb >= 0`` is exactly
    "some peer's message contained this entry".  config.merge_kernel picks
    the XLA gather loop or the pallas DMA kernel (ops/merge_pallas.py — the
    TPU fast path); shapes the kernel's tiling can't express fall back to
    XLA.  One definition of the op serves both paths, so the kernel-parity
    tests pin exactly what production runs.
    """
    hb, age, status, alive = state.hb, state.age, state.status, state.alive

    from gossipfs_tpu.ops import merge_pallas

    # The gossip view: what a sender's datagram contains for each subject
    # (absent entries as -1 — heartbeats are never negative).  Heartbeat
    # counts are rebased per subject so the view fits a narrow dtype
    # (config.view_dtype: int16, or int8 for random topologies), shrinking
    # the HBM traffic of the F-way gather — the round's dominant cost — by
    # 2-4x over int32.  The base is
    # derived from *gossip-eligible* copies only: hb lanes of FAILED/UNKNOWN
    # entries and dead nodes' frozen rows keep crash-time counters forever,
    # and anchoring on those would mask a rejoining node's fresh hb=0
    # entries out of gossip once the run is > rebase_window rounds old.
    # Gossip-eligible entries (MEMBER, so age <= t_fail at the holder) lag
    # the freshest eligible copy by O(t_fail) per hop, so same-incarnation
    # copies never fall rebase_window behind.  The one reachable clamp: a
    # rejoin while a zombie MEMBER copy of the old incarnation (counter
    # > rebase_window ahead) survives somewhere — the fresh entries drop out
    # of gossip, but the reference's incarnation-free max-merge dominates
    # those counts anyway (slave.go:419-424); dissemination rides the
    # introducer's join broadcast in both worlds.
    elig = (status == MEMBER) & senders[:, None]
    colmax = jnp.max(jnp.where(elig, hb, 0), axis=0)        # int32 [N]
    base = jnp.maximum(colmax - config.rebase_window, 0)
    rel = hb - base[None, :]
    gossiped = elig & (rel >= 0)
    vdtype = jnp.int8 if config.view_dtype == "int8" else jnp.int16
    view = jnp.where(gossiped, rel, -1).astype(vdtype)
    interpret = config.merge_kernel == "pallas_interpret"
    use_pallas = (
        config.merge_kernel != "xla"
        and merge_pallas.supported(state.n, edges.shape[1])
        # the compiled kernel is Mosaic/TPU-only; "pallas" on a CPU/GPU
        # backend (preset smoke-runs) falls back rather than failing to
        # lower ("pallas_interpret" runs anywhere, for tests)
        and (interpret or jax.default_backend() == "tpu")
    )
    if use_pallas:
        best_rel = merge_pallas.fanout_max_merge(
            view,
            edges,
            block_r=config.merge_block_r,
            block_c=config.merge_block_c,
            slots=config.merge_slots,
            interpret=interpret,
        )
    else:
        # XLA gather path: also the fallback for unsupported shapes/backends
        best_rel = merge_pallas.fanout_max_merge_xla(view, edges)
    any_member = best_rel >= 0
    # un-rebase; keep absent entries at -1 (base can exceed any real hb)
    best_hb = jnp.where(
        any_member, best_rel.astype(jnp.int32) + base[None, :], -1
    )

    recv = alive[:, None]
    advance = recv & (status == MEMBER) & (best_hb > hb)       # max-merge + stamp
    add = recv & (status == UNKNOWN) & any_member              # learn new member
    hb = jnp.where(advance | add, best_hb, hb)
    age = jnp.where(advance | add, 0, age)
    status = jnp.where(add, MEMBER, status)
    return SimState(hb=hb, age=age, status=status, alive=alive, round=state.round)


@partial(jax.jit, static_argnames=("config",))
def gossip_round(
    state: SimState,
    events: RoundEvents,
    edges: jax.Array | None,
    config: SimConfig,
) -> tuple[SimState, RoundMetrics, jax.Array]:
    """Advance the whole cluster by one heartbeat period.

    ``edges`` is the random-topology in-edge array; pass None for ring mode,
    where edges are derived from the post-tick membership tables (the
    reference computes push targets after updateMemberList, slave.go:510-524).
    Returns (next_state, per-round metrics, fail_events [N,N]).
    """
    state = _apply_events(state, events, config)
    state, fail, active = _tick(state, config)
    if config.topology == "ring":
        edges = topology.ring_edges_from_status(state.status)
    assert edges is not None
    state = _merge(state, edges, active, config)

    # age advances for every entry not refreshed this round (refreshes wrote
    # 0); saturates at AGE_CLAMP, beyond every protocol threshold (config.py)
    state = state._replace(
        age=jnp.minimum(state.age + 1, AGE_CLAMP).astype(jnp.int8),
        round=state.round + 1,
    )

    dead = ~state.alive
    metrics = RoundMetrics(
        true_detections=jnp.sum(fail & dead[None, :], dtype=jnp.int32),
        false_positives=jnp.sum(fail & state.alive[None, :], dtype=jnp.int32),
        n_alive=jnp.sum(state.alive, dtype=jnp.int32),
    )
    return state, metrics, fail


def _update_carry(
    carry: MetricsCarry,
    state: SimState,
    rejoined: jax.Array,
    fail: jax.Array,
    round_idx: jax.Array,
) -> MetricsCarry:
    n = state.n
    first_detect, converged = carry
    # rejoined = joins that actually took effect: new incarnation, new clock
    first_detect = jnp.where(rejoined, -1, first_detect)
    converged = jnp.where(rejoined, -1, converged)

    any_fail = jnp.any(fail, axis=0)
    first_detect = jnp.where((first_detect < 0) & any_fail, round_idx, first_detect)

    eye = jnp.eye(n, dtype=bool)
    dropped = ~state.alive[:, None] | eye | (state.status != MEMBER)
    all_dropped = jnp.all(dropped, axis=0) & ~state.alive
    converged = jnp.where((converged < 0) & all_dropped, round_idx, converged)
    return MetricsCarry(first_detect=first_detect, converged=converged)


@partial(
    jax.jit,
    static_argnames=("config", "num_rounds", "crash_rate", "rejoin_rate"),
)
def run_rounds(
    state: SimState,
    config: SimConfig,
    num_rounds: int,
    key: jax.Array,
    events: RoundEvents | None = None,
    crash_rate: float = 0.0,
    rejoin_rate: float = 0.0,
    churn_ok: jax.Array | None = None,
) -> tuple[SimState, MetricsCarry, RoundMetrics]:
    """Scan ``num_rounds`` gossip rounds.

    ``events``: optional pre-scheduled RoundEvents stacked to [num_rounds, N]
    (deterministic fault injection — the sim's CTRL+C).  ``crash_rate`` /
    ``rejoin_rate`` add per-round random churn on top (BASELINE configs 3/4).
    ``churn_ok``: optional bool [N] mask of nodes eligible for *random* churn
    — benchmark runs exclude their tracked crash victims so a random rejoin
    can't reset the tracked detection/convergence rounds mid-measurement.
    Returns final state, per-subject detection/convergence rounds, and
    per-round metrics stacked over the horizon.
    """
    n = config.n
    if events is None:
        zeros = jnp.zeros((num_rounds, n), dtype=bool)
        events = RoundEvents(crash=zeros, leave=zeros, join=zeros)

    def step(carry, ev: RoundEvents):
        st, mc = carry
        k = jax.random.fold_in(key, st.round)
        k_edge, k_churn = jax.random.split(k)
        if crash_rate > 0.0 or rejoin_rate > 0.0:
            crash, join = topology.churn_masks(k_churn, st.alive, crash_rate, rejoin_rate)
            if churn_ok is not None:
                crash, join = crash & churn_ok, join & churn_ok
            ev = RoundEvents(crash=ev.crash | crash, leave=ev.leave, join=ev.join | join)
        edges = (
            None
            if config.topology == "ring"
            else topology.random_in_edges(k_edge, config.n, config.fanout)
        )
        round_idx = st.round
        alive_before = st.alive
        st, metrics, fail = gossip_round(st, ev, edges, config)
        # joins lost to a dead introducer don't reset metrics (slave.go:22 SPOF)
        rejoined = ev.join & ~alive_before & st.alive
        mc = _update_carry(mc, st, rejoined, fail, round_idx)
        return (st, mc), metrics

    (state, mcarry), per_round = lax.scan(step, (state, MetricsCarry.init(n)), events)
    return state, mcarry, per_round
