"""Gossip topologies: who merges whose membership table each round.

We express the exchange as **in-edges**: ``A[i, f]`` is the f-th peer whose
table node *i* merges this round.  This receiver-centric form makes the round
kernel a plain row gather (no scatter), which is both XLA-friendly and exactly
local under subject-axis sharding.

Parity mode — the reference *pushes* its full list to the three fixed ring
neighbours ``self-1, self+1, self+2 (mod N)`` (reference: slave/slave.go:515-524).
Inverting the push direction, node *i* *receives* from offsets ``+1, -1, -2``;
``ring_in_edges`` encodes those, so the simulated information flow matches the
Go wire traffic edge-for-edge.

North-star mode — BASELINE.json generalises to random fanout ``k = ceil(log2 N)``:
each node merges k uniformly random distinct-from-self peers per round
(fresh graph every round, seeded — the deterministic stand-in for "pick k
random gossip targets").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gossipfs_tpu.config import SimConfig


def ring_edges_from_status(status: jax.Array,
                           include_suspects: bool = False) -> jax.Array:
    """int32 [N, 3] — per-receiver ring in-edges over each node's *own* list.

    The reference recomputes its three push targets every heartbeat from its
    current member-list positions (self-1, self+1, self+2 — reference:
    slave/slave.go:515-524), so the ring heals as members are removed.  We keep
    that dynamism but (a) order the ring by node id instead of join order and
    (b) invert push to receive: with converged lists, node *i* receives from
    exactly {next member above, first below, second below} in cyclic id order.
    During transient list disagreement the inversion is approximate (a sender
    whose list differs from the receiver's may pick different targets).

    ``include_suspects`` (suspicion runs, suspicion/): SUSPECT entries are
    still list positions, so they stay ring push targets — the UDP engine
    agrees by construction (its members dict holds suspects until the
    confirm removes them).  Excluding them would make ring suspicion
    self-reinforcing: a suspected neighbor would never be gossiped to
    again, so no refutation could ever reach the suspecting side.

    Nodes with too few other members fall back to self-edges, which merge as
    no-ops (senders below min_group don't gossip anyway, slave.go:504-509).
    """
    from gossipfs_tpu.core.state import MEMBER, SUSPECT

    n = status.shape[0]
    i = jnp.arange(n, dtype=jnp.int32)[:, None]
    j = jnp.arange(n, dtype=jnp.int32)[None, :]
    listed = status == MEMBER
    if include_suspects:
        listed = listed | (status == SUSPECT)
    m = listed & (j != i)
    big = jnp.int32(n + 1)
    dn = jnp.where(m, (j - i) % n, big)
    next1 = jnp.argmin(dn, axis=1).astype(jnp.int32)
    dp = jnp.where(m, (i - j) % n, big)
    prev1 = jnp.argmin(dp, axis=1).astype(jnp.int32)
    dp2 = dp.at[jnp.arange(n), prev1].set(big)
    prev2 = jnp.argmin(dp2, axis=1).astype(jnp.int32)
    cnt = jnp.sum(m, axis=1)
    self_idx = jnp.arange(n, dtype=jnp.int32)
    next1 = jnp.where(cnt >= 1, next1, self_idx)
    prev1 = jnp.where(cnt >= 1, prev1, self_idx)
    prev2 = jnp.where(cnt >= 2, prev2, self_idx)
    return jnp.stack([next1, prev1, prev2], axis=1)


def random_in_edges(key: jax.Array, n: int, fanout: int) -> jax.Array:
    """int32 [N, F] — per-round uniform random peers, never self.

    Samples uniformly from the n-1 non-self indices by drawing in ``[0, n-1)``
    and shifting values >= self up by one (no rejection loop — static shapes,
    scan-safe).  Peers may repeat within a row (sampling with replacement),
    matching random-gossip practice; duplicates only waste a merge.
    """
    if n - 1 <= jnp.iinfo(jnp.uint16).max:
        # 16-bit draws halve the per-round threefry work (the [N, F] edge
        # tensor is the round's only non-trivial host-free RNG cost);
        # backend-independent, same uniformity
        draw = jax.random.randint(
            key, (n, fanout), 0, n - 1, dtype=jnp.uint16
        ).astype(jnp.int32)
    else:
        draw = jax.random.randint(key, (n, fanout), 0, n - 1, dtype=jnp.int32)
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    return draw + (draw >= self_idx).astype(jnp.int32)


def random_arc_bases(key: jax.Array, n: int, fanout: int) -> jax.Array:
    """int32 [N] — start of each receiver's arc of F *consecutive* senders.

    The ``random_arc`` topology replaces F independent uniform draws with one
    uniform draw of an arc start: receiver i merges rows
    ``{(b_i + k) % N, k < F}``.  Arc positions are uniform over the n-F
    starts whose window excludes i (mirroring ``random_in_edges``'s
    never-self), so the probability an arc hits any fixed set S is
    ``~1-(1-|S|/N)^F`` — the same first-order epidemic coverage as F iid
    picks, re-randomized every round (bench/curves.py verifies TTD/FPR
    match).  What the structure buys: the F-way random row gather — the
    round's dominant cost — becomes one windowed row-max (computable in
    O(log F) passes, independent of F) plus a single 1-way gather
    (ops/merge_pallas.py ``arc_merge_update_blocked``).
    """
    draw = jax.random.randint(key, (n,), 0, n - fanout, dtype=jnp.int32)
    return (jnp.arange(n, dtype=jnp.int32) + 1 + draw) % n


def random_arc_bases_aligned(
    key: jax.Array, n: int, fanout: int, align: int
) -> jax.Array:
    """int32 [N] arc bases drawn as multiples of ``align``.

    The tile-aligned variant of :func:`random_arc_bases`: every base is a
    multiple of ``align`` (and ``fanout`` a multiple of ``align``), so the
    rr kernel's windowed row-max collapses to an ``align``-way group
    reduction that rides the view build plus one pair-max over N/align
    group rows — the O(log F) shift-doubling passes disappear.

    Unlike the plain draw, an aligned arc MAY include the receiver
    itself.  Self-inclusion is a merge no-op: the gossip view is built
    from the same post-tick state the receiver sweep reads, so a
    receiver's own row contributes values equal to what it already
    holds and the strict ``advance`` compare rejects them
    (core/rounds.py _membership_update).  Coverage is therefore the
    plain arc's minus an O(F/N) self-overlap correction
    (bench/curves.py measures detection parity).
    """
    if fanout % align or n % align:
        raise ValueError(
            f"aligned arc needs align | fanout and align | n "
            f"(align={align}, fanout={fanout}, n={n})"
        )
    nb = n // align
    draw = jax.random.randint(key, (n,), 0, nb, dtype=jnp.int32)
    return draw * align


def arc_edges(bases: jax.Array, fanout: int) -> jax.Array:
    """Expand arc bases to explicit [N, F] in-edges (oracle / XLA path)."""
    n = bases.shape[0]
    offs = jnp.arange(fanout, dtype=jnp.int32)[None, :]
    return (bases[:, None] + offs) % n


def in_edges(config: SimConfig, key: jax.Array, status: jax.Array) -> jax.Array:
    """Per-round in-edges in the form the round kernel consumes.

    ring needs ``status``; ``random_arc`` yields arc BASES [N] (what
    ``gossip_round``/``_merge`` take for that topology — expand with
    :func:`arc_edges` for consumers needing explicit [N, F] edges);
    ``random`` yields explicit [N, F] edges.
    """
    if config.topology == "ring":
        return ring_edges_from_status(
            status, include_suspects=config.suspicion is not None
        )
    if config.topology == "random_arc":
        if config.arc_align > 1:
            return random_arc_bases_aligned(
                key, config.n, config.fanout, config.arc_align
            )
        return random_arc_bases(key, config.n, config.fanout)
    return random_in_edges(key, config.n, config.fanout)


def churn_masks(
    key: jax.Array,
    alive: jax.Array,
    crash_rate: float,
    rejoin_rate: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Random crash-stop + rejoin masks for one round.

    ``crash_rate`` is the per-round probability an alive node crashes
    (BASELINE configs 3/4: 1% crash-stop, 5% churn); ``rejoin_rate`` the
    per-round probability a dead node rejoins (churn/preemption recovery).
    """
    k1, k2 = jax.random.split(key)
    u = jax.random.uniform(k1, alive.shape)
    crash = alive & (u < crash_rate)
    v = jax.random.uniform(k2, alive.shape)
    join = (~alive) & (v < rejoin_rate)
    return crash, join
