"""Greedy delta-debugging for failing schedules.

A fuzzer-found divergence is only actionable once it is SMALL: the
minimal repro names the one message ordering the engines disagree on.
:func:`shrink` takes a failing case doc and a ``still_fails`` predicate
(the caller decides what "fails" means — usually "this engine's verdict
row is not ok", see ``tools/conformance.py``) and greedily minimizes:

  0. search NEIGHBOURING CORPUS SEEDS first: when the doc carries its
     ``(family, seed)`` provenance, regenerate the draws at seeds within
     ``seed_radius`` and restart from any failing draw that is smaller
     (fewer steps, then fewer rounds, then fewer total datagram copies,
     then the lowest seed as a canonical tiebreak).  Generator draws
     differ only in which nodes the rng picks, so a neighbouring seed
     can hand ddmin a strictly easier starting point for free — one
     engine run per candidate, before the O(steps^2) pass begins;
  1. drop schedule steps one at a time, to fixpoint (classic ddmin with
     chunk size 1 — schedules are tens of steps, not thousands, so the
     O(steps^2) pass costs less than one socket-engine run);
  2. drop mid-run checkpoints the failure does not need;
  3. reduce per-step datagram ``copies`` to 1;
  4. truncate trailing rounds the failure does not need (binary search
     down, keeping a small settling pad after the last step).

Every candidate is re-validated against the schedule schema before the
predicate sees it, so the minimized doc is replayable by the same
harness — minimal repros are committed under ``regressions/`` and
replayed by tier-1 exactly like the campaign storm cases.
"""

from __future__ import annotations

import copy

from gossipfs_tpu.conformance.harness import run_case_reference
from gossipfs_tpu.conformance.schedules import (FAMILIES, generate,
                                                serialize, validate)


def _try(candidate: dict, still_fails) -> bool:
    try:
        validate(candidate)
    except ValueError:
        return False
    return bool(still_fails(candidate))


def _size(case: dict):
    """Smaller-is-better ordering for whole draws: steps dominate (each
    is a datagram the repro must explain), rounds break ties, then total
    injected copies; the seed itself is last so equal-size failing draws
    canonicalize to the lowest seed in the neighbourhood."""
    return (len(case["steps"]), case["rounds"],
            sum(int(s.get("copies", 1)) for s in case["steps"]),
            case.get("seed", 0))


def _seed_pass(case: dict, still_fails, radius: int) -> dict:
    fam, seed = case.get("family"), case.get("seed")
    if radius <= 0 or fam not in FAMILIES or not isinstance(seed, int):
        return case
    best = case
    for s in range(max(0, seed - radius), seed + radius + 1):
        if s == seed:
            continue
        cand = generate(fam, seed=s)
        if _size(cand) < _size(best) and _try(cand, still_fails):
            best = cand
    return best


def shrink(case: dict, still_fails, *, settle_pad: int = 6,
           seed_radius: int = 2) -> dict:
    """Minimize ``case`` while ``still_fails(candidate)`` stays true.

    The predicate is called on structurally-valid candidates only and
    should be deterministic-ish (socket-engine flakes make the shrink
    conservative, never wrong: a candidate that fails to reproduce is
    simply kept out).  Returns a new doc; the input is not mutated.
    """
    case = copy.deepcopy(case)
    if not _try(case, still_fails):
        raise ValueError("shrink needs a failing case to start from")

    # 0) seed-neighbourhood search — restart ddmin from the smallest
    # failing draw within seed_radius of this one's corpus seed
    case = _seed_pass(case, still_fails, seed_radius)

    # 1) drop steps to fixpoint
    changed = True
    while changed:
        changed = False
        for i in reversed(range(len(case["steps"]))):
            trial = copy.deepcopy(case)
            del trial["steps"][i]
            if _try(trial, still_fails):
                case = trial
                changed = True

    # 2) drop checkpoints the failure does not need
    for i in reversed(range(len(case["checkpoints"]))):
        trial = copy.deepcopy(case)
        del trial["checkpoints"][i]
        if _try(trial, still_fails):
            case = trial

    # 3) single copies
    for i, step in enumerate(case["steps"]):
        if int(step.get("copies", 1)) > 1:
            trial = copy.deepcopy(case)
            trial["steps"][i]["copies"] = 1
            if _try(trial, still_fails):
                case = trial

    # 4) truncate trailing rounds (keep a settling pad after the last
    # step / checkpoint so confirm windows still run out)
    floor = 1
    if case["steps"]:
        floor = max(floor, max(s["round"] for s in case["steps"]) + 1)
    if case["checkpoints"]:
        floor = max(floor,
                    max(c["round"] for c in case["checkpoints"]) + 1)
    lo, hi = floor + settle_pad, case["rounds"]
    while lo < hi:
        mid = (lo + hi) // 2
        trial = copy.deepcopy(case)
        trial["rounds"] = mid
        if _try(trial, still_fails):
            hi = mid
        else:
            lo = mid + 1
    if hi < case["rounds"]:
        trial = copy.deepcopy(case)
        trial["rounds"] = hi
        if _try(trial, still_fails):
            case = trial

    # 5) resync the declared expectation to the MINIMIZED doc's oracle:
    # step/round minimization legitimately changes the predicted endgame
    # (truncating rounds before a re-confirm window closes turns a
    # declared 'gone' into 'suspect'), and a committed repro whose own
    # oracle selfcheck fails would blame the generator instead of the
    # engine it indicts.
    ref = run_case_reference(case)
    for s in case["tracked"]:
        exp = case["expect"][str(s)]
        exp["final"] = ref["final"][s]
        emitted = {e["kind"] for e in ref["events"] if e["subject"] == s}
        exp["forbid"] = sorted(set(exp["forbid"]) - emitted)

    validate(case)
    return case


def save(case: dict, path) -> None:
    """Write a minimized repro in the canonical byte form (the same
    serializer the seed-determinism tests pin)."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(serialize(case))
