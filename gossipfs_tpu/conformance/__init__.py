"""Conformance fuzzing: the protocol contract gone dynamic.

``analysis/protocol_spec.py`` (round 17) pins the SWIM+Lifeguard
lifecycle *statically* — the drift lint diffs what each engine's code
SAYS against the contract.  This package is its runtime twin: it walks
the same transition table and *executes* it, generating adversarial
message schedules (delayed/dropped REFUTEs, replayed incarnations,
SUSPECT verb floods, forged REMOVEs, malformed datagrams) and driving
every engine through them, then comparing each engine's observable
surface against a step-for-step reference prediction.

  * :mod:`schedules`  — seed-pure adversarial-schedule generator driven
    by ``protocol_spec`` (``gossipfs-conformance/v1`` case docs);
  * :mod:`harness`    — one injection driver per engine, plus the
    per-round reference oracle built on ``suspicion/runtime.py``;
  * :mod:`verdict`    — the per-(schedule, engine) conformance matrix;
  * :mod:`shrink`     — greedy delta-debugging for failing schedules
    (minimal repros land in ``regressions/``).

``tools/conformance.py`` is the CLI; ``CONFORMANCE_r19.json`` is the
committed matrix artifact.
"""

from gossipfs_tpu.conformance.schedules import (  # noqa: F401
    FAMILIES,
    coverage,
    generate,
    generate_corpus,
)
from gossipfs_tpu.conformance.verdict import compare, run_matrix  # noqa: F401
