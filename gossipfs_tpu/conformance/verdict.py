"""The per-(schedule, engine) conformance matrix.

``compare()`` holds one engine bundle against the reference prediction
for the same case; ``run_matrix()`` sweeps a corpus x engine grid and
aggregates ``all_agree`` — the shape ``CONFORMANCE_r19.json`` commits
and ``tools/verify_claims.py spec_conformance`` re-verifies.

What is compared (and, as deliberately, what is not):

  * **final_status** / **checkpoints** — the membership classification
    of every tracked subject at observer 0 (member / suspect / gone),
    at the end and at the schedule's mid-run checkpoints.  Schedules
    keep >= 2 rounds of margin around every checkpoint, so wall-clock
    jitter on the socket engines cannot flip a verdict;
  * **expected_kinds** — the reference's lifecycle kind-set per tracked
    subject (minus the family's declared ``optional`` kinds — benign
    arrival-order races) must appear in the engine's flight-recorder
    stream.  Kind SETS, not sequences: engines attribute observers
    differently (the tensor recorder is cluster-wide) and dissemination
    order is topology-drawn;
  * **forbidden_kinds** — the family's declared must-not-fire kinds
    (e.g. ``confirm`` in a refute race) are absent for the subject;
  * **causal_order** — cluster-wide, first ``suspect`` at-or-before
    first ``confirm`` per subject whenever suspicion is armed.  NOT
    per-observer: an observer whose suspicion arrived by adoption
    (a SUSPECT datagram racing its own staleness tick) legitimately
    confirms without a local ``suspect`` event;
  * **incarnation_grace** — on engines exposing per-entry incarnations
    (reference, udp, native — NOT the tensor sim, whose detector API
    carries no hb surface: skipped, not fabricated), a tracked subject
    that finished as a member must carry a counter past the hb<=1
    detection grace.

Round *numbers* of events are never compared — socket engines are
wall-clock jittered by design; the checkpoints are the timing assert.
"""

from __future__ import annotations

from gossipfs_tpu.conformance import harness, schedules


def _kinds(bundle: dict, subject: int) -> set[str]:
    return {e["kind"] for e in bundle["events"] if e["subject"] == subject}


def _first_round(bundle: dict, subject: int, kind: str):
    rounds = [e["round"] for e in bundle["events"]
              if e["subject"] == subject and e["kind"] == kind]
    return min(rounds) if rounds else None


def compare(case: dict, ref: dict, eng: dict) -> dict:
    """One matrix row: engine bundle vs the reference prediction."""
    checks: dict[str, dict] = {}

    mismatched = {
        str(s): {"engine": eng["final"].get(s), "reference": ref["final"][s]}
        for s in case["tracked"] if eng["final"].get(s) != ref["final"][s]
    }
    checks["final_status"] = {"ok": not mismatched, "mismatched": mismatched}

    cp_mismatch = {}
    for r, ref_status in ref["checkpoints"].items():
        eng_status = eng["checkpoints"].get(r, {})
        for s in case["tracked"]:
            if eng_status.get(s) != ref_status[s]:
                cp_mismatch[f"round {r} subject {s}"] = {
                    "engine": eng_status.get(s), "reference": ref_status[s]}
    checks["checkpoints"] = {"ok": not cp_mismatch,
                             "mismatched": cp_mismatch}

    missing_kinds = {}
    forbidden_hit = {}
    for s in case["tracked"]:
        exp = case["expect"][str(s)]
        required = _kinds(ref, s) - set(exp["optional"])
        got = _kinds(eng, s)
        if not required <= got:
            missing_kinds[str(s)] = sorted(required - got)
        hit = got & set(exp["forbid"])
        if hit:
            forbidden_hit[str(s)] = sorted(hit)
    checks["expected_kinds"] = {"ok": not missing_kinds,
                                "missing": missing_kinds}
    checks["forbidden_kinds"] = {"ok": not forbidden_hit,
                                 "fired": forbidden_hit}

    causal_bad = {}
    if case["config"].get("suspicion", True):
        for s in case["tracked"]:
            confirm_at = _first_round(eng, s, "confirm")
            if confirm_at is None:
                continue
            suspect_at = _first_round(eng, s, "suspect")
            if suspect_at is None or suspect_at > confirm_at:
                causal_bad[str(s)] = {"first_suspect": suspect_at,
                                      "first_confirm": confirm_at}
    checks["causal_order"] = {"ok": not causal_bad, "violations": causal_bad}

    if eng["incarnations"]:
        stale_members = {
            str(s): eng["incarnations"].get(s)
            for s in case["tracked"]
            if eng["final"].get(s) == "member"
            and not eng["incarnations"].get(s, 0) > 1
        }
        checks["incarnation_grace"] = {"ok": not stale_members,
                                       "stale": stale_members}
    else:
        checks["incarnation_grace"] = {"ok": True, "skipped": True}

    return {
        "family": case["family"],
        "seed": case["seed"],
        "engine": eng["engine"],
        "ok": all(c["ok"] for c in checks.values()),
        "checks": checks,
    }


def oracle_selfcheck(case: dict, ref: dict) -> dict:
    """The reference prediction must itself satisfy the family's
    DECLARED expectations (final status, checkpoint statuses, forbidden
    kinds).  A schedule whose oracle run disagrees with its own family
    sheet is a generator timing bug, not an engine divergence — this
    row catches it before any engine is blamed."""
    problems = []
    for s in case["tracked"]:
        exp = case["expect"][str(s)]
        if ref["final"][s] != exp["final"]:
            problems.append(
                f"subject {s}: predicted final {ref['final'][s]!r} != "
                f"declared {exp['final']!r}")
        hit = _kinds(ref, s) & set(exp["forbid"])
        if hit:
            problems.append(
                f"subject {s}: prediction emits forbidden {sorted(hit)}")
    for cp in case["checkpoints"]:
        got = ref["checkpoints"].get(cp["round"], {})
        for s_str, status in cp["status"].items():
            if got.get(int(s_str)) != status:
                problems.append(
                    f"checkpoint round {cp['round']} subject {s_str}: "
                    f"predicted {got.get(int(s_str))!r} != declared "
                    f"{status!r}")
    return {
        "family": case["family"],
        "seed": case["seed"],
        "engine": "reference",
        "ok": not problems,
        "checks": {"oracle_selfcheck": {"ok": not problems,
                                        "problems": problems}},
    }


def run_case(case: dict, engines=None) -> list[dict]:
    """All matrix rows for one case: the oracle selfcheck plus one
    compare row per requested engine the family can run."""
    ref = harness.run_case_reference(case)
    rows = [oracle_selfcheck(case, ref)]
    for engine in case["engines"]:
        if engine == "reference":
            continue
        if engines is not None and engine not in engines:
            continue
        bundle = harness.RUNNERS[engine](case)
        rows.append(compare(case, ref, bundle))
    return rows


def run_matrix(corpus, engines=None) -> dict:
    """The full corpus x engine conformance matrix (the committed
    artifact's core).  ``engines=None`` runs every engine each family
    declares; passing a subset (e.g. the CPU claim slice) restricts the
    socket/tensor columns while the oracle selfcheck always runs."""
    rows = []
    for case in corpus:
        rows.extend(run_case(case, engines=engines))
    failing = [r for r in rows if not r["ok"]]
    return {
        "schema": "gossipfs-conformance-matrix/v1",
        "cases": len(corpus),
        "rows": rows,
        "engines_run": sorted({r["engine"] for r in rows}),
        "coverage": schedules.coverage(),
        "all_agree": not failing,
        "disagreements": [
            {"family": r["family"], "seed": r["seed"], "engine": r["engine"],
             "failed_checks": sorted(k for k, c in r["checks"].items()
                                     if not c["ok"])}
            for r in failing
        ],
    }
