"""Adversarial schedule generator, driven by ``analysis/protocol_spec.py``.

Each *family* probes one guard edge (or rate limit, or codec invariant)
of the protocol contract under a hostile message ordering the static
drift lint cannot see: a REFUTE delayed past the confirm window, a
replayed stale incarnation reviving a dead entry's freshness, SUSPECT
floods past the refute-once-per-period limit, a forged REMOVE of a live
member, malformed datagrams through the wire codec.  A schedule is a
seed-pure ``gossipfs-conformance/v1`` JSON case doc: the same
``(family, seed)`` always serializes byte-identically, so corpus slices
are pinnable (``tools/verify_claims.py spec_conformance``) and failing
cases replay exactly (``shrink.py`` -> ``regressions/``).

Every probe a family declares is validated against the contract's own
transition table — ``generate()`` refuses a probe string that names an
edge ``protocol_spec.TRANSITIONS`` does not carry, which is what makes
the generator *spec-driven* rather than a hand-rolled scenario list.
``coverage()`` proves the family set exercises every wire verb, every
injection seam, and every lifecycle transition (the
``conformance-verb-coverage`` lint rule checks the same closure
statically from the :data:`FAMILIES` literal).

Schedule vocabulary (``steps``; rounds are schedule-relative, armed
after each engine's warmup):

  * ``crash`` / ``leave`` / ``join`` — engine injection seams
    (``protocol_spec.INJECTIONS``; every engine carries them);
  * ``blackouts`` (top-level) — scenario-plane correlated outages
    (``scenarios.CorrelatedOutage``, armed at schedule round 0);
  * ``verb`` — one crafted control datagram per target through the
    engine's real wire codec (udp/native sockets; the reference applies
    it to its handler table).  The tensor engine has no datagram seam,
    so verb/malformed steps are wire-plane-only and families that need
    them exclude ``tensor`` from ``engines``;
  * ``malformed`` — codec-hardening payloads (garbage, unparsable
    heartbeats, unknown verbs, and ``mixed_refresh``: a valid
    incarnation-advance entry with a trailing malformed chunk — a
    hardened codec salvages the valid entry, a brittle one drops the
    whole datagram).  Round 20 adds the delta wire format's styles:
    ``truncated_delta`` (a marked frame cut mid-entry), ``delta_refresh``
    (a clean single-entry delta advance — the race/zombie carrier) and
    ``stale_full_replay`` (a full-list fragment with a stale counter) —
    the dispatch contract says marked frames run the SAME hardened
    max-merge as full lists, whatever the receiver's dissemination mode.

The cluster profile is the campaign/north-star protocol mode shared by
``campaigns/engines.py`` (random fanout push, gossip-only removal,
fresh cooldown) — the one profile all four surfaces can run, since the
tensor engine's suspicion gate requires exactly that mode.
"""

from __future__ import annotations

import json
import random

from gossipfs_tpu.analysis import protocol_spec

SCHEMA = "gossipfs-conformance/v1"

# One profile for the whole corpus (see module docstring).  t_suspect is
# wide (10 rounds) so socket-engine wall-clock jitter of a round or two
# never crosses a checkpoint boundary; every checkpoint below keeps >= 2
# rounds of margin to the nearest predicted transition.
N = 8
CONFIG = {
    "t_fail": 5,
    "t_suspect": 10,
    "t_cooldown": 6,
    "min_group": 4,
    "push": "random",
    "fanout": 3,
    "remove_broadcast": False,
    "fresh_cooldown": True,
    "lh_multiplier": 0,
    "lh_frac": 0.25,
}

# Family metadata as a PURE literal dict: the conformance-verb-coverage
# lint rule parses it straight off this module's AST (framework
# literal_dict), so the verb/injection closure is checkable without
# importing (and the import-time coverage() check keeps it honest
# against the generators).  engines lists which surfaces can run the
# family at all — wire-verb and codec families have no tensor seam.
FAMILIES = {
    "refute_race": {
        "doc": "rack blackout heals mid-suspect-window: the REFUTE wave "
               "must win the confirm race (delayed-refute edge)",
        "verbs": ["SUSPECT", "REFUTE"],
        "injections": [],
        "probes": ["MEMBER->SUSPECT:stale", "SUSPECT->MEMBER:refute_evidence"],
        "engines": ["reference", "tensor", "udp", "native"],
    },
    "confirm_expiry": {
        "doc": "crash with no refuting evidence: SUSPECT must hold the "
               "full window, then confirm and remove",
        "verbs": ["SUSPECT"],
        "injections": ["crash", "hb_freeze"],
        "probes": ["MEMBER->SUSPECT:stale", "SUSPECT->FAILED:confirm_window"],
        "engines": ["reference", "tensor", "udp", "native"],
    },
    "direct_confirm": {
        "doc": "suspicion disarmed: stale confirms directly, no SUSPECT "
               "detour (the disarmed MEMBER->FAILED row)",
        "verbs": [],
        "injections": ["crash", "hb_freeze"],
        "probes": ["MEMBER->FAILED:stale"],
        "engines": ["reference", "tensor", "udp", "native"],
    },
    "leave_broadcast": {
        "doc": "graceful leave: the LEAVE broadcast removes the member "
               "everywhere with no detection lifecycle",
        "verbs": ["LEAVE"],
        "injections": ["leave"],
        "probes": ["MEMBER->FAILED:leave_or_remove"],
        "engines": ["reference", "tensor", "udp", "native"],
    },
    "rejoin_cooldown": {
        "doc": "confirm -> cooldown expiry -> introducer rejoin; plus a "
               "duplicate JOIN about an already-listed member (must be "
               "a silent no-op)",
        "verbs": ["JOIN"],
        "injections": ["crash", "hb_freeze", "join"],
        "probes": [
            "SUSPECT->FAILED:confirm_window",
            "FAILED->UNKNOWN:cooldown_expiry",
            "UNKNOWN->MEMBER:join_or_merge_add",
        ],
        # the duplicate-JOIN probe is a wire datagram, so the tensor sim
        # (no datagram seam) sits this family out
        "engines": ["reference", "udp", "native"],
    },
    "suspect_flood": {
        "doc": "SUSPECT verb flood about a LIVE member, past the "
               "refute-once-per-period rate limit: the subject bumps + "
               "refutes, observers must not confirm",
        "verbs": ["SUSPECT", "REFUTE"],
        "injections": [],
        "probes": ["SUSPECT->MEMBER:refute_evidence"],
        "engines": ["reference", "udp", "native"],
    },
    "stale_refute_replay": {
        "doc": "replayed REFUTE with a stale incarnation mid-window: it "
               "cancels the suspicion and re-freshens the entry (the "
               "explicit-REFUTE rule), delaying — not preventing — the "
               "confirm",
        "verbs": ["REFUTE"],
        "injections": ["crash", "hb_freeze"],
        "probes": [
            "MEMBER->SUSPECT:stale",
            "SUSPECT->MEMBER:refute_evidence",
            "SUSPECT->FAILED:confirm_window",
        ],
        "engines": ["reference", "udp", "native"],
    },
    "remove_poison": {
        "doc": "forged REMOVE of a live member: removal + cooldown "
               "suppression, then the victim's own gossip re-adds it "
               "after expiry — no detection lifecycle may fire",
        "verbs": ["REMOVE"],
        "injections": [],
        "probes": [
            "MEMBER->FAILED:leave_or_remove",
            "FAILED->UNKNOWN:cooldown_expiry",
            "UNKNOWN->MEMBER:join_or_merge_add",
        ],
        "engines": ["reference", "udp", "native"],
    },
    "malformed_codec": {
        "doc": "codec hardening: pure-garbage datagrams are no-ops, and "
               "a mixed datagram (valid incarnation advance + trailing "
               "malformed chunk) must still deliver the refute",
        "verbs": [],
        "injections": ["crash", "hb_freeze"],
        "probes": ["MEMBER->SUSPECT:stale", "SUSPECT->MEMBER:refute_evidence",
                   "SUSPECT->FAILED:confirm_window"],
        "engines": ["reference", "udp", "native"],
    },
    "truncated_delta": {
        "doc": "delta wire hardening (round 20): a delta frame cut "
               "mid-entry — the valid incarnation advance in front must "
               "still merge and deliver the refute, the truncated tail "
               "is skipped (a lost/garbled delta degrades to a smaller "
               "merge, never a protocol error)",
        "verbs": [],
        "injections": ["crash", "hb_freeze"],
        "probes": ["MEMBER->SUSPECT:stale", "SUSPECT->MEMBER:refute_evidence",
                   "SUSPECT->FAILED:confirm_window"],
        "engines": ["reference", "udp", "native"],
    },
    "delta_stale_race": {
        "doc": "a stale full-list replay racing a delta advance about "
               "the same member: max-merge must keep the advance "
               "whatever the arrival order — an engine that regresses "
               "the counter never re-stales and the confirm dies",
        "verbs": [],
        "injections": ["crash", "hb_freeze"],
        "probes": ["MEMBER->SUSPECT:stale", "SUSPECT->MEMBER:refute_evidence",
                   "SUSPECT->FAILED:confirm_window"],
        "engines": ["reference", "udp", "native"],
    },
    "delta_unknown_member": {
        "doc": "a delta frame about a member the receivers no longer "
               "list (graceful leave, mid-cooldown): the fail-list "
               "suppression must beat the merge-add — no zombie "
               "resurrection from a marked frame",
        "verbs": [],
        "injections": ["leave"],
        "probes": ["MEMBER->FAILED:leave_or_remove"],
        "engines": ["reference", "udp", "native"],
    },
}

#: event kinds the verdict plane compares (protocol lifecycle + the
#: injection ground truth; everything else — round_tick, scenario_arm —
#: is bookkeeping noise)
TRACKED_KINDS = tuple(sorted(protocol_spec.lifecycle_emit_kinds()))


def _check_probe(probe: str) -> None:
    """A probe string names a contract edge: ``SRC->DST:guard`` must be
    a ``protocol_spec.TRANSITIONS`` row — the generator is spec-driven,
    not a free-form scenario list."""
    edge, _, guard = probe.partition(":")
    src, _, dst = edge.partition("->")
    if protocol_spec.transition(src, dst, guard) is None:
        raise ValueError(f"probe {probe!r} is not a protocol_spec transition")


def _base(family: str, seed: int, rounds: int, suspicion: bool = True) -> dict:
    meta = FAMILIES[family]
    for probe in meta["probes"]:
        _check_probe(probe)
    for verb in meta["verbs"]:
        if verb not in protocol_spec.WIRE_VERBS:
            raise ValueError(f"unknown wire verb {verb!r}")
    for inj in meta["injections"]:
        if protocol_spec.injection(inj) is None:
            raise ValueError(f"unknown injection {inj!r}")
    cfg = dict(CONFIG)
    cfg["suspicion"] = suspicion
    return {
        "schema": SCHEMA,
        "family": family,
        "seed": seed,
        "n": N,
        "rounds": rounds,
        "config": cfg,
        "engines": list(meta["engines"]),
        "verbs": list(meta["verbs"]),
        "injections": list(meta["injections"]),
        "probes": list(meta["probes"]),
        "blackouts": [],
        "steps": [],
        "tracked": [],
        "expect": {},
        "checkpoints": [],
    }


def _subject(rng: random.Random) -> int:
    # never the introducer (node 0): rejoin rides through it
    return rng.randrange(1, N)


def _gen_refute_race(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("refute_race", seed, rounds=22)
    # blackout [2, 12): observers go stale at ~7-9 and SUSPECT at <= 10;
    # the heal at 12 floods fresh counters back in, so the refute lands
    # ~13-14 — five-plus rounds ahead of the confirm deadline (~18-20)
    case["blackouts"] = [{"start": 2, "end": 12, "nodes": [s]}]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "member",
                               "forbid": ["confirm", "remove"],
                               "optional": []}}
    case["checkpoints"] = [{"round": 11, "status": {str(s): "suspect"}}]
    return case


def _gen_confirm_expiry(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("confirm_expiry", seed, rounds=26)
    case["steps"] = [{"round": 2, "op": "crash", "node": s}]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone",
                               "forbid": ["refute"],
                               "optional": []}}
    # suspect enters <= 10, confirm >= 17: round 14 is mid-window with
    # >= 3 rounds of margin on both sides
    case["checkpoints"] = [{"round": 14, "status": {str(s): "suspect"}}]
    return case


def _gen_direct_confirm(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("direct_confirm", seed, rounds=16, suspicion=False)
    case["steps"] = [{"round": 2, "op": "crash", "node": s}]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone",
                               "forbid": ["suspect", "refute"],
                               "optional": []}}
    case["checkpoints"] = [{"round": 12, "status": {str(s): "gone"}}]
    return case


def _gen_leave_broadcast(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    # rounds end BEFORE the fail-list cooldown expires (~9-10): a rare
    # dropped LEAVE datagram could otherwise re-gossip the entry back
    case = _base("leave_broadcast", seed, rounds=8)
    case["steps"] = [{"round": 3, "op": "leave", "node": s}]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone",
                               "forbid": ["suspect", "confirm", "refute"],
                               "optional": []}}
    case["checkpoints"] = [{"round": 6, "status": {str(s): "gone"}}]
    return case


def _gen_rejoin_cooldown(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    s2 = rng.choice([i for i in range(1, N) if i != s])
    case = _base("rejoin_cooldown", seed, rounds=36)
    case["steps"] = [
        {"round": 2, "op": "crash", "node": s},
        # duplicate JOIN about a live, already-listed member: the
        # introducer re-adds idempotently — no lifecycle event for s2
        {"round": 5, "op": "verb", "verb": "JOIN", "about": s2, "to": [0],
         "copies": 2},
        # confirm ~17-20, fail-list expiry ~23-26: round 29 rejoins
        # through the introducer with the cooldown safely spent
        {"round": 29, "op": "join", "node": s},
    ]
    case["tracked"] = [s, s2]
    case["expect"] = {
        str(s): {"final": "member", "forbid": ["refute"], "optional": []},
        str(s2): {"final": "member",
                  "forbid": ["suspect", "refute", "confirm", "remove"],
                  "optional": []},
    }
    case["checkpoints"] = [
        {"round": 25, "status": {str(s): "gone"}},
        {"round": 34, "status": {str(s): "member"}},
    ]
    return case


def _gen_suspect_flood(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    observers = rng.sample([i for i in range(N) if i != s], 2)
    case = _base("suspect_flood", seed, rounds=12)
    # 3 copies straight at the subject + 1 at each of two observers,
    # four rounds running: 20 SUSPECT datagrams about one live member.
    # The subject answers each round's burst with ONE incarnation bump +
    # REFUTE broadcast (the refute_broadcast rate limit); observers
    # adopt the suspicion and drop it at their next tick — the entry is
    # locally fresh, so adoption is refuting-evidence-free bookkeeping.
    case["steps"] = [
        {"round": r, "op": "verb", "verb": "SUSPECT", "about": s,
         "to": [s, s, s] + observers, "copies": 1}
        for r in (3, 4, 5, 6)
    ]
    case["tracked"] = [s]
    # whether an observer's adopted suspicion is popped by the REFUTE
    # datagram (-> a "refute" event) or dropped silently at its next
    # tick is a benign arrival-order race — "refute" is optional, the
    # hard requirements are no confirm/remove and final membership
    case["expect"] = {str(s): {"final": "member",
                               "forbid": ["confirm", "remove"],
                               "optional": ["refute", "suspect"]}}
    case["checkpoints"] = [{"round": 10, "status": {str(s): "member"}}]
    return case


def _gen_stale_refute_replay(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("stale_refute_replay", seed, rounds=34)
    case["steps"] = [
        {"round": 2, "op": "crash", "node": s},
        # mid-suspect-window (suspect <= 10, confirm >= 17): a REPLAYED
        # REFUTE carrying a stale incarnation (hb=1).  The counter does
        # not advance (max-merge), but the explicit REFUTE rule cancels
        # the suspicion and re-stamps freshness — the entry re-stales
        # from here, pushing the confirm out by a full t_fail+t_suspect
        {"round": 13, "op": "verb", "verb": "REFUTE", "about": s,
         "hb": "stale", "to": "live", "copies": 2},
    ]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone", "forbid": [],
                               "optional": []}}
    case["checkpoints"] = [
        {"round": 15, "status": {str(s): "member"}},   # replay revived it
        {"round": 24, "status": {str(s): "suspect"}},  # re-staled, window 2
    ]
    return case


def _gen_remove_poison(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("remove_poison", seed, rounds=22)
    case["steps"] = [
        # forged REMOVE about a LIVE member to every other node: all of
        # them fail-list s (cooldown suppression holds ~6 rounds), then
        # s's own list gossip re-adds it after expiry — the protocol
        # self-heals a poisoned removal without any detection lifecycle
        {"round": 4, "op": "verb", "verb": "REMOVE", "about": s,
         "to": "others", "copies": 2},
    ]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "member",
                               "forbid": ["suspect", "confirm"],
                               "optional": []}}
    case["checkpoints"] = [{"round": 7, "status": {str(s): "gone"}}]
    return case


def _gen_malformed_codec(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("malformed_codec", seed, rounds=34)
    case["steps"] = [
        {"round": 2, "op": "crash", "node": s},
        # pure garbage through the wire codec: every style must be a
        # complete no-op (no ghost members, no aborted ticks)
        {"round": 3, "op": "malformed", "style": "garbage", "to": "live",
         "copies": 1},
        {"round": 3, "op": "malformed", "style": "empty_hb", "to": "live",
         "copies": 1},
        {"round": 4, "op": "malformed", "style": "unknown_verb",
         "to": "live", "copies": 1},
        {"round": 4, "op": "malformed", "style": "bad_hb", "to": "live",
         "copies": 1},
        # the codec-hardening probe: one datagram carrying a VALID
        # incarnation advance for the crashed subject plus a trailing
        # malformed chunk.  A hardened decoder salvages the valid entry
        # (refute-by-advance fires, mirroring the engine that skips bad
        # chunks); a brittle one throws and drops the whole datagram —
        # the refute never lands and the checkpoint below goes red
        {"round": 13, "op": "malformed", "style": "mixed_refresh",
         "about": s, "hb_boost": 100, "to": "live", "copies": 2},
    ]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone", "forbid": [],
                               "optional": []}}
    case["checkpoints"] = [
        {"round": 15, "status": {str(s): "member"}},
        {"round": 24, "status": {str(s): "suspect"}},
    ]
    return case


def _gen_truncated_delta(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("truncated_delta", seed, rounds=34)
    case["steps"] = [
        {"round": 2, "op": "crash", "node": s},
        # mid-suspect-window: a DELTA frame carrying a valid incarnation
        # advance for the crashed subject, cut mid-entry after it.  The
        # dispatch contract: a marked frame runs the SAME hardened
        # max-merge as a full list, so the advance is salvaged
        # (refute-by-advance revives s) and the truncated chunk is
        # skipped — a brittle delta decoder drops the whole frame and
        # the revive checkpoint goes red (timings mirror malformed_codec)
        {"round": 13, "op": "malformed", "style": "truncated_delta",
         "about": s, "hb_boost": 100, "to": "live", "copies": 2},
    ]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone", "forbid": [],
                               "optional": []}}
    case["checkpoints"] = [
        {"round": 15, "status": {str(s): "member"}},
        {"round": 24, "status": {str(s): "suspect"}},
    ]
    return case


def _gen_delta_stale_race(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    case = _base("delta_stale_race", seed, rounds=34)
    case["steps"] = [
        {"round": 2, "op": "crash", "node": s},
        # the race: a clean delta advance for the crashed subject AND a
        # replayed stale full-list fragment (hb=1) about the same
        # member, the stale copy injected LAST.  Max-merge is
        # order-free: the advance must survive (revive at ~13), the
        # stale replay must neither regress the counter nor re-stamp
        # freshness.  An engine that adopts the stale counter leaves
        # hb=1 — inside the detection grace, so s never re-stales and
        # the suspect checkpoint goes red
        {"round": 13, "op": "malformed", "style": "delta_refresh",
         "about": s, "hb_boost": 100, "to": "live", "copies": 2},
        {"round": 13, "op": "malformed", "style": "stale_full_replay",
         "about": s, "to": "live", "copies": 2},
    ]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone", "forbid": [],
                               "optional": []}}
    case["checkpoints"] = [
        {"round": 15, "status": {str(s): "member"}},
        {"round": 24, "status": {str(s): "suspect"}},
    ]
    return case


def _gen_delta_unknown_member(seed: int) -> dict:
    rng = random.Random(seed)
    s = _subject(rng)
    # rounds end BEFORE the fail-list cooldown expires (~9-10), like
    # leave_broadcast: past expiry a re-injected advance legitimately
    # re-adds (the cooldown intentionally scopes zombie suppression)
    case = _base("delta_unknown_member", seed, rounds=9)
    case["steps"] = [
        {"round": 3, "op": "leave", "node": s},
        # mid-cooldown: a clean delta advance about the departed member,
        # whom no receiver lists any more.  The merge-add guard is the
        # probe: fail-listed entries are NOT resurrected, marked frame
        # or not — a brittle engine re-adds the zombie and the gone
        # checkpoint goes red
        {"round": 6, "op": "malformed", "style": "delta_refresh",
         "about": s, "hb_boost": 100, "to": "live", "copies": 2},
    ]
    case["tracked"] = [s]
    case["expect"] = {str(s): {"final": "gone",
                               "forbid": ["suspect", "confirm", "refute"],
                               "optional": []}}
    case["checkpoints"] = [{"round": 8, "status": {str(s): "gone"}}]
    return case


_GENERATORS = {
    "refute_race": _gen_refute_race,
    "confirm_expiry": _gen_confirm_expiry,
    "direct_confirm": _gen_direct_confirm,
    "leave_broadcast": _gen_leave_broadcast,
    "rejoin_cooldown": _gen_rejoin_cooldown,
    "suspect_flood": _gen_suspect_flood,
    "stale_refute_replay": _gen_stale_refute_replay,
    "remove_poison": _gen_remove_poison,
    "malformed_codec": _gen_malformed_codec,
    "truncated_delta": _gen_truncated_delta,
    "delta_stale_race": _gen_delta_stale_race,
    "delta_unknown_member": _gen_delta_unknown_member,
}


def generate(family: str, seed: int = 0) -> dict:
    """One seed-pure case doc (same inputs -> byte-identical
    :func:`serialize` output)."""
    if family not in _GENERATORS:
        raise ValueError(f"unknown schedule family {family!r}; "
                         f"have {sorted(_GENERATORS)}")
    case = _GENERATORS[family](seed)
    validate(case)
    return case


def generate_corpus(seeds=(0,)) -> list[dict]:
    """The full corpus: every family x every seed, generation order
    stable (family table order, then seed order)."""
    return [generate(family, seed) for family in FAMILIES for seed in seeds]


def serialize(case: dict) -> str:
    """Canonical byte form (sorted keys): the seed-determinism and
    round-trip contract the tests pin."""
    return json.dumps(case, sort_keys=True, indent=2) + "\n"


def parse(text: str) -> dict:
    case = json.loads(text)
    validate(case)
    return case


def validate(case: dict) -> dict:
    """Structural + spec validation of a case doc (generated or loaded
    from ``regressions/``)."""
    if case.get("schema") != SCHEMA:
        raise ValueError(f"not a {SCHEMA} doc: {case.get('schema')!r}")
    if case["family"] not in FAMILIES:
        raise ValueError(f"unknown family {case['family']!r}")
    for probe in case["probes"]:
        _check_probe(probe)
    for verb in case["verbs"]:
        if verb not in protocol_spec.WIRE_VERBS:
            raise ValueError(f"unknown wire verb {verb!r}")
    for inj in case["injections"]:
        if protocol_spec.injection(inj) is None:
            raise ValueError(f"unknown injection {inj!r}")
    for step in case["steps"]:
        op = step["op"]
        if op not in ("crash", "leave", "join", "verb", "malformed"):
            raise ValueError(f"unknown step op {op!r}")
        if not 0 <= step["round"] < case["rounds"]:
            raise ValueError(f"step round {step['round']} outside schedule")
        if op == "verb" and step["verb"] not in protocol_spec.WIRE_VERBS:
            raise ValueError(f"unknown wire verb {step['verb']!r}")
    for subject in case["tracked"]:
        if str(subject) not in case["expect"]:
            raise ValueError(f"tracked subject {subject} has no expect row")
        exp = case["expect"][str(subject)]
        if exp["final"] not in ("member", "suspect", "gone"):
            raise ValueError(f"bad final status {exp['final']!r}")
        for kind in exp["forbid"] + exp["optional"]:
            if kind not in TRACKED_KINDS:
                raise ValueError(f"unknown event kind {kind!r}")
    for cp in case["checkpoints"]:
        if not 0 <= cp["round"] < case["rounds"]:
            raise ValueError(f"checkpoint round {cp['round']} outside run")
        for status in cp["status"].values():
            if status not in ("member", "suspect", "gone"):
                raise ValueError(f"bad checkpoint status {status!r}")
    return case


def coverage() -> dict:
    """The corpus-wide closure over the contract: which wire verbs,
    injection seams, and transitions the family set exercises.  The
    import-time assert below keeps :data:`FAMILIES` honest; the
    ``conformance-verb-coverage`` lint rule re-derives the same closure
    statically for drift protection."""
    verbs: set[str] = set()
    injections: set[str] = set()
    probes: set[str] = set()
    for meta in FAMILIES.values():
        verbs.update(meta["verbs"])
        injections.update(meta["injections"])
        probes.update(meta["probes"])
    covered_edges = set()
    for probe in probes:
        _check_probe(probe)
        edge, _, guard = probe.partition(":")
        src, _, dst = edge.partition("->")
        covered_edges.add((src, dst, guard))
    missing_edges = [
        f"{t.src}->{t.dst}:{t.guard}" for t in protocol_spec.TRANSITIONS
        if (t.src, t.dst, t.guard) not in covered_edges
    ]
    return {
        "families": len(FAMILIES),
        "verbs": sorted(verbs),
        "verbs_missing": sorted(set(protocol_spec.WIRE_VERBS) - verbs),
        "injections": sorted(injections),
        "injections_missing": sorted(
            {i.name for i in protocol_spec.INJECTIONS} - injections),
        "transitions_missing": missing_edges,
        "complete": (verbs == set(protocol_spec.WIRE_VERBS)
                     and injections >= {i.name
                                        for i in protocol_spec.INJECTIONS}
                     and not missing_edges),
    }
