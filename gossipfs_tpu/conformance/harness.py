"""Per-engine injection drivers + the step-for-step reference oracle.

One ``gossipfs-conformance/v1`` case doc (``schedules.py``) runs through
four surfaces:

  * **reference** — a synchronous per-node model built directly on the
    contract's per-node lifecycle API (``suspicion/runtime.py``) with
    the udp engine's handler table transcribed rule-for-rule (max-merge,
    cooldown suppression, the refute-once-per-period rate limit, the
    min_group refresh-only guard, the hb<=1 detection grace).  It runs
    on a logical round clock (period = 1.0), so its prediction is
    deterministic — the oracle every socket run is compared against;
  * **tensor** — ``detector/sim.py`` via the injection verbs and the
    scenario plane (no datagram seam: wire-verb families exclude it);
  * **udp** — ``detector/udp.py`` over real localhost sockets, schedule
    steps injected as crafted datagrams through the engine's own wire
    codec;
  * **native** — the C++ epoll engine (``gossipfs_tpu/native.py``),
    crafted datagrams straight at its sockets, membership/suspect/
    incarnation surfaces read over the sized C ABI
    (``gfs_suspects`` / ``gfs_incarnation``).

Every driver returns the same *bundle* shape::

    {"engine": ..., "events": [{round, observer, subject, kind}, ...],
     "final": {subject: "member"|"suspect"|"gone"},
     "checkpoints": {round: {subject: status}},
     "incarnations": {subject: hb} | {},      # engines that expose hb
     "counters": {...}}

with event rounds schedule-relative (warmup happens off the clock on
every engine) and filtered to the contract's lifecycle kinds —
``verdict.py`` consumes nothing else.  Socket runs are wall-clock
jittered; the schedules keep >= 2 rounds of margin around every
checkpoint so the comparison is protocol, not scheduling.
"""

from __future__ import annotations

import asyncio
import random
import socket
import time

from gossipfs_tpu.analysis import protocol_spec
from gossipfs_tpu.conformance.schedules import TRACKED_KINDS, validate
from gossipfs_tpu.detector.udp import (CMD_SEP, DELTA_MARK, ENTRY_SEP,
                                       FIELD_SEP)
from gossipfs_tpu.scenarios.schedule import CorrelatedOutage, FaultScenario
from gossipfs_tpu.suspicion.params import SuspicionParams
from gossipfs_tpu.suspicion.runtime import SuspicionRuntime

#: hb value for a REPLAYED/stale incarnation (below any live counter
#: past the warmup grace)
STALE_HB = 1

#: warmup rounds the reference runs off the clock (counters past the
#: hb<=1 grace, mirroring the socket engines' warmed start)
_WARMUP = 3


def suspicion_params(cfg: dict) -> SuspicionParams | None:
    if not cfg.get("suspicion", True):
        return None
    return SuspicionParams(t_suspect=int(cfg["t_suspect"]),
                           lh_multiplier=int(cfg["lh_multiplier"]),
                           lh_frac=float(cfg["lh_frac"]))


def case_scenario(case: dict) -> FaultScenario | None:
    """The schedule's blackout windows as the scenario plane's rule
    table (CorrelatedOutage: src OR dst dark -> drop), armed at
    schedule round 0 on every engine."""
    if not case["blackouts"]:
        return None
    return FaultScenario(
        name=f"conformance-{case['family']}",
        n=case["n"],
        outages=tuple(
            CorrelatedOutage(start=b["start"], end=b["end"],
                             nodes=tuple(b["nodes"]))
            for b in case["blackouts"]),
    )


# ---------------------------------------------------------------------------
# wire payloads (the adversary speaks the engines' own codec)
# ---------------------------------------------------------------------------


def wire_verb(verb: str, about_addr: str, hb: int | None = None) -> str:
    """One control datagram, byte-compatible with both socket engines'
    codecs (detector/udp.py handle() / native HandleDatagram)."""
    if verb not in protocol_spec.WIRE_VERBS:
        raise ValueError(f"unknown wire verb {verb!r}")
    if verb == "REFUTE":
        return f"{about_addr}{FIELD_SEP}{hb if hb is not None else 0}" \
               f"{CMD_SEP}REFUTE"
    return f"{about_addr}{CMD_SEP}{verb}"


def malformed_payload(style: str, about_addr: str | None = None,
                      hb: int | None = None) -> str:
    """Codec-hardening payloads.  ``mixed_refresh`` is the sharp one: a
    VALID entry (an incarnation advance for ``about_addr``) followed by
    a malformed chunk — a hardened decoder salvages the valid entry, a
    brittle one throws on the bad chunk and loses the whole datagram."""
    if style == "garbage":
        return "!!not-a-protocol-datagram!!"
    if style == "empty_hb":
        return f"x{FIELD_SEP}"
    if style == "bad_hb":
        return f"127.0.0.1:1{FIELD_SEP}notanumber"
    if style == "unknown_verb":
        return f"127.0.0.1:1{CMD_SEP}FROB"
    if style == "mixed_refresh":
        return (f"{about_addr}{FIELD_SEP}{hb}{FIELD_SEP}0.0"
                f"{ENTRY_SEP}x{FIELD_SEP}")
    # delta wire format (round 20, protocol_spec.DELTA_GOSSIP).  Every
    # engine must dispatch a marked frame through the SAME hardened
    # max-merge as a full list, whatever its own dissemination mode.
    if style == "truncated_delta":
        # a delta frame cut mid-entry: the valid advance in front must
        # still merge (hardened salvage), the truncated tail is skipped
        return (f"{DELTA_MARK}{about_addr}{FIELD_SEP}{hb}{FIELD_SEP}0.0"
                f"{ENTRY_SEP}x{FIELD_SEP}")
    if style == "delta_refresh":
        # a well-formed single-entry delta advance — the race/zombie
        # probes' carrier (delta_stale_race, delta_unknown_member)
        return f"{DELTA_MARK}{about_addr}{FIELD_SEP}{hb}{FIELD_SEP}0.0"
    if style == "stale_full_replay":
        # a replayed full-list fragment with a STALE counter: max-merge
        # must neither regress the entry nor re-stamp its freshness
        return f"{about_addr}{FIELD_SEP}{STALE_HB}{FIELD_SEP}0.0"
    raise ValueError(f"unknown malformed style {style!r}")


#: malformed styles whose payload carries a live incarnation advance for
#: ``about`` (the drivers compute hb = current + hb_boost at fire time)
_ADVANCE_STYLES = ("mixed_refresh", "truncated_delta", "delta_refresh")


def _steps_by_round(case: dict) -> dict[int, list[dict]]:
    by_round: dict[int, list[dict]] = {}
    for step in case["steps"]:
        by_round.setdefault(step["round"], []).append(step)
    return by_round


def _targets(step: dict, alive: list[int]) -> list[int]:
    """Resolve a step's ``to`` spec against the engine's live set at
    fire time (``"live"`` = every live node, ``"others"`` = every live
    node except the subject)."""
    to = step["to"]
    if to == "live":
        return list(alive)
    if to == "others":
        return [i for i in alive if i != step.get("about")]
    return list(to)


# ---------------------------------------------------------------------------
# reference oracle
# ---------------------------------------------------------------------------


class _Member:
    __slots__ = ("hb", "ts")

    def __init__(self, hb: int, ts: float):
        self.hb = int(hb)
        self.ts = ts


class _RefNode:
    """One reference process: the udp engine's handler table + tick,
    transcribed onto a logical round clock (period = 1.0) with the
    contract's per-node lifecycle API carrying the suspicion state."""

    def __init__(self, world: "ReferenceEngine", idx: int):
        self.world = world
        self.idx = idx
        self.addr = f"ref:{idx}"
        self.alive = True
        self.members: dict[str, _Member] = {}
        self.fail_list: dict[str, float] = {}
        self.rt = (SuspicionRuntime(world.params)
                   if world.params is not None else None)
        self._last_refute = float("-inf")
        self.refute_broadcasts = 0
        # same stream construction as UdpNode's push draw — the oracle's
        # dissemination is a faithful peer, not a bit-twin (socket runs
        # are wall-jittered anyway; the prediction is the OBSERVABLE
        # surface, which the schedule margins make draw-independent)
        self._rng = random.Random(0x5EED ^ (idx * 2654435761))

    # -- receive dispatch (mirrors UdpNode.handle) --------------------------
    def handle(self, payload: str) -> None:
        if not self.alive:
            return
        if CMD_SEP in payload:
            arg, verb = payload.split(CMD_SEP, 1)
            if verb == "JOIN":
                self._add_member(arg)
            elif verb in ("LEAVE", "REMOVE"):
                self._remove_member(arg)
            elif verb == "SUSPECT":
                self._on_suspect(arg)
            elif verb == "REFUTE":
                self._on_refute(arg)
            # unknown verbs: silent no-op (codec hardening contract)
        elif payload.startswith(DELTA_MARK):
            # delta frame: strip the marker, run the SAME hardened
            # max-merge (the udp/native dispatch rule — a truncated or
            # replayed delta degrades to a smaller merge, never an error)
            self._merge(self._decode(payload[len(DELTA_MARK):]))
        else:
            self._merge(self._decode(payload))

    @staticmethod
    def _decode(payload: str) -> list[tuple[str, int]]:
        # the HARDENED decode is the contract: malformed chunks are
        # skipped, valid entries in the same datagram still merge
        out = []
        for chunk in payload.split(ENTRY_SEP):
            parts = chunk.split(FIELD_SEP)
            if len(parts) >= 2:
                try:
                    out.append((parts[0], int(float(parts[1]))))
                except ValueError:
                    continue
        return out

    def _on_suspect(self, addr: str) -> None:
        if self.rt is None:
            return
        now = self.world.now
        if addr == self.addr:
            me = self.members.get(self.addr)
            if me is None:
                return
            if now - self._last_refute < 1.0:
                return  # refute once per period (RATE_LIMITS row)
            self._last_refute = now
            me.hb += 1
            me.ts = now
            self.refute_broadcasts += 1
            msg = f"{self.addr}{FIELD_SEP}{me.hb}{CMD_SEP}REFUTE"
            for peer in list(self.members):
                if peer != self.addr:
                    self.world.send(self.idx, peer, msg)
        elif addr in self.members:
            self.rt.adopt(addr, now)

    def _on_refute(self, arg: str) -> None:
        parts = arg.split(FIELD_SEP)
        addr = parts[0]
        try:
            hb = int(float(parts[1])) if len(parts) > 1 else 0
        except ValueError:
            hb = 0
        m = self.members.get(addr)
        if m is None:
            return
        if hb > m.hb:
            m.hb = hb
        m.ts = self.world.now  # an explicit REFUTE re-stamps freshness
        if self.rt is not None and self.rt.refute(addr):
            self.world.obs("refute", self.idx, addr)

    def _add_member(self, addr: str) -> None:
        if addr not in self.members:
            self.members[addr] = _Member(0, self.world.now)
        msg = self._encode()
        for peer in list(self.members):
            if peer != self.addr:
                self.world.send(self.idx, peer, msg)

    def _remove_member(self, addr: str) -> None:
        member = self.members.pop(addr, None)
        if member is not None and addr not in self.fail_list:
            # fresh_cooldown profile: stamp removal time
            self.fail_list[addr] = self.world.now
            self.world.obs("remove", self.idx, addr)
        if self.rt is not None:
            self.rt.drop(addr)

    def _merge(self, remote: list[tuple[str, int]]) -> None:
        now = self.world.now
        for addr, hb in remote:
            local = self.members.get(addr)
            if local is not None:
                if hb > local.hb:
                    local.hb = hb
                    local.ts = now
                    if self.rt is not None and self.rt.refute(addr):
                        self.world.obs("refute", self.idx, addr)
            elif addr not in self.fail_list:
                self.members[addr] = _Member(hb, now)

    def _encode(self) -> str:
        return ENTRY_SEP.join(
            f"{a}{FIELD_SEP}{m.hb}{FIELD_SEP}{m.ts}"
            for a, m in self.members.items())

    # -- heartbeat tick (mirrors UdpNode.tick; unit = 1 round) --------------
    def tick(self) -> None:
        if not self.alive:
            return
        w = self.world
        now = w.now
        if len(self.members) < w.min_group:
            for m in self.members.values():
                m.ts = now  # refresh-only guard
            return
        me = self.members.get(self.addr)
        if me is not None:
            me.hb += 1
            me.ts = now
        for addr in list(self.members):
            if addr == self.addr:
                continue
            m = self.members[addr]
            stale = m.hb > 1 and m.ts < now - w.t_fail
            if not stale:
                if self.rt is not None:
                    self.rt.drop(addr)  # fresh entry: adoption discarded
                continue
            if self.rt is not None:
                if self.rt.suspect(addr, now):
                    self.world.obs("suspect", self.idx, addr)
                    msg = f"{addr}{CMD_SEP}SUSPECT"
                    w.send(self.idx, addr, msg)
                    peers = [a for a in self.members
                             if a != self.addr and a != addr]
                    for peer in self._rng.sample(
                            peers, min(w.fanout, len(peers))):
                        w.send(self.idx, peer, msg)
                    continue
                window = self.rt.t_suspect_window(1.0, len(self.members))
                if not self.rt.expired(addr, now, window):
                    # per-tick re-notification (round 16 contract)
                    w.send(self.idx, addr, f"{addr}{CMD_SEP}SUSPECT")
                    continue
                self.rt.confirm(addr)
            w.confirm(self.idx, addr)
            self._remove_member(addr)
        for addr in list(self.fail_list):
            if self.fail_list[addr] < now - w.t_cooldown:
                del self.fail_list[addr]
        msg = self._encode()
        peers = [a for a in self.members if a != self.addr]
        for peer in self._rng.sample(peers, min(w.fanout, len(peers))):
            w.send(self.idx, peer, msg)


class ReferenceEngine:
    """The deterministic world the reference nodes live in: synchronous
    per-round delivery (datagram latency << period on every real
    engine), blackout gates on organic sends only (injected datagrams
    model an adversary inside the network, exactly like the raw-socket
    injection the socket drivers use)."""

    def __init__(self, case: dict):
        cfg = case["config"]
        self.case = case
        self.n = case["n"]
        self.params = suspicion_params(cfg)
        self.t_fail = int(cfg["t_fail"])
        self.t_cooldown = int(cfg["t_cooldown"])
        self.min_group = int(cfg["min_group"])
        self.fanout = int(cfg["fanout"])
        self.now = 0.0
        self.recording = False
        self.events: list[dict] = []
        self.confirms = 0
        self.nodes = [_RefNode(self, i) for i in range(self.n)]
        for node in self.nodes:  # steady-state start, like the engines
            node.members = {p.addr: _Member(0, 0.0) for p in self.nodes}
        self._queue: list[tuple[int, str]] = []

    # -- plumbing ----------------------------------------------------------
    def addr_of(self, idx: int) -> str:
        return self.nodes[idx].addr

    def _dark(self, idx: int) -> bool:
        return any(b["start"] <= self.now < b["end"] and idx in b["nodes"]
                   for b in self.case["blackouts"])

    def send(self, src: int, peer_addr: str, msg: str) -> None:
        dst = int(peer_addr.rsplit(":", 1)[1])
        if self._dark(src) or self._dark(dst):
            return
        self._queue.append((dst, msg))

    def inject(self, dst: int, payload: str) -> None:
        self._queue.append((dst, payload))

    def _drain(self) -> None:
        # to fixpoint: a delivered SUSPECT triggers a REFUTE broadcast
        # that lands the same round (datagram latency << period); the
        # refute-per-period rate limit bounds the cascade
        while self._queue:
            batch, self._queue = self._queue, []
            for dst, msg in batch:
                self.nodes[dst].handle(msg)

    def obs(self, kind: str, observer: int, subject_addr: str,
            **detail) -> None:
        if not self.recording:
            return
        subject = int(subject_addr.rsplit(":", 1)[1]) \
            if ":" in subject_addr else -1
        self.events.append({"round": int(self.now), "observer": observer,
                            "subject": subject, "kind": kind})

    def confirm(self, observer: int, subject_addr: str) -> None:
        self.confirms += 1
        self.obs("confirm", observer, subject_addr)

    def status(self, observer: int, subject: int) -> str:
        node = self.nodes[observer]
        addr = self.addr_of(subject)
        if node.rt is not None and addr in node.rt.suspects:
            return "suspect"
        return "member" if addr in node.members else "gone"

    # -- the schedule loop --------------------------------------------------
    def _apply(self, step: dict) -> None:
        op = step["op"]
        if op == "crash":
            node = self.nodes[step["node"]]
            node.alive = False
            self.obs("crash", -1, node.addr)
            self.obs("hb_freeze", -1, node.addr)
        elif op == "leave":
            node = self.nodes[step["node"]]
            msg = f"{node.addr}{CMD_SEP}LEAVE"
            for peer in list(node.members):
                if peer != node.addr:
                    self.send(node.idx, peer, msg)
            node.alive = False
            self.obs("leave", -1, node.addr)
        elif op == "join":
            node = self.nodes[step["node"]]
            node.alive = True
            node.members = {node.addr: _Member(0, self.now)}
            node.fail_list = {}
            if node.rt is not None:
                node.rt = SuspicionRuntime(self.params)
            self.send(node.idx, self.addr_of(0),
                      f"{node.addr}{CMD_SEP}JOIN")
            self.obs("join", -1, node.addr)
        elif op in ("verb", "malformed"):
            alive = [i for i in range(self.n) if self.nodes[i].alive]
            about = step.get("about")
            about_addr = self.addr_of(about) if about is not None else None
            for t in _targets(step, alive):
                if op == "verb":
                    hb = None
                    if step.get("hb") == "stale":
                        hb = STALE_HB
                    payload = wire_verb(step["verb"], about_addr, hb=hb)
                else:
                    hb = None
                    if step["style"] in _ADVANCE_STYLES:
                        m = self.nodes[t].members.get(about_addr)
                        hb = (m.hb if m else 0) + int(step["hb_boost"])
                    payload = malformed_payload(step["style"],
                                                about_addr=about_addr,
                                                hb=hb)
                for _ in range(int(step.get("copies", 1))):
                    self.inject(t, payload)

    def run(self) -> dict:
        case = self.case
        steps = _steps_by_round(case)
        # warmup off the clock: counters past the hb<=1 grace
        for r in range(-_WARMUP, 0):
            self.now = float(r)
            for node in self.nodes:
                node.tick()
            self._drain()
        self.recording = True
        checkpoints: dict[int, dict[int, str]] = {}
        for r in range(case["rounds"]):
            self.now = float(r)
            for step in steps.get(r, ()):
                self._apply(step)
            self._drain()
            for node in self.nodes:
                node.tick()
            self._drain()
            for cp in case["checkpoints"]:
                if cp["round"] == r:
                    checkpoints[r] = {
                        s: self.status(0, s) for s in case["tracked"]}
        final = {s: self.status(0, s) for s in case["tracked"]}
        incarnations = {}
        for s in case["tracked"]:
            m = self.nodes[0].members.get(self.addr_of(s))
            if m is not None:
                incarnations[s] = m.hb
        return {
            "engine": "reference",
            "events": self.events,
            "final": final,
            "checkpoints": checkpoints,
            "incarnations": incarnations,
            "counters": {
                "confirms": self.confirms,
                "refute_broadcasts": sum(
                    n.refute_broadcasts for n in self.nodes),
            },
        }


def run_case_reference(case: dict) -> dict:
    validate(case)
    return ReferenceEngine(case).run()


# ---------------------------------------------------------------------------
# shared driver plumbing
# ---------------------------------------------------------------------------


def _lifecycle_events(recorder_events, round0: int = 0) -> list[dict]:
    return [
        {"round": e.round - round0, "observer": e.observer,
         "subject": e.subject, "kind": e.kind}
        for e in recorder_events if e.kind in TRACKED_KINDS
    ]


def _classify(membership: list[int], suspects: list[int],
              subject: int) -> str:
    if subject in suspects:
        return "suspect"
    return "member" if subject in membership else "gone"


class _Injector:
    """Raw-socket datagram injection for the socket engines: the
    adversary writes through the engines' REAL receive path (codec,
    dispatch, rate limits) with no test seam in between."""

    def __init__(self, base_port: int):
        self.base_port = base_port
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def send(self, node: int, payload: str, copies: int = 1) -> None:
        for _ in range(copies):
            self.sock.sendto(payload.encode(),
                             ("127.0.0.1", self.base_port + node))

    def close(self) -> None:
        self.sock.close()


def _free_udp_base(n: int) -> int:
    from gossipfs_tpu.deploy.launcher import _free_port_base

    return _free_port_base(n, tcp=False)


# ---------------------------------------------------------------------------
# tensor driver (injection verbs + scenario plane; no datagram seam)
# ---------------------------------------------------------------------------


def run_case_tensor(case: dict) -> dict:
    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.detector.sim import SimDetector
    from gossipfs_tpu.obs.recorder import FlightRecorder

    validate(case)
    for step in case["steps"]:
        if step["op"] in ("verb", "malformed"):
            raise ValueError(
                f"family {case['family']!r} carries wire-plane steps; "
                "the tensor engine has no datagram seam (schedules.py "
                "engines gating)")
    cfg = case["config"]
    sim_cfg = SimConfig(
        n=case["n"], topology="random", fanout=int(cfg["fanout"]),
        t_fail=int(cfg["t_fail"]), t_cooldown=int(cfg["t_cooldown"]),
        min_group=int(cfg["min_group"]), remove_broadcast=False,
        fresh_cooldown=True, suspicion=suspicion_params(cfg),
    )
    det = SimDetector(sim_cfg)
    det.advance(_WARMUP)  # off the clock: counters past the hb<=1 grace
    rec = FlightRecorder(source="tensor-conformance", n=case["n"],
                         case=case["family"])
    det.attach_recorder(rec)
    r0 = int(det.state.round)
    sc = case_scenario(case)
    if sc is not None:
        det.load_scenario(sc)
    steps = _steps_by_round(case)
    checkpoints: dict[int, dict[int, str]] = {}
    for r in range(case["rounds"]):
        for step in steps.get(r, ()):
            getattr(det, step["op"])(step["node"])
        det.advance(1)
        for cp in case["checkpoints"]:
            if cp["round"] == r:
                membership = det.membership(0)
                suspects = det.suspects(0)
                checkpoints[r] = {
                    s: _classify(membership, suspects, s)
                    for s in case["tracked"]}
    membership = det.membership(0)
    suspects = det.suspects(0)
    return {
        "engine": "tensor",
        "events": _lifecycle_events(rec.events, round0=r0),
        "final": {s: _classify(membership, suspects, s)
                  for s in case["tracked"]},
        "checkpoints": checkpoints,
        # the tensor state exposes no per-entry incarnation surface at
        # the detector API — absent, not fabricated (the n/a rule)
        "incarnations": {},
        "counters": {},
    }


# ---------------------------------------------------------------------------
# udp driver (asyncio cluster + crafted datagrams)
# ---------------------------------------------------------------------------


def udp_case_period(n: int) -> float:
    # The asyncio round clock is sleep-paced (run(1) = sleep(period);
    # round += 1) while staleness/expiry thresholds are measured in TRUE
    # wall seconds — per-round loop overhead therefore shrinks every
    # threshold when counted in rounds.  At the campaign period (0.05s
    # for n=8) the overhead is ~30% of a round and a 5-round staleness
    # window crosses ~3.5 schedule rounds in: the reference and the
    # socket engine then straddle checkpoints.  Conformance runs pad the
    # period so the overhead fraction (and the skew) stays well under
    # the schedules' >=2-round checkpoint margins.
    from gossipfs_tpu.campaigns.engines import udp_period

    return max(0.25, udp_period(n))


async def _udp_case(case: dict, period: float,
                    warmup_timeout: float) -> dict:
    from gossipfs_tpu.detector.udp import UdpCluster
    from gossipfs_tpu.obs.recorder import FlightRecorder

    cfg = case["config"]
    n = case["n"]
    base = _free_udp_base(n)
    cluster = UdpCluster(
        n, base_port=base, period=period, t_fail=int(cfg["t_fail"]),
        t_cooldown=int(cfg["t_cooldown"]), min_group=int(cfg["min_group"]),
        fresh_cooldown=True, suspicion=suspicion_params(cfg),
        push=cfg["push"], fanout=int(cfg["fanout"]),
        remove_broadcast=bool(cfg["remove_broadcast"]),
    )
    inj = _Injector(base)
    await cluster.start_all()
    try:
        # warmed steady-state start OFF the round clock (nodes tick on
        # their own heartbeat tasks; cluster._round stays 0, so the
        # recorded stream is schedule-relative) — engines.py's idiom
        cluster.seed_full_membership()
        deadline = time.monotonic() + warmup_timeout
        while time.monotonic() < deadline:
            if all(len(node.members) == n
                   and min(m.hb for m in node.members.values()) > 1
                   for node in cluster.nodes):
                break
            await asyncio.sleep(period)
        else:
            raise TimeoutError(
                f"udp cluster (n={n}) did not warm within "
                f"{warmup_timeout}s")
        rec = FlightRecorder(source="udp-conformance", n=n,
                             case=case["family"])
        cluster.attach_recorder(rec)
        sc = case_scenario(case)
        if sc is not None:
            cluster.load_scenario(sc)
        steps = _steps_by_round(case)
        checkpoints: dict[int, dict[int, str]] = {}
        for r in range(case["rounds"]):
            for step in steps.get(r, ()):
                await _udp_step(cluster, inj, step)
            await cluster.run(1)
            for cp in case["checkpoints"]:
                if cp["round"] == r:
                    membership = cluster.membership(0)
                    suspects = cluster.suspects(0)
                    checkpoints[r] = {
                        s: _classify(membership, suspects, s)
                        for s in case["tracked"]}
        membership = cluster.membership(0)
        suspects = cluster.suspects(0)
        incarnations = {}
        for s in case["tracked"]:
            m = cluster.nodes[0].members.get(cluster.nodes[s].addr)
            if m is not None:
                incarnations[s] = int(m.hb)
        tick_errors = [repr(node.last_tick_error)
                       for node in cluster.nodes
                       if node.last_tick_error is not None]
        return {
            "engine": "udp",
            "events": _lifecycle_events(rec.events),
            "final": {s: _classify(membership, suspects, s)
                      for s in case["tracked"]},
            "checkpoints": checkpoints,
            "incarnations": incarnations,
            "counters": {"tick_errors": tick_errors},
        }
    finally:
        inj.close()
        cluster.stop_all()


async def _udp_step(cluster, inj: _Injector, step: dict) -> None:
    op = step["op"]
    if op == "crash":
        cluster.crash(step["node"])
    elif op == "leave":
        cluster.leave(step["node"])
    elif op == "join":
        await cluster.join(step["node"])
    else:
        alive = [i for i in range(cluster.n) if cluster.nodes[i].alive]
        about = step.get("about")
        about_addr = cluster.nodes[about].addr if about is not None else None
        for t in _targets(step, alive):
            if op == "verb":
                hb = STALE_HB if step.get("hb") == "stale" else None
                payload = wire_verb(step["verb"], about_addr, hb=hb)
            else:
                hb = None
                if step["style"] in _ADVANCE_STYLES:
                    m = cluster.nodes[t].members.get(about_addr)
                    hb = (int(m.hb) if m else 0) + int(step["hb_boost"])
                payload = malformed_payload(step["style"],
                                            about_addr=about_addr, hb=hb)
            inj.send(t, payload, copies=int(step.get("copies", 1)))


def run_case_udp(case: dict, *, period: float | None = None,
                 warmup_timeout: float = 60.0) -> dict:
    validate(case)
    if period is None:
        period = udp_case_period(case["n"])
    return asyncio.run(_udp_case(case, period, warmup_timeout))


# ---------------------------------------------------------------------------
# native driver (C++ epoll engine + crafted datagrams over the C ABI)
# ---------------------------------------------------------------------------


def run_case_native(case: dict, *, period: float | None = None,
                    warmup_timeout: float = 120.0) -> dict:
    from gossipfs_tpu.campaigns.engines import native_period
    from gossipfs_tpu.native import NativeUdpDetector
    from gossipfs_tpu.obs.recorder import FlightRecorder

    validate(case)
    cfg = case["config"]
    n = case["n"]
    if period is None:
        period = native_period(n)
    base = _free_udp_base(n)
    det = NativeUdpDetector(
        n, base_port=base, period=period, t_fail=int(cfg["t_fail"]),
        t_cooldown=int(cfg["t_cooldown"]), min_group=int(cfg["min_group"]),
        fresh_cooldown=True, push=cfg["push"], fanout=int(cfg["fanout"]),
        remove_broadcast=bool(cfg["remove_broadcast"]),
        suspicion=suspicion_params(cfg),
    )
    inj = _Injector(base)
    try:
        det.seed_full_membership()
        deadline = time.monotonic() + warmup_timeout
        while not det.warm():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"native cluster (n={n}) did not warm within "
                    f"{warmup_timeout}s")
            time.sleep(period)
        rec = FlightRecorder(source="native-conformance", n=n,
                             case=case["family"])
        r0 = det.attach_recorder(rec)
        sc = case_scenario(case)
        if sc is not None:
            det.load_scenario(sc, round0=r0)
        steps = _steps_by_round(case)
        checkpoints: dict[int, dict[int, str]] = {}
        for r in range(case["rounds"]):
            for step in steps.get(r, ()):
                _native_step(det, inj, step)
            target = r0 + r + 1
            if det.round < target:
                det.advance(target - det.round)
            for cp in case["checkpoints"]:
                if cp["round"] == r:
                    membership = det.membership(0)
                    suspects = det.suspects(0)
                    checkpoints[r] = {
                        s: _classify(membership, suspects, s)
                        for s in case["tracked"]}
        membership = det.membership(0)
        suspects = det.suspects(0)
        final = {s: _classify(membership, suspects, s)
                 for s in case["tracked"]}
        incarnations = {}
        for s in case["tracked"]:
            hb = det.incarnation(0, s)
            if hb >= 0:
                incarnations[s] = hb
        # stop the loop BEFORE the drain's host-side parse (engines.py)
        det.stop()
        det.pump_obs()
        rec.close()
        return {
            "engine": "native",
            "events": _lifecycle_events(rec.events),
            "final": final,
            "checkpoints": checkpoints,
            "incarnations": incarnations,
            "counters": {},
        }
    finally:
        inj.close()
        det.close()


def _native_step(det, inj: _Injector, step: dict) -> None:
    op = step["op"]
    if op in ("crash", "leave", "join"):
        getattr(det, op)(step["node"])
        return
    alive = det.alive_nodes()
    about = step.get("about")
    about_addr = det.wire_addr(about) if about is not None else None
    for t in _targets(step, alive):
        if op == "verb":
            hb = STALE_HB if step.get("hb") == "stale" else None
            payload = wire_verb(step["verb"], about_addr, hb=hb)
        else:
            hb = None
            if step["style"] in _ADVANCE_STYLES:
                cur = det.incarnation(t, about)
                hb = max(cur, 0) + int(step["hb_boost"])
            payload = malformed_payload(step["style"],
                                        about_addr=about_addr, hb=hb)
        inj.send(t, payload, copies=int(step.get("copies", 1)))


#: the one driver table verdict.py / tools/conformance.py dispatch on
RUNNERS = {
    "reference": run_case_reference,
    "tensor": run_case_tensor,
    "udp": run_case_udp,
    "native": run_case_native,
}
